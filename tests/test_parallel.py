"""Parallelism-strategy correctness vs single-device oracles.

Mirrors the reference's test style (numerical oracle comparison, e.g.
test_adasum_pytorch.py compares against a NumPy implementation): every
sharded program must match the unsharded math bit-for-bit or to fp tolerance
on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import (
    MeshSpec, build_mesh, moe_ffn, pipeline_apply, ring_attention,
    ulysses_attention,
)
from horovod_tpu.parallel.ring_attention import blockwise_attention_reference
from horovod_tpu.models import transformer as tfm


def mesh_of(**sizes):
    return build_mesh(MeshSpec(**sizes), jax.devices()[:MeshSpec(**sizes).total])


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [True, False])
def test_ring_attention_matches_oracle(causal, use_flash):
    """Both ring paths: per-hop Pallas flash chunks with log-space merge,
    and the streaming jnp fallback."""
    B, H, S, dh, SP = 2, 4, 16, 8, 4
    key = jax.random.PRNGKey(0)
    q, k, v = [jax.random.normal(kk, (B, H, S, dh))
               for kk in jax.random.split(key, 3)]
    oracle = blockwise_attention_reference(q, k, v, causal=causal)

    m = mesh_of(sp=SP)
    spec = P(None, None, "sp", None)

    def f(q, k, v):
        return ring_attention(q, k, v, "sp", causal=causal,
                              use_flash=use_flash)

    out = jax.jit(jax.shard_map(
        f, mesh=m, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_flash", [True, False])
def test_ring_attention_grad_matches_oracle(use_flash):
    B, H, S, dh, SP = 1, 2, 8, 4, 4
    key = jax.random.PRNGKey(1)
    q, k, v = [jax.random.normal(kk, (B, H, S, dh))
               for kk in jax.random.split(key, 3)]

    def loss_oracle(qkv):
        return jnp.sum(blockwise_attention_reference(*qkv, causal=True) ** 2)

    go = jax.grad(loss_oracle)((q, k, v))

    m = mesh_of(sp=SP)
    spec = P(None, None, "sp", None)

    def local(qkv):
        # Local loss contribution only — no psum before grad: psum's
        # transpose would scale cotangents by the axis size. The ppermute
        # transposes route k/v cotangents back to their source ranks.
        out = ring_attention(*qkv, "sp", causal=True,
                             use_flash=use_flash)
        return jnp.sum(out ** 2)

    def loss_sharded(qkv):
        f = jax.shard_map(lambda t: jax.grad(local)(t), mesh=m,
                          in_specs=((spec,) * 3,), out_specs=(spec,) * 3,
                          check_vma=False)
        return f(qkv)

    gs = jax.jit(loss_sharded)((q, k, v))
    for a, b in zip(gs, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_matches_oracle():
    B, H, S, dh, SP = 2, 8, 16, 4, 4
    key = jax.random.PRNGKey(2)
    q, k, v = [jax.random.normal(kk, (B, H, S, dh))
               for kk in jax.random.split(key, 3)]
    oracle = blockwise_attention_reference(q, k, v, causal=True)
    m = mesh_of(sp=SP)
    spec = P(None, None, "sp", None)
    out = jax.jit(jax.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=True),
        mesh=m, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_matches_sequential():
    PP, L, M, mb, D = 4, 8, 4, 2, 16
    key = jax.random.PRNGKey(3)
    ws = jax.random.normal(key, (L, D, D)) / D ** 0.5
    x = jax.random.normal(jax.random.PRNGKey(4), (M, mb, D))

    def layer(a, w):
        return jnp.tanh(a @ w), None

    def seq_apply(xm):
        out, _ = lax.scan(layer, xm, ws)
        return out

    oracle = jax.vmap(seq_apply)(x)

    m = mesh_of(pp=PP)

    def stage_fn(stage_ws, act):
        out, _ = lax.scan(layer, act, stage_ws)
        return out

    def run(ws_sharded, xm):
        y = pipeline_apply(stage_fn, ws_sharded, xm, "pp")
        # emit zeros except on last stage; psum collapses to the real value
        return lax.psum(y, "pp")

    out = jax.jit(jax.shard_map(
        run, mesh=m, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=2e-5, atol=2e-5)


def test_moe_sharded_matches_single():
    EP, T, D, F, E = 4, 32, 8, 16, 8
    key = jax.random.PRNGKey(5)
    ks = jax.random.split(key, 4)
    x = jax.random.normal(ks[0], (T, D))
    router = jax.random.normal(ks[1], (D, E))
    w1 = jax.random.normal(ks[2], (E, D, F)) / D ** 0.5
    w2 = jax.random.normal(ks[3], (E, F, D)) / F ** 0.5

    # Oracle: dense top-1 MoE with no capacity drops.
    logits = x @ router
    probs = jax.nn.softmax(logits, -1)
    eidx = jnp.argmax(probs, -1)
    gate = jnp.max(probs, -1)
    h = jax.nn.gelu(jnp.einsum("td,edf->tef", x, w1))
    y_all = jnp.einsum("tef,efd->ted", h, w2)
    oracle = y_all[jnp.arange(T), eidx] * gate[:, None]

    m = mesh_of(ep=EP)
    out = jax.jit(jax.shard_map(
        lambda xx, r, a, b: moe_ffn(xx, r, a, b, "ep", capacity_factor=64.0),
        mesh=m,
        in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=P("ep"), check_vma=False))(x, router, w1, w2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Transformer flagship: sharded loss == single-device loss; step runs.
# ---------------------------------------------------------------------------

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, d_ff=64,
                            n_layers=4, max_seq=64, attn="ring")


def _data(cfg, B=8, S=16):
    k = jax.random.PRNGKey(7)
    tokens = jax.random.randint(k, (B, S), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


def _loss_single(cfg, params, tokens, targets):
    m1 = build_mesh(MeshSpec(), jax.devices()[:1])
    lg = tfm.build_loss_and_grads(cfg, m1)
    loss, grads = jax.jit(lg)(params, tokens, targets)
    return loss, grads


@pytest.mark.parametrize("spec", [
    dict(dp=2, tp=2, sp=2),
    dict(dp=2, sp=4),
    dict(dp=8),
    dict(tp=4, dp=2),
])
def test_transformer_loss_matches_single_device(spec):
    cfg = CFG
    params = tfm.init(jax.random.PRNGKey(0), cfg)
    tokens, targets = _data(cfg)
    loss1, grads1 = _loss_single(cfg, params, tokens, targets)

    m = mesh_of(**spec)
    tfm.validate_cfg_for_mesh(cfg, m)
    lg = tfm.build_loss_and_grads(cfg, m)
    loss, grads = jax.jit(lg)(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(loss1), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4),
        grads, grads1)


def test_transformer_pipeline_loss_matches():
    cfg = dataclasses_replace(CFG, microbatches=2)
    params = tfm.init(jax.random.PRNGKey(0), cfg)
    tokens, targets = _data(cfg)
    loss1, grads1 = _loss_single(
        dataclasses_replace(CFG, microbatches=1), params, tokens, targets)

    m = mesh_of(pp=2, dp=2, sp=2)
    lg = tfm.build_loss_and_grads(cfg, m)
    loss, grads = jax.jit(lg)(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(loss1), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4),
        grads, grads1)


def test_transformer_moe_train_step_runs():
    cfg = dataclasses_replace(CFG, num_experts=4, attn="ring")
    params = tfm.init(jax.random.PRNGKey(0), cfg)
    tokens, targets = _data(cfg)
    m = mesh_of(dp=2, ep=2, sp=2)
    tfm.validate_cfg_for_mesh(cfg, m)
    opt = optax.sgd(1e-2)
    params = tfm.shard_params(params, cfg, m)
    before = jax.tree_util.tree_map(np.asarray, params)  # step donates params
    step = tfm.build_train_step(cfg, m, opt)
    opt_state = opt.init(params)
    p2, _, loss = step(params, opt_state, tokens, targets)
    assert np.isfinite(float(loss))
    # Params actually moved.
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - b))), p2, before)
    assert max(jax.tree_util.tree_leaves(moved)) > 0


def dataclasses_replace(cfg, **kw):
    import dataclasses
    return dataclasses.replace(cfg, **kw)


def test_ring_attention_bf16_tolerance():
    """bf16 inputs through the flash-chunk ring: the merge accumulates in
    f32 (chunks are upcast), so error stays at bf16-input level — not
    P per-hop quantizations."""
    B, H, S, dh, SP = 1, 2, 16, 8, 4
    key = jax.random.PRNGKey(3)
    q, k, v = [jax.random.normal(kk, (B, H, S, dh), jnp.bfloat16)
               for kk in jax.random.split(key, 3)]
    oracle = blockwise_attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True)

    m = mesh_of(sp=SP)
    spec = P(None, None, "sp", None)
    out = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True),
        mesh=m, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(oracle), rtol=2e-2, atol=2e-2)
