"""Control-plane authentication (reference: runner/common/util/secret.py —
HMAC-signed service RPC; previously the KV accepted writes from anyone)."""

import urllib.error

import pytest

from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer
from horovod_tpu.runner.secret import (compute_digest, check_digest,
                                       make_secret_key)


def test_digest_roundtrip():
    secret = make_secret_key().encode()
    d = compute_digest(secret, "PUT", "/s/k", b"value")
    assert check_digest(secret, "PUT", "/s/k", b"value", d)
    assert not check_digest(secret, "PUT", "/s/k", b"othervalue", d)
    assert not check_digest(secret, "GET", "/s/k", b"value", d)
    assert not check_digest(b"other-secret", "PUT", "/s/k", b"value", d)
    assert not check_digest(secret, "PUT", "/s/k", b"value", None)


def test_rendezvous_rejects_unsigned_requests():
    secret = make_secret_key()
    srv = RendezvousServer(secret=secret.encode())
    port = srv.start()
    try:
        good = KVClient("127.0.0.1", port, secret=secret.encode())
        good.put("scope", "k", b"v1")
        assert good.get("scope", "k") == b"v1"

        anon = KVClient("127.0.0.1", port, secret=None)
        with pytest.raises(urllib.error.HTTPError) as ei:
            anon.put("scope", "k", b"poison")
        assert ei.value.code == 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            anon.get("scope", "k", timeout=1.0)
        assert ei.value.code == 403

        bad = KVClient("127.0.0.1", port, secret=b"wrong-key")
        with pytest.raises(urllib.error.HTTPError) as ei:
            bad.put("scope", "k", b"poison")
        assert ei.value.code == 403
        # The value was never overwritten by unauthorized writers.
        assert good.get("scope", "k") == b"v1"
    finally:
        srv.stop()


def test_rendezvous_open_without_secret():
    srv = RendezvousServer()
    port = srv.start()
    try:
        c = KVClient("127.0.0.1", port, secret=None)
        c.put("s", "k", b"x")
        assert c.get("s", "k") == b"x"
    finally:
        srv.stop()
