"""Metrics registry + export-path unit tests (ISSUE 2 tentpole).

Covers: counter/gauge/histogram semantics, label cardinality cap,
disabled-mode no-ops, thread-safety under a hammer, Prometheus rendering
and multi-rank merge, the exporter sinks (JSON dump, KV push, timeline
counter tracks), collective-layer instrumentation through a real run,
and the rendezvous server's /metrics route.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from horovod_tpu.observability import metrics as m


def reg():
    return m.MetricsRegistry(enabled=True, label_max=64)


# ------------------------------------------------------------- semantics

def test_counter_semantics():
    r = reg()
    c = r.counter("c_total", "help", labelnames=("op",))
    c.labels(op="a").inc()
    c.labels(op="a").inc(2.5)
    c.labels(op="b").inc()
    assert c.labels(op="a").value == 3.5
    assert c.labels(op="b").value == 1.0


def test_counter_default_series_without_labels():
    r = reg()
    c = r.counter("plain_total")
    c.inc()
    c.inc(4)
    assert c.value == 5.0


def test_gauge_set_and_dec():
    r = reg()
    g = r.gauge("g")
    g.set(10)
    g.dec(3)
    g.inc(0.5)
    assert g.value == 7.5


def test_histogram_buckets_and_sum():
    r = reg()
    h = r.histogram("h_seconds", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 100.0):
        h.observe(v)
    s = h.labels()
    assert s.count == 4
    assert s.sum == 105.0
    # counts per (le 1, le 2, le 4, +Inf) — non-cumulative internally
    assert s.counts == [1, 1, 1, 1]


def test_family_reregistration_conflict():
    r = reg()
    r.counter("x_total", labelnames=("a",))
    with pytest.raises(ValueError):
        r.gauge("x_total", labelnames=("a",))
    with pytest.raises(ValueError):
        r.counter("x_total", labelnames=("b",))


def test_label_cardinality_cap_folds_to_other():
    r = m.MetricsRegistry(enabled=True, label_max=4)
    c = r.counter("capped_total", labelnames=("k",))
    for i in range(50):
        c.labels(k=f"key{i}").inc()
    fam = r.snapshot()["families"]["capped_total"]
    series = {tuple(s["labels"]): s["value"] for s in fam["series"]}
    assert len(series) <= 5  # 4 real + the fold bucket
    assert series[("other",)] == 46.0  # keys 4..49 folded, none lost


# ---------------------------------------------------------- disabled mode

def test_disabled_registry_is_noop():
    r = m.MetricsRegistry(enabled=False)
    c = r.counter("c_total", labelnames=("op",))
    assert c is m.NOOP
    c.labels(op="a").inc()
    c.observe(1)  # histogram surface too — never raises
    c.set(2)
    assert r.snapshot()["families"] == {}
    assert m.render_snapshots([r.snapshot()]) == ""


def test_env_disable(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "0")
    m.reset_for_tests()
    try:
        assert not m.registry().enabled
        assert m.registry().counter("x_total") is m.NOOP
    finally:
        monkeypatch.setenv("HOROVOD_METRICS", "1")
        m.reset_for_tests()


# ----------------------------------------------------------- thread hammer

def test_thread_hammer_counter_and_histogram():
    r = reg()
    c = r.counter("hammer_total", labelnames=("t",))
    h = r.histogram("hammer_seconds", buckets=m.TIME_BUCKETS)
    n_threads, n_iter = 8, 2000

    def work(tid):
        child = c.labels(t=str(tid % 2))
        for i in range(n_iter):
            child.inc()
            h.observe(1e-6 * (i % 7 + 1))

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(s["value"] for s in
                r.snapshot()["families"]["hammer_total"]["series"])
    assert total == n_threads * n_iter
    hs = h.labels()
    assert hs.count == n_threads * n_iter
    assert sum(hs.counts) == hs.count


# ------------------------------------------------------------- rendering

def test_render_merges_ranks_with_rank_label():
    r0, r1 = reg(), reg()
    for rank, r in enumerate((r0, r1)):
        r.counter("calls_total", "calls", ("op",)).labels(
            op="allreduce").inc(rank + 1)
    text = m.render_snapshots([r0.snapshot(rank=0), r1.snapshot(rank=1)])
    assert 'calls_total{op="allreduce",rank="0"} 1' in text
    assert 'calls_total{op="allreduce",rank="1"} 2' in text
    assert text.count("# TYPE calls_total counter") == 1


def test_render_histogram_cumulative_buckets():
    r = reg()
    h = r.histogram("lat_seconds", buckets=(1.0, 2.0))
    for v in (0.5, 0.6, 1.5, 9.0):
        h.observe(v)
    text = m.render_snapshots([r.snapshot()])
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="2"} 3' in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    assert "lat_seconds_sum 11.6" in text
    assert "lat_seconds_count 4" in text


def test_parse_snapshot_rejects_garbage():
    assert m.parse_snapshot(b"\xff\x00 not json") is None
    assert m.parse_snapshot(b"[1,2,3]") is None
    assert m.parse_snapshot(b'{"families": {}}') == {"families": {}}


# ------------------------------------------------------- exporter sinks

def _mk_cfg(**kw):
    from horovod_tpu.common.config import Config
    return Config(**kw)


def test_exporter_json_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "1")
    m.reset_for_tests()
    m.registry().counter("dumped_total").inc(7)
    from horovod_tpu.observability.export import MetricsExporter
    path = tmp_path / "metrics-{rank}.json"
    cfg = _mk_cfg(metrics_dump=str(path), metrics_dump_interval=0.1,
                  metrics_push_interval=0.1)
    exp = MetricsExporter(cfg, rank_fn=lambda: 3, timeline_fn=lambda: None)
    exp.tick(force=True)
    snap = json.loads((tmp_path / "metrics-3.json").read_text())
    assert snap["rank"] == 3
    assert snap["families"]["dumped_total"]["series"][0]["value"] == 7
    m.reset_for_tests()


def test_exporter_kv_push(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "1")
    m.reset_for_tests()
    m.registry().counter("pushed_total").inc()
    pushed = {}

    class FakeKV:
        def put(self, scope, key, value):
            pushed[(scope, key)] = value

    from horovod_tpu.observability.export import MetricsExporter
    cfg = _mk_cfg(metrics_push_interval=0.1)
    exp = MetricsExporter(cfg, rank_fn=lambda: 1, timeline_fn=lambda: None,
                          kv_factory=FakeKV)
    exp.tick(force=True)
    snap = json.loads(pushed[("metrics", "rank-1")])
    assert "pushed_total" in snap["families"]
    m.reset_for_tests()


def test_exporter_timeline_counter_tracks(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "1")
    m.reset_for_tests()
    m.registry().counter("tracked_total", labelnames=("op",)).labels(
        op="x").inc(5)
    emitted = []

    class FakeTL:
        def counter(self, name, values):
            emitted.append((name, values))

    from horovod_tpu.observability.export import MetricsExporter
    cfg = _mk_cfg(metrics_push_interval=0.1)
    exp = MetricsExporter(cfg, rank_fn=lambda: 0,
                          timeline_fn=lambda: FakeTL())
    exp.tick(force=True)
    assert ("tracked_total", {"x": 5.0}) in emitted
    m.reset_for_tests()


# -------------------------------------- instrumentation through a real run

def test_collectives_record_metrics(hvd):
    m.reset_for_tests()
    try:
        hvd.allreduce(np.ones((16,), np.float32), op="sum")
        hvd.allreduce(np.ones((16,), np.float32), op="sum")
        hvd.grouped_allreduce(
            [np.ones((4,), np.float32), np.ones((2, 2), np.float64)],
            op="sum")
        snap = hvd.metrics()
        fams = snap["families"]
        calls = {tuple(s["labels"]): s["value"]
                 for s in fams["horovod_collective_calls_total"]["series"]}
        assert calls[("allreduce", "float32")] >= 3
        total_bytes = sum(
            s["value"]
            for s in fams["horovod_collective_bytes_total"]["series"])
        # 8-device mesh (conftest): 2x 16 f32 + group of (4 f32, 4 f64)
        assert total_bytes == 8 * (2 * 64 + 16 + 32)
        cache = {tuple(s["labels"]): s["value"]
                 for s in fams["horovod_compile_cache_total"]["series"]}
        # second allreduce reuses the first's executable
        assert cache[("hit",)] >= 1 and cache[("miss",)] >= 2
        lat = fams["horovod_collective_seconds"]["series"]
        assert sum(s["count"] for s in lat) >= 3
        grp = fams["horovod_grouped_fusion_tensors"]["series"]
        assert sum(s["count"] for s in grp) == 1
        text = hvd.metrics_text()
        assert "horovod_collective_bytes_total" in text
    finally:
        m.reset_for_tests()


def test_disabled_mode_skips_collective_metrics(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "0")
    m.reset_for_tests()
    import horovod_tpu as hvd
    hvd.shutdown()
    hvd.init()
    try:
        hvd.allreduce(np.ones((4,), np.float32), op="sum")
        assert hvd.metrics()["families"] == {}
        assert hvd.metrics_text() == ""
    finally:
        hvd.shutdown()
        monkeypatch.setenv("HOROVOD_METRICS", "1")
        m.reset_for_tests()


# ----------------------------------------------------- /metrics route

def test_rendezvous_metrics_route():
    from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer
    m.reset_for_tests()
    srv = RendezvousServer()
    port = srv.start()
    try:
        kv = KVClient("127.0.0.1", port)
        kv.put("scope", "key", b"v")
        worker = m.MetricsRegistry(enabled=True)
        worker.counter("horovod_collective_calls_total", "",
                       ("op", "dtype")).labels(
                           op="allreduce", dtype="float32").inc(9)
        kv.put("metrics", "rank-1",
               json.dumps(worker.snapshot(rank=1)).encode())
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        # launcher-side KV metrics and the pushed worker snapshot merge
        assert 'horovod_kv_requests_total{method="PUT"}' in text
        assert "horovod_kv_request_seconds_bucket" in text
        assert ('horovod_collective_calls_total'
                '{op="allreduce",dtype="float32",rank="1"} 9') in text
        # retry counters render as explicit zeros on a healthy server
        assert 'horovod_retry_attempts_total{policy="kv"}' in text
    finally:
        srv.stop()
        m.reset_for_tests()


def test_fresh_snapshots_fake_clock():
    """Aging is a pure function of the snapshot `time` stamps and an
    injectable now — dead ranks age out, live ranks and stamp-less
    snapshots (fail open) stay."""
    snaps = [{"rank": 0, "time": 100.0},
             {"rank": 1, "time": 50.0},     # stale
             {"rank": 2}]                   # no stamp: kept
    kept = m.fresh_snapshots(snaps, stale_seconds=30.0, now=110.0)
    assert [s.get("rank") for s in kept] == [0, 2]
    # 0 disables aging entirely
    assert len(m.fresh_snapshots(snaps, stale_seconds=0.0,
                                 now=110.0)) == 3


def test_stale_cutoff_defaults_to_push_interval_multiple(monkeypatch):
    monkeypatch.delenv("HOROVOD_METRICS_STALE_SECONDS", raising=False)
    monkeypatch.setenv("HOROVOD_METRICS_PUSH_INTERVAL", "2.0")
    assert m.stale_cutoff_seconds() == pytest.approx(6.0)
    monkeypatch.setenv("HOROVOD_METRICS_STALE_SECONDS", "42")
    assert m.stale_cutoff_seconds() == 42.0
    monkeypatch.setenv("HOROVOD_METRICS_STALE_SECONDS", "0")
    assert m.stale_cutoff_seconds() == 0.0


def test_metrics_route_ages_out_dead_rank_snapshots(monkeypatch):
    """The ISSUE 11 regression: a rank evicted (or SIGKILL'd) mid-job
    kept rendering its last snapshot in the job-wide merge forever.
    Snapshots whose SERVER-side arrival stamp is older than
    HOROVOD_METRICS_STALE_SECONDS must drop out of the scrape; fresh
    ones stay — and a skewed WORKER clock in the snapshot body must not
    matter (the server stamps arrival itself)."""
    import time as _time
    from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer
    m.reset_for_tests()
    monkeypatch.setenv("HOROVOD_METRICS_STALE_SECONDS", "30")
    srv = RendezvousServer()
    port = srv.start()
    try:
        kv = KVClient("127.0.0.1", port)
        worker = m.MetricsRegistry(enabled=True)
        worker.counter("horovod_x_total").inc(5)
        fresh = worker.snapshot(rank=0)
        # A live rank whose host clock is badly skewed: its own stamp
        # claims 1000s ago, but the push just ARRIVED — it must render.
        fresh["time"] = _time.time() - 1000.0
        dead = worker.snapshot(rank=1)
        kv.put("metrics", "rank-0", json.dumps(fresh).encode())
        kv.put("metrics", "rank-1", json.dumps(dead).encode())
        # Fake clock on the server stamp: rank 1's last arrival was
        # long ago (the rank died and stopped refreshing).
        with srv._handler.lock:
            srv._handler.put_times["metrics/rank-1"] = \
                _time.time() - 1000.0
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert 'horovod_x_total{rank="0"} 5' in text
        assert 'rank="1"' not in text
    finally:
        srv.stop()
        m.reset_for_tests()


def test_metrics_route_survives_garbage_snapshot():
    from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer
    m.reset_for_tests()
    srv = RendezvousServer()
    port = srv.start()
    try:
        KVClient("127.0.0.1", port).put("metrics", "rank-0",
                                        b"\xde\xad not json")
        status = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).status
        assert status == 200
    finally:
        srv.stop()
        m.reset_for_tests()


# -------------------------------------------------------------- resilience

def test_retry_metrics_counted(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "1")
    m.reset_for_tests()
    from horovod_tpu.common.exceptions import RetryError
    from horovod_tpu.common.resilience import RetryPolicy
    pol = RetryPolicy(max_attempts=3, base_delay=0.001, max_delay=0.001,
                      deadline=None, retryable=lambda e: True,
                      name="testpol")
    with pytest.raises(RetryError):
        pol.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))
    snap = m.registry().snapshot()
    retries = {tuple(s["labels"]): s["value"] for s in
               snap["families"]["horovod_retry_attempts_total"]["series"]}
    exhausted = {tuple(s["labels"]): s["value"] for s in
                 snap["families"]["horovod_retry_exhausted_total"]["series"]}
    assert retries[("testpol",)] == 2  # 3 attempts = 2 retries
    assert exhausted[("testpol",)] == 1
    m.reset_for_tests()


def test_circuit_breaker_transition_metrics():
    m.reset_for_tests()
    from horovod_tpu.common.exceptions import CircuitOpenError
    from horovod_tpu.common.resilience import CircuitBreaker
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=2, recovery_timeout=10.0,
                        clock=lambda: clock[0])
    for _ in range(2):
        with pytest.raises(ValueError):
            br.call(lambda: (_ for _ in ()).throw(ValueError("y")))
    with pytest.raises(CircuitOpenError):
        br.call(lambda: 1)
    clock[0] = 11.0  # half-open: probe succeeds, circuit closes
    assert br.call(lambda: 42) == 42
    snap = m.registry().snapshot()
    trans = {tuple(s["labels"]): s["value"] for s in
             snap["families"]["horovod_circuit_transitions_total"]["series"]}
    assert trans[("open",)] == 1
    assert trans[("closed",)] == 1
    m.reset_for_tests()
