"""Sharded-checkpoint writer subprocess for test_kv_ha (ISSUE 16
satellite): one REAL writer process of a writers=2 multi-writer save
(ckpt/async_ckpt.py, PR 14). It persists its half of the leaf through
`_persist` — shard files + fragment publish for the peer rank, fragment
collection + merged-manifest commit for the primary — with the ckpt KV
client built from the job env (`kv_from_env`), which is a multi-endpoint
HA client whenever HOROVOD_RENDEZVOUS_ADDRS is set. The harness points
this process's ADDR/PORT at a replica it already killed, so every KV op
here lands only by failing over to the promoted primary.
"""

import argparse
import sys

import numpy as np

from horovod_tpu.ckpt import async_ckpt
from horovod_tpu.ckpt import manifest as mf
from horovod_tpu.ckpt import sharded


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--root", required=True)
    ap.add_argument("--step", type=int, required=True)
    ap.add_argument("--gen", type=int, required=True)
    ap.add_argument("--val", type=float, required=True)
    a = ap.parse_args(argv)
    # this process IS rank a.rank of the 2-writer job
    async_ckpt.AsyncCheckpointer._rank = staticmethod(lambda: a.rank)
    s = async_ckpt.AsyncCheckpointer(a.root, writers=2)
    lo, hi = (0, 4) if a.rank == 0 else (4, 8)
    snaps = [sharded.LeafSnapshot(
        mf.LeafEntry(path="['w']", shape=(8,), dtype="float32",
                     spec=[["tp"]]),
        [((lo,), (hi,), np.full((hi - lo,), a.val, np.float32))])]
    s._persist(async_ckpt._Job(a.step, a.gen, snaps, 16, {}, 0.0))
    if a.rank == 0 and mf.latest_committed(a.root) != (a.gen, a.step):
        print(f"WRITER_FAIL rank=0 step={a.step} "
              f"last_error={s.last_error}", flush=True)
        return 1
    print(f"WRITER_DONE rank={a.rank} step={a.step} "
          f"failovers={getattr(s._kv_client(), 'failovers', 0)}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
