"""Pallas flash-attention kernel numerics (forward AND gradients) against
the exact score-materializing oracle. Runs in interpret mode on the CPU
mesh; the identical kernel compiles on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel.ring_attention import (
    blockwise_attention_reference)


def _qkv(key, B=2, H=2, S=256, dh=64, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (B, H, S, dh), dtype)  # noqa: E731
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    want = blockwise_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v = _qkv(jax.random.PRNGKey(1), S=256)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = blockwise_attention_reference(q, k, v, causal=True)
        return jnp.sum(jnp.sin(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_small_seq_full_block():
    """S <= 1024 takes the kernel with block == S (always-legal tiling)."""
    q, k, v = _qkv(jax.random.PRNGKey(2), S=100)
    got = flash_attention(q, k, v, causal=True)
    want = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_untileable_seq_falls_back():
    """S > 1024 with no 128-multiple divisor actually exercises the
    reference fallback branch (S=1100: _auto_block returns None)."""
    from horovod_tpu.ops.flash_attention import _auto_block, can_tile
    assert _auto_block(1100) is None
    assert not can_tile(1100)
    q, k, v = _qkv(jax.random.PRNGKey(4), S=1100, B=1, H=2)
    got = flash_attention(q, k, v, causal=True)
    want = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_smaller_blocks():
    q, k, v = _qkv(jax.random.PRNGKey(3), S=256)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    want = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
