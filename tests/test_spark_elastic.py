"""Elastic-on-agents: the Spark elastic protocol without Spark.

The agent protocol (spark/elastic.py) is Spark-agnostic — agents only
need a KV client — so these tests run agents in THREADS placing REAL
worker subprocesses over loopback, driving the same
ElasticDriver/RoundPublisher/drive_elastic_loop path the CLI uses
(reference analog: test/integration/test_elastic_spark.py runs elastic
jobs on a local pyspark session).
"""

import json
import threading
import time

import numpy as np
import pytest

from horovod_tpu.runner import secret as secret_mod
from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer
from horovod_tpu.spark import elastic as spe


def _thread_agent_runner(ip, port, key):
    """Agents as daemon threads (what Spark tasks would do)."""
    stops = []

    def runner(n_agents, max_agents):
        ts = []
        for i in range(n_agents):
            ev = threading.Event()
            stops.append(ev)
            t = threading.Thread(
                target=spe.agent_main,
                args=(KVClient(ip, port, secret=key.encode()), i),
                kwargs={"stop_event": ev, "poll_interval": 0.1},
                daemon=True)
            t.start()
            ts.append(t)

        class _Job:
            def join(self, timeout=None):
                for ev in stops:
                    ev.set()
                for t in ts:
                    t.join(timeout=timeout)
        return _Job()

    return runner, stops


def _make_train_fn():
    # Defined as a closure so cloudpickle serializes it BY VALUE — worker
    # subprocesses cannot import the test module.
    def train_fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        s = int(np.asarray(hvd.allreduce(
            np.asarray(hvd.rank() + 1, np.int32), op="sum")))
        out = (hvd.rank(), hvd.size(), s)
        hvd.shutdown()
        return out
    return train_fn


def test_kv_agent_discovery_and_handle():
    key = secret_mod.make_secret_key()
    rdv = RendezvousServer(secret=key.encode())
    port = rdv.start()
    try:
        kv = KVClient("127.0.0.1", port, secret=key.encode())
        kv.put(spe._SCOPE, "agent/0",
               json.dumps({"host": "agent0", "ts": 1}).encode())
        kv.put(spe._SCOPE, "agent/1",
               json.dumps({"host": "agent1", "ts": 2}).encode())
        disc = spe.KVAgentDiscovery(kv, max_agents=4)
        # staleness is judged on the DRIVER clock by heartbeat-value
        # change (executor clocks may be skewed): both look alive at
        # first sight...
        assert disc.find_available_hosts_and_slots() == \
            {"agent0": 1, "agent1": 1}
        # ...then only the agent whose heartbeat keeps changing survives
        # a >15s quiet period
        kv.put(spe._SCOPE, "agent/0",
               json.dumps({"host": "agent0", "ts": 3}).encode())
        real_mono = time.monotonic()
        import itertools
        import unittest.mock as mock
        ctr = itertools.count()
        # keep the fake clock ADVANCING — a constant would deadlock
        # KVClient.get's 404-retry deadline, which shares the time module
        with mock.patch.object(spe.time, "monotonic",
                               lambda: real_mono + 16 + next(ctr) * 0.01):
            assert disc.find_available_hosts_and_slots() == {"agent0": 1}

        h = spe._AgentHandle(kv, 1, "agent0")
        assert h.poll() is None
        kv.put(spe._SCOPE, "status/1/agent0/0", b"0")
        assert h.poll() == 0
        h2 = spe._AgentHandle(kv, 2, "agent1")
        h2.terminate()
        assert kv.get(spe._SCOPE, "kill/agent1", timeout=0) == b"1"
        assert h2.poll() == 143
    finally:
        rdv.stop()


def test_spark_elastic_happy_path(monkeypatch):
    """2 agents, 2 worker subprocesses, one real ring: every rank's
    allreduce sum must be 1+2=3."""
    from horovod_tpu.runner.launch import _local_ip

    # run_elastic creates its own rdv+secret; intercept the agent runner
    results_holder = {}

    def agent_runner_factory(n_agents, max_agents):
        # resolve ip/port/secret lazily from the env run_elastic built?
        raise AssertionError("replaced below")

    # We need the runner to know the rdv address that run_elastic creates.
    # Patch RendezvousServer.start to capture the instance.
    captured = {}
    orig_start = RendezvousServer.start

    def capturing_start(self):
        port = orig_start(self)
        captured["port"] = port
        captured["secret"] = self._secret if hasattr(self, "_secret") \
            else None
        return port

    monkeypatch.setattr(RendezvousServer, "start", capturing_start)

    def agent_runner(n_agents, max_agents):
        ip = _local_ip()
        key = captured["key"]
        runner, _stops = _thread_agent_runner(ip, captured["port"], key)
        return runner(n_agents, max_agents)

    # secret: run_elastic generates it; capture via make_secret_key
    orig_make = secret_mod.make_secret_key

    def capturing_make():
        k = orig_make()
        captured["key"] = k
        return k

    monkeypatch.setattr(secret_mod, "make_secret_key", capturing_make)

    out = spe.run_elastic(_make_train_fn(), num_proc=2, min_num_proc=2,
                          start_timeout=30, elastic_timeout=60,
                          _agent_runner=agent_runner)
    assert len(out) == 2
    ranks = sorted(r[0] for r in out if r)
    assert ranks == [0, 1]
    for r in out:
        assert r[1] == 2 and r[2] == 3, out


def test_spark_elastic_runs_with_fewer_agents(monkeypatch):
    """Only 1 of 2 requested agents registers: the job proceeds at
    min_num_proc=1 instead of waiting forever."""
    from horovod_tpu.runner.launch import _local_ip

    captured = {}
    orig_start = RendezvousServer.start

    def capturing_start(self):
        port = orig_start(self)
        captured["port"] = port
        return port

    monkeypatch.setattr(RendezvousServer, "start", capturing_start)
    orig_make = secret_mod.make_secret_key

    def capturing_make():
        k = orig_make()
        captured["key"] = k
        return k

    monkeypatch.setattr(secret_mod, "make_secret_key", capturing_make)

    def agent_runner(n_agents, max_agents):
        runner, _ = _thread_agent_runner(
            _local_ip(), captured["port"], captured["key"])
        return runner(1, max_agents)  # one agent shows up

    out = spe.run_elastic(_make_train_fn(), num_proc=2, min_num_proc=1,
                          start_timeout=30, elastic_timeout=60,
                          _agent_runner=agent_runner)
    assert len(out) == 1
    assert out[0][1] == 1  # world size 1


def test_spark_elastic_no_agents_times_out(monkeypatch):
    with pytest.raises(TimeoutError, match="agent registered"):
        spe.run_elastic(_make_train_fn(), num_proc=1, start_timeout=1.0,
                        _agent_runner=lambda n, m: None)


def test_newer_launch_record_replaces_live_worker(tmp_path):
    """ADVICE r2: if the kill command for a replaced worker is swallowed
    (spawn()'s stale-key cleanup races the agent's consumption), the
    NEWER launch record itself must terminate the old process — a live
    worker with a newer launch is a replacement, not a survivor."""
    import cloudpickle

    key = secret_mod.make_secret_key()
    rdv = RendezvousServer(secret=key.encode())
    port = rdv.start()
    stop = threading.Event()
    try:
        kv = KVClient("127.0.0.1", port, secret=key.encode())

        marker_dir = str(tmp_path)

        def sleeper():
            import os
            import time as _t
            rnd = os.environ.get("HOROVOD_ELASTIC_ROUND", "?")
            open(os.path.join(os.environ["MARKER_DIR"],
                              f"pid_{rnd}_{os.getpid()}"), "w").close()
            _t.sleep(120)
            return None

        kv.put(spe._SCOPE, "fn", cloudpickle.dumps(sleeper))

        t = threading.Thread(
            target=spe.agent_main,
            args=(KVClient("127.0.0.1", port, secret=key.encode()), 0),
            kwargs={"stop_event": stop, "poll_interval": 0.05},
            daemon=True)
        t.start()
        # the agent heartbeats its hostname; round records are host-keyed
        deadline = time.monotonic() + 10
        host = None
        while time.monotonic() < deadline and host is None:
            raw = kv.get(spe._SCOPE, "agent/0", timeout=0)
            if raw:
                host = json.loads(raw)["host"]
            time.sleep(0.05)
        assert host, "agent never heartbeat"

        def launch(round_id):
            kv.put(spe._SCOPE, f"launch/{round_id}/{host}",
                   json.dumps({
                       "round": round_id, "rank": 0,
                       "env": {"HOROVOD_ELASTIC_ROUND": str(round_id),
                               "MARKER_DIR": marker_dir}}).encode())
            kv.put(spe._SCOPE, "round_hint", str(round_id).encode())

        import os

        def pids(rnd):
            return [int(f.split("_")[-1]) for f in os.listdir(marker_dir)
                    if f.startswith(f"pid_{rnd}_")]

        launch(1)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not pids(1):
            time.sleep(0.1)
        assert pids(1), "round-1 worker never started"
        (old_pid,) = pids(1)

        # NO kill key (simulating the swallowed kill) — just a newer
        # launch record. The agent must terminate the old worker and
        # start the new one.
        launch(2)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not pids(2):
            time.sleep(0.1)
        assert pids(2), "round-2 worker never started"

        def alive(pid):
            try:
                os.kill(pid, 0)
                return True
            except ProcessLookupError:
                return False

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and alive(old_pid):
            time.sleep(0.1)
        assert not alive(old_pid), "replaced worker still running"
        kv.put(spe._SCOPE, "stopall", b"1")
    finally:
        stop.set()
        rdv.stop()
