"""Fused conv+BN+ReLU block (ops/conv_block.py) vs the jax.lax
reference.

The kernels run in interpret mode on the CPU mesh (same fallback as
flash_attention / conv_bn_backward), so these tests exercise the real
pallas_call path: the fused forward (stats ride the matmul pass) and
the fused masked backward are checked against `conv_block_reference` —
the ground truth XLA would compute unfused — and against jax.grad of
the identical math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.conv_block import (conv1x1_bn_act,
                                        conv1x1_bn_act_nhwc,
                                        conv1x1_bn_relu,
                                        conv1x1_fwd_fused,
                                        conv_block_reference)


def _mk(m, cin, c, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    return (jax.random.normal(ks[0], (m, cin), dtype),
            jax.random.normal(ks[1], (cin, c), dtype) * 0.1,
            jax.random.normal(ks[2], (c,), dtype) * 0.5 + 1.0,
            jax.random.normal(ks[3], (c,), dtype) * 0.1)


def _close(a, b, tol):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    assert np.max(np.abs(a - b)) <= tol * (np.max(np.abs(a)) + 1e-9), \
        (np.max(np.abs(a - b)), np.max(np.abs(a)))


def test_fwd_kernel_matmul_and_stat_sums():
    """The fused forward's three outputs: y bit-matches the matmul, and
    the resident-accumulator stat rows match the full reductions —
    including with row padding (M=250 is not a sublane multiple)."""
    x, w, _, _ = _mk(250, 16, 64)
    y, ssum, ssq = conv1x1_fwd_fused(x, w)
    yr = x @ w
    _close(y, yr, 1e-6)
    _close(ssum, yr.sum(0), 1e-5)
    _close(ssq, (yr ** 2).sum(0), 1e-5)


@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("m,cin,c", [(256, 32, 48), (250, 16, 64)])
def test_forward_matches_reference(m, cin, c, relu):
    x, w, scale, bias = _mk(m, cin, c)
    z_ref, (m_ref, v_ref) = conv_block_reference(x, w, scale, bias,
                                                 1e-5, None, relu)
    z, (mean, var) = conv1x1_bn_act(x, w, scale, bias, 1e-5, None, relu)
    _close(z_ref, z, 1e-5)
    _close(m_ref, mean, 1e-5)
    _close(v_ref, var, 1e-5)


@pytest.mark.parametrize("relu", [True, False])
def test_grads_match_autodiff(relu):
    """All four gradients (x, w, scale, bias) of the fused block match
    jax.grad of the reference — the ReLU mask folded into the kernel
    included."""
    x, w, scale, bias = _mk(256, 32, 48, seed=1)

    def loss_f(f):
        return lambda *a: jnp.sum(jnp.sin(f(*a, 1e-5, None, relu)[0]))

    gr = jax.grad(loss_f(conv_block_reference),
                  argnums=(0, 1, 2, 3))(x, w, scale, bias)
    gf = jax.grad(loss_f(conv1x1_bn_act),
                  argnums=(0, 1, 2, 3))(x, w, scale, bias)
    for a, b in zip(gr, gf):
        _close(a, b, 1e-5)


def test_stats_cotangents_are_exact():
    """A loss that differentiates the returned batch stats (the aux
    outputs) still gets exact gradients — the dmean/dvar cotangents
    fold into the kernel's per-channel vectors."""
    x, w, scale, bias = _mk(96, 8, 16, seed=3)

    def loss_f(f):
        def L(*a):
            z, (mean, var) = f(*a)
            return (jnp.sum(jnp.sin(z)) + 0.3 * jnp.sum(jnp.cos(mean))
                    + 0.1 * jnp.sum(var ** 2))
        return L

    gr = jax.grad(loss_f(conv_block_reference),
                  argnums=(0, 1, 2, 3))(x, w, scale, bias)
    gf = jax.grad(loss_f(conv1x1_bn_relu),
                  argnums=(0, 1, 2, 3))(x, w, scale, bias)
    for a, b in zip(gr, gf):
        _close(a, b, 1e-5)


def test_bf16_path():
    """bf16 in / f32 accumulation: gradients match the reference within
    bf16 tolerance (the ISSUE 12 acceptance bar)."""
    x, w, scale, bias = _mk(256, 32, 48, dtype=jnp.bfloat16)
    scale, bias = scale.astype(jnp.float32), bias.astype(jnp.float32)

    def loss_f(f):
        return lambda *a: jnp.sum(jnp.sin(f(*a)[0].astype(jnp.float32)))

    gr = jax.grad(loss_f(conv_block_reference), argnums=(0, 1))(
        x, w, scale, bias)
    gf = jax.grad(loss_f(conv1x1_bn_relu), argnums=(0, 1))(
        x, w, scale, bias)
    for a, b in zip(gr, gf):
        _close(a.astype(jnp.float32), b.astype(jnp.float32), 2e-2)


def test_bf16_boundary_mask_matches_forward():
    """The ReLU-boundary contract with a bf16 model: the backward mask
    must make the SAME sign decisions as the forward. The fused op's
    epilogue is deliberately all-f32 with final-rounding-only (see
    conv_block_reference) precisely so those decisions are
    reproducible; this test CONSTRUCTS exact boundaries — per channel,
    bias is the exact f32 negation of one row's pre-activation
    product, so the forward zpre is exactly 0 there (ReLU-dead, true
    gradient 0) — and demands tight gradient agreement, which a single
    mask flip (an O(1) elementwise error) breaks."""
    ks = jax.random.split(jax.random.PRNGKey(2), 2)
    m, cin, c = 64, 8, 16
    x = jax.random.normal(ks[0], (m, cin), jnp.bfloat16)
    w = jax.random.normal(ks[1], (cin, c), jnp.bfloat16) * 0.1
    scale = jnp.full((c,), 1.015625, jnp.bfloat16)
    # Reproduce the forward chain to place the boundaries.
    y = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32
                            ).astype(jnp.bfloat16)
    yf = y.astype(jnp.float32)
    mean = jnp.mean(yf, axis=0)
    var = jnp.mean(yf ** 2, axis=0) - mean ** 2
    inv = jax.lax.rsqrt(var + 1e-5)
    prod = np.asarray((yf - mean) * inv
                      * scale.astype(jnp.float32), np.float32)
    # zpre == ±1e-5 at one row per channel: a margin far ABOVE any
    # FMA-contraction residue (XLA may fuse the f32 mul+add, so exact-
    # zero cancellation points are not reproducible — measure-zero in
    # training) and far BELOW bf16 rounding (~1e-2 relative), so any
    # reintroduction of storage-dtype arithmetic into the epilogue or
    # the mask flips these signs and fails the tight tolerance.
    delta = 1e-5 * (-1.0) ** np.arange(c)
    bias = jnp.asarray(-prod[np.arange(c) % m, np.arange(c)] + delta,
                       jnp.float32)

    def loss_f(f):
        return lambda *a: jnp.sum(jnp.sin(f(*a)[0].astype(jnp.float32)))

    zr, _ = conv_block_reference(x, w, scale, bias)
    zf, _ = conv1x1_bn_relu(x, w, scale, bias)
    assert np.array_equal(np.asarray(zr, np.float32),
                          np.asarray(zf, np.float32))
    gr = jax.grad(loss_f(conv_block_reference),
                  argnums=(0, 1, 2, 3))(x, w, scale, bias)
    gf = jax.grad(loss_f(conv1x1_bn_relu),
                  argnums=(0, 1, 2, 3))(x, w, scale, bias)
    for a, b in zip(gr, gf):
        _close(a.astype(jnp.float32), b.astype(jnp.float32), 2e-2)


def test_nhwc_wrapper_shapes():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16),
                          jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 16, 32),
                          jnp.float32) * 0.1
    scale, bias = jnp.ones((32,)), jnp.zeros((32,))
    z, (mean, var) = conv1x1_bn_act_nhwc(x, w, scale, bias)
    assert z.shape == (2, 8, 8, 32)
    assert mean.shape == (32,) and var.shape == (32,)
    z_ref, _ = conv_block_reference(x.reshape(-1, 16),
                                    w.reshape(16, 32), scale, bias)
    _close(z_ref.reshape(2, 8, 8, 32), z, 1e-5)


def test_relu_mask_actually_masks():
    """The backward really is the ReLU backward: gradients w.r.t. x are
    zero wherever the block output is clamped to zero (pin against a
    bias shift that clamps most of one channel)."""
    x, w, scale, _ = _mk(64, 8, 16, seed=5)
    bias = jnp.full((16,), -10.0)  # clamps every channel hard
    z, _ = conv1x1_bn_relu(x, w, scale, bias)
    assert float(jnp.max(z)) == 0.0
    g = jax.grad(lambda x: jnp.sum(conv1x1_bn_relu(
        x, w, scale, bias)[0]))(x)
    _close(g, jnp.zeros_like(g), 1e-12)


def test_sync_bn_semantics_across_mesh():
    """Under shard_map with axis_name, the fused block computes GLOBAL
    batch stats and gradients whose psum equals the single-device
    oracle — sync-BN semantics (models/resnet.batch_norm contract)."""
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("hvd",))
    m, cin, c = 64, 8, 16
    x, w, scale, bias = _mk(m, cin, c, seed=7)

    def local(x_loc, w, scale, bias):
        def loss(x_loc, w, scale, bias):
            z, st = conv1x1_bn_act(x_loc, w, scale, bias, 1e-5, "hvd",
                                   True)
            return jnp.sum(jnp.sin(z)), st
        (l, st), g = jax.value_and_grad(
            loss, argnums=(0, 1, 2, 3), has_aux=True)(x_loc, w, scale,
                                                      bias)
        gw = jax.lax.psum(g[1], "hvd")
        gs = jax.lax.psum(g[2], "hvd")
        gb = jax.lax.psum(g[3], "hvd")
        return jax.lax.psum(l, "hvd"), st, g[0], gw, gs, gb

    sharded = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P("hvd"), P(), P(), P()),
        out_specs=(P(), P(), P("hvd"), P(), P(), P()),
        check_vma=False))
    l_sh, (mean_sh, var_sh), gx_sh, gw_sh, gs_sh, gb_sh = sharded(
        x, w, scale, bias)

    def oracle_loss(x, w, scale, bias):
        z, st = conv_block_reference(x, w, scale, bias)
        return jnp.sum(jnp.sin(z)), st
    (l_o, (mean_o, var_o)), g_o = jax.value_and_grad(
        oracle_loss, argnums=(0, 1, 2, 3), has_aux=True)(x, w, scale,
                                                         bias)
    assert abs(float(l_sh) - float(l_o)) < 1e-4
    _close(mean_o, mean_sh, 1e-5)
    _close(var_o, var_sh, 1e-5)
    _close(g_o[0], gx_sh, 1e-4)
    _close(g_o[1], gw_sh, 1e-4)
    _close(g_o[2], gs_sh, 1e-4)
    _close(g_o[3], gb_sh, 1e-4)


def test_resnet_block_path_matches_unfused(monkeypatch):
    """The model-level wire-up (models/resnet.py HOROVOD_CONV_BLOCK):
    loss, gradients, and running-stat updates are identical with the
    fused block family on and off. Mini 2-block depth keeps
    interpret-mode runtime testable."""
    from horovod_tpu.models import resnet

    resnet.STAGE_BLOCKS[8] = (1, 1)  # test-only mini depth
    try:
        params, stats = resnet.init(jax.random.PRNGKey(0), depth=8,
                                    num_classes=10, dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3),
                              jnp.float32)
        yl = jax.random.randint(jax.random.PRNGKey(2), (2,), 0, 10)

        def run(block):
            monkeypatch.setenv("HOROVOD_CONV_BLOCK",
                               "1" if block else "0")

            def loss(p):
                return resnet.loss_fn(p, stats, (x, yl), depth=8,
                                      train=True)
            (l, ns), g = jax.value_and_grad(loss, has_aux=True)(params)
            return l, ns, g

        l0, ns0, g0 = run(False)
        l1, ns1, g1 = run(True)
        assert abs(float(l0) - float(l1)) < 1e-5
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            _close(a, b, 1e-4)
        for a, b in zip(jax.tree_util.tree_leaves(ns0),
                        jax.tree_util.tree_leaves(ns1)):
            _close(a, b, 1e-4)
    finally:
        resnet.STAGE_BLOCKS.pop(8, None)


def test_kernels_lower_through_real_tpu_compiler(monkeypatch):
    """Both new kernels compile for a real v5e topology (compile-only
    client, zero chips) at a representative ResNet site — probe/skip
    logic shared with the conv_bn_backward suite (tests/tpu_probe.py)."""
    from tpu_probe import compile_kernel_text, tpu_topology

    from horovod_tpu.ops import conv_bn_backward as cbb
    from horovod_tpu.ops.conv_block import (conv1x1_bn_act_bwd_fused,
                                            conv1x1_fwd_fused)

    # conftest pins the CPU backend, which flips the kernels to
    # interpret mode — force the real Mosaic lowering (both modules
    # share conv_bn_backward._interpret)
    monkeypatch.setattr(cbb, "_interpret", lambda: False)
    topo = tpu_topology(monkeypatch)
    m, cin, c = 128 * 28 * 28, 128, 512

    def st(shape, dt=jnp.bfloat16):
        return jax.ShapeDtypeStruct(shape, dt)
    vec = lambda: st((c,), jnp.float32)  # noqa: E731
    compile_kernel_text(topo, conv1x1_fwd_fused,
                        (st((m, cin)), st((cin, c))), "_fwd_kernel")
    compile_kernel_text(
        topo,
        lambda dz, y, x, w, s, b, mean, inv, db, dg:
        conv1x1_bn_act_bwd_fused(dz, y, x, w, s, b, mean, inv, db, dg),
        (st((m, c)), st((m, c)), st((m, cin)), st((cin, c)),
         vec(), vec(), vec(), vec(), vec(), vec()),
        "_bwd_kernel")
