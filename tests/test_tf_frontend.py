"""TensorFlow frontend tests (reference analog: test/parallel/
test_tensorflow.py — collective semantics through the TF API surface)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")


def test_tf_allreduce_roundtrip(hvd):
    import horovod_tpu.frontends.tensorflow as tfvd
    x = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    y = tfvd.allreduce(x)  # average of identical copies == identity
    assert isinstance(y, tf.Tensor)
    np.testing.assert_allclose(y.numpy(), x.numpy())
    s = tfvd.allreduce(x, op=tfvd.Sum)
    np.testing.assert_allclose(s.numpy(), x.numpy() * tfvd.size())


def test_tf_broadcast_variables(hvd):
    import horovod_tpu.frontends.tensorflow as tfvd
    v = tf.Variable(tf.ones((3,)) * (tfvd.rank() + 7))
    tfvd.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), 7.0)


def test_tf_allgather_alltoall(hvd):
    import horovod_tpu.frontends.tensorflow as tfvd
    k = tfvd.size()
    g = tfvd.allgather(tf.ones((2, 3)))
    assert g.shape == (2 * k, 3)
    out, recv = tfvd.alltoall(tf.ones((2 * k, 3)))
    assert out.shape == (2 * k, 3)
    np.testing.assert_array_equal(recv.numpy(), np.full(k, 2))


def test_tf_distributed_gradient_tape(hvd):
    import horovod_tpu.frontends.tensorflow as tfvd
    w = tf.Variable([[2.0]])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(w * 3.0)
    dtape = tfvd.DistributedGradientTape(tape)
    (grad,) = dtape.gradient(loss, [w])
    # identical ranks → average == local gradient
    np.testing.assert_allclose(grad.numpy(), [[3.0]], rtol=1e-6)


def test_tf_tape_compression_and_predivide(hvd):
    import horovod_tpu.frontends.tensorflow as tfvd
    with pytest.raises(ValueError):
        tfvd.DistributedGradientTape(tf.GradientTape(), op=tfvd.Sum,
                                     gradient_predivide_factor=2.0)
    w = tf.Variable(tf.ones((4, 4)))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(w * 0.5)
    dtape = tfvd.DistributedGradientTape(
        tape, compression=tfvd.Compression.fp16,
        gradient_predivide_factor=4.0)
    (grad,) = dtape.gradient(loss, [w])
    assert grad.dtype == tf.float32  # decompressed back
    np.testing.assert_allclose(grad.numpy(), 0.5, rtol=1e-2)


def test_tf_distributed_optimizer(hvd):
    import keras

    import horovod_tpu.frontends.tensorflow as tfvd
    v = tf.Variable(1.0)
    opt = tfvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.1))
    opt.apply_gradients([(tf.constant(2.0), v)])
    # mean grad over identical ranks == 2.0 → v = 1 - 0.1*2
    np.testing.assert_allclose(v.numpy(), 0.8, rtol=1e-6)


def test_tf_optimizer_local_aggregation(hvd):
    import keras

    import horovod_tpu.frontends.tensorflow as tfvd
    v = tf.Variable(0.0)
    opt = tfvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=1.0),
                                    backward_passes_per_step=2)
    opt.apply_gradients([(tf.constant(1.0), v)])
    np.testing.assert_allclose(v.numpy(), 0.0)  # first pass only accumulates
    opt.apply_gradients([(tf.constant(3.0), v)])
    # second pass applies the local mean (1+3)/2 = 2
    np.testing.assert_allclose(v.numpy(), -2.0, rtol=1e-6)


def test_tf_function_allreduce(hvd):
    """Collectives inside tf.function lower to the py_function bridge
    (reference: tensorflow/mpi_ops.cc:461 AsyncOpKernels work in graphs)."""
    import horovod_tpu.frontends.tensorflow as tfvd

    @tf.function
    def f(x):
        return tfvd.allreduce(x, op=tfvd.Sum)

    x = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    y = f(x)
    assert y.shape == (2, 3)
    np.testing.assert_allclose(y.numpy(), x.numpy() * tfvd.size())

    @tf.function
    def g(x):
        out = tfvd.allgather(x)
        b = tfvd.broadcast(x, root_rank=0)
        return out, b

    out, b = g(tf.ones((2, 3)))
    assert out.shape == (2 * tfvd.size(), 3)
    np.testing.assert_allclose(b.numpy(), 1.0)


def test_tf_function_reducescatter_alltoall_barrier(hvd):
    """The remaining collectives work through the graph bridge too."""
    import horovod_tpu.frontends.tensorflow as tfvd
    k = tfvd.size()

    @tf.function
    def f(x):
        rs = tfvd.reducescatter(x, op=tfvd.Sum)
        out, recv = tfvd.alltoall(x)
        b = tfvd.barrier()
        return rs, out, recv, b

    x = tf.ones((2 * k, 3))
    rs, out, recv, b = f(x)
    np.testing.assert_allclose(rs.numpy(), np.full((2, 3), float(k)))
    assert out.shape == (2 * k, 3)
    np.testing.assert_array_equal(recv.numpy(), np.full(k, 2))
    assert int(b) == 0


def test_tf_function_gradient_tape_step(hvd):
    """A tf.function-wrapped train step with DistributedGradientTape
    converges (VERDICT r2 #3)."""
    import horovod_tpu.frontends.tensorflow as tfvd
    w = tf.Variable([[2.0]])
    opt_lr = 0.1

    @tf.function
    def train_step(x):
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.square(w * x - 3.0))
        dtape = tfvd.DistributedGradientTape(tape)
        (grad,) = dtape.gradient(loss, [w])
        w.assign_sub(opt_lr * grad)
        return loss

    losses = [float(train_step(tf.constant([[1.0]]))) for _ in range(20)]
    assert losses[-1] < losses[0] * 1e-3, losses
    np.testing.assert_allclose(w.numpy(), 3.0, rtol=1e-2)


def test_tf_function_grouped_order_chained(hvd):
    """Bridge ops in one graph are chained with control dependencies so
    execution order == trace order on every rank."""
    import horovod_tpu.frontends.tensorflow as tfvd

    @tf.function
    def f(a, b):
        x = tfvd.allreduce(a, op=tfvd.Sum)
        y = tfvd.allreduce(b, op=tfvd.Sum)  # no data dep on x
        return x, y

    cf = f.get_concrete_function(
        tf.TensorSpec((2,), tf.float32), tf.TensorSpec((3,), tf.float32))
    eager_ops = [op for op in cf.graph.get_operations()
                 if op.type == "EagerPyFunc"]
    assert len(eager_ops) == 2
    assert any(c is eager_ops[0] for c in eager_ops[1].control_inputs), \
        f"second collective not chained: {eager_ops[1].control_inputs}"


def test_tf_function_bpps_keras_native(hvd):
    """Keras-3 path: bpps maps onto gradient_accumulation_steps and works
    inside tf.function."""
    import keras

    import horovod_tpu.frontends.tensorflow as tfvd
    v = tf.Variable(0.0)
    opt = tfvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=1.0),
                                    backward_passes_per_step=2)
    assert isinstance(opt, keras.optimizers.Optimizer)

    @tf.function
    def step(g):
        opt.apply_gradients([(g, v)])

    step(tf.constant(1.0))
    np.testing.assert_allclose(v.numpy(), 0.0)  # accumulating
    step(tf.constant(3.0))
    np.testing.assert_allclose(v.numpy(), -2.0, rtol=1e-6)  # mean applied


def test_tf_function_bpps_eager_wrapper_raises(hvd):
    """Non-Keras optimizers keep the eager wrapper, whose Python-state
    accumulation cannot be traced."""
    import horovod_tpu.frontends.tensorflow as tfvd

    class _DummyOpt:
        def apply_gradients(self, gv, **kw):
            pass

    opt = tfvd.DistributedOptimizer(_DummyOpt(), backward_passes_per_step=2)
    v = tf.Variable(1.0)

    @tf.function
    def step():
        opt.apply_gradients([(tf.constant(2.0), v)])

    with pytest.raises(NotImplementedError, match="backward_passes_per_step"):
        step()


def test_tf_metric_average_callback(hvd):
    import horovod_tpu.frontends.tensorflow as tfvd
    cb = tfvd.MetricAverageCallback()
    logs = {"loss": 4.0}
    cb.on_epoch_end(0, logs)
    np.testing.assert_allclose(logs["loss"], 4.0)  # identical ranks


def test_callbacks_namespace_and_lr_schedule(hvd):
    """Reference spelling parity: hvd.callbacks.* exists
    (tensorflow/keras/callbacks.py), and LearningRateScheduleCallback
    applies a multiplier over its epoch range."""
    import keras

    import horovod_tpu.frontends.tensorflow as tfvd

    for name in ("BroadcastGlobalVariablesCallback", "MetricAverageCallback",
                 "LearningRateWarmupCallback",
                 "LearningRateScheduleCallback"):
        assert hasattr(tfvd.callbacks, name)

    model = keras.Sequential([keras.layers.Input((2,)),
                              keras.layers.Dense(1)])
    model.compile(optimizer=keras.optimizers.SGD(learning_rate=1.0),
                  loss="mse")
    cb = tfvd.callbacks.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e,
        start_epoch=1, end_epoch=3)
    cb.set_model(model)
    cb.on_epoch_begin(0)
    np.testing.assert_allclose(float(model.optimizer.learning_rate), 1.0)
    cb.on_epoch_begin(1)
    np.testing.assert_allclose(float(model.optimizer.learning_rate), 0.1)
    cb.on_epoch_begin(2)
    np.testing.assert_allclose(float(model.optimizer.learning_rate), 0.01,
                               rtol=1e-6)
    cb.on_epoch_begin(3)  # out of range: unchanged
    np.testing.assert_allclose(float(model.optimizer.learning_rate), 0.01,
                               rtol=1e-6)


def test_lr_schedule_smooth_and_reference_kwargs(hvd):
    """staircase=False interpolates per batch; reference kwargs
    (momentum_correction, steps_per_epoch) are accepted
    (reference: _keras/callbacks.py:108)."""
    import keras

    import horovod_tpu.frontends.tensorflow as tfvd

    model = keras.Sequential([keras.layers.Input((2,)),
                              keras.layers.Dense(1)])
    model.compile(optimizer=keras.optimizers.SGD(learning_rate=1.0),
                  loss="mse")
    cb = tfvd.callbacks.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.5 ** e,
        staircase=False, momentum_correction=False, steps_per_epoch=4)
    cb.set_model(model)
    cb.on_epoch_begin(1)
    cb.on_train_batch_end(1)  # epoch 1.5 -> 0.5**1.5
    np.testing.assert_allclose(float(model.optimizer.learning_rate),
                               0.5 ** 1.5, rtol=1e-5)


def test_tf_jit_compile_pinned_error(hvd):
    """`tf.function(jit_compile=True)` around a collective fails with TF's
    unsupported-op (EagerPyFunc) error: the graph bridge re-enters the
    eager engine via py_function, which TF-XLA cannot compile. Pinned here
    so the failure mode is a contract, not a surprise; the migration path
    is documented in docs/migration.md ("TF-XLA training steps"). The
    reference compiles collectives under TF-XLA via paired async custom
    calls (tensorflow/xla_mpi_ops.cc:176-218) — an intentionally
    unreplicated design: this framework's XLA-native path is the jax
    frontend, where the collective IS an XLA op inside the jitted step.
    """
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    @tf.function(jit_compile=True)
    def step(x):
        return tfvd.allreduce(x, op=tfvd.Sum, name="xla_pin")

    with pytest.raises(Exception) as ei:
        step(tf.constant([1.0, 2.0]))
    msg = str(ei.value)
    assert "EagerPyFunc" in msg or "unsupported operations" in msg
    # plain tf.function (no jit_compile) with the same collective works
    @tf.function
    def step_ok(x):
        return tfvd.allreduce(x, op=tfvd.Sum, name="xla_pin_ok")

    out = step_ok(tf.constant([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(),
                               np.array([1.0, 2.0]) * hvd.size())


def test_tf_min_max_product_exports(hvd):
    """Reference exports Min/Max/Product on the TF surface too
    (tensorflow/mpi_ops.py:85-87)."""
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    t = tf.constant([2.0, 5.0])
    out = tfvd.allreduce(t, op=tfvd.Product, name="tfpr")
    np.testing.assert_allclose(out.numpy(),
                               np.array([2.0, 5.0]) ** hvd.size())
    out2 = tfvd.allreduce(t, op=tfvd.Max, name="tfmx")
    np.testing.assert_allclose(out2.numpy(), t.numpy())


def test_tf_api_sweep_round4(hvd):
    """Round-4 TF surface sweep vs reference mpi_ops.py/functions.py:
    grouped allgather/reducescatter, topology *_op tensors, broadcast_
    over Variables, broadcast_object_fn."""
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    k = hvd.size()
    outs = tfvd.grouped_allgather([tf.ones((2, 3)), tf.zeros((1, 5))])
    assert outs[0].shape == (2 * k, 3) and outs[1].shape == (k, 5)

    outs = tfvd.grouped_reducescatter([tf.ones((k * 2, 3))],
                                      op=tfvd.Sum)
    np.testing.assert_allclose(outs[0].numpy(),
                               np.full((2, 3), float(k)))

    assert int(tfvd.size_op()) == k
    assert int(tfvd.rank_op()) == hvd.rank()
    assert int(tfvd.local_rank_op()) == hvd.local_rank()
    assert int(tfvd.local_size_op()) == hvd.local_size()
    assert int(tfvd.process_set_included_op()) == 1

    v = tf.Variable([1.0, 2.0])
    got = tfvd.broadcast_([v], root_rank=0)
    assert got[0] is v
    np.testing.assert_allclose(v.numpy(), [1.0, 2.0])

    fn = tfvd.broadcast_object_fn(root_rank=0)
    assert fn({"a": 1}) == {"a": 1}


def test_tf_keras_load_model_rewraps_optimizer(hvd, tmp_path):
    """hvd.load_model reloads a model saved with a DistributedOptimizer
    and keeps it distributed for retraining (reference:
    tensorflow/keras/__init__.py:234)."""
    import keras

    import horovod_tpu.frontends.tensorflow as tfvd

    m = keras.Sequential([keras.layers.Input((4,)), keras.layers.Dense(2)])
    m.compile(optimizer=tfvd.DistributedOptimizer(
        keras.optimizers.SGD(0.1)), loss="mse")
    m.fit(np.ones((8, 4)), np.ones((8, 2)), epochs=1, verbose=0)
    path = str(tmp_path / "m.keras")
    m.save(path)

    m2 = tfvd.load_model(path)
    assert type(m2.optimizer).__name__ == "DistributedSGD"
    assert float(m2.optimizer.learning_rate) == pytest.approx(0.1)
    m2.fit(np.ones((8, 4)), np.ones((8, 2)), epochs=1, verbose=0)


def test_tf_grouped_ops_inside_tf_function(hvd):
    """grouped_allgather/grouped_reducescatter must ride the py_function
    bridge like every other collective (parity row 24: 'eager AND inside
    tf.function')."""
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    k = hvd.size()

    @tf.function
    def f(x, y):
        ag = tfvd.grouped_allgather([x])
        rs = tfvd.grouped_reducescatter([y], op=tfvd.Sum)
        return ag[0], rs[0]

    ag, rs = f(tf.ones((2, 3)), tf.ones((k * 2, 3)))
    assert ag.shape == (2 * k, 3)
    np.testing.assert_allclose(rs.numpy(), np.full((2, 3), float(k)))


def test_partial_distributed_tape_and_optimizer(hvd):
    """PartialDistributed{GradientTape,Optimizer}: local layers' grads
    are never reduced and (by default) divided by the set size
    (reference: tensorflow/__init__.py:1205, keras/__init__.py:116,
    pull/3695 scaling)."""
    import keras
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    k = hvd.size()

    # tape path: one global var, one local var. With identical ranks the
    # averaged global grad equals the local grad; the LOCAL one is
    # divided by k.
    g_var = tf.Variable([2.0])
    l_var = tf.Variable([3.0])
    with tf.GradientTape() as tape:
        loss = 4.0 * g_var[0] + 8.0 * l_var[0]
    # wrap with local_layers=... needs Layer objects for the helper, so
    # register directly on the tape
    dtape = tfvd.DistributedGradientTape(tape)
    dtape.register_local_source(l_var)
    gg, lg = dtape.gradient(loss, [g_var, l_var])
    np.testing.assert_allclose(gg.numpy(), [4.0])
    np.testing.assert_allclose(lg.numpy(), [8.0 / k])

    # optimizer path via local_layers: the local Dense layer's weights
    # step by grad/k; equality of updates is checked vs manual math
    local_layer = keras.layers.Dense(1, use_bias=False,
                                     kernel_initializer="ones")
    local_layer.build((None, 1))
    opt = tfvd.PartialDistributedOptimizer(
        keras.optimizers.SGD(1.0), local_layers=[local_layer])
    assert type(opt).__name__ == "PartialDistributedSGD"
    w = local_layer.trainable_weights[0]
    grads = [tf.ones_like(w)]
    opt.apply(grads, [w])
    # w started at 1, lr=1, grad 1 scaled by 1/k -> w = 1 - 1/k
    np.testing.assert_allclose(w.numpy(), [[1.0 - 1.0 / k]], rtol=1e-6)

    # with no local layers it degrades to the plain DistributedOptimizer
    opt2 = tfvd.PartialDistributedOptimizer(keras.optimizers.SGD(0.1))
    assert type(opt2).__name__ == "DistributedSGD"


def test_keras_alias_module(hvd):
    """`horovod.keras`-shaped import surface (reference:
    horovod/keras/__init__.py re-exports)."""
    import horovod_tpu.frontends.keras as khvd

    assert khvd.size() == hvd.size()
    out = khvd.allreduce(np.ones(3, np.float32), op=khvd.Sum)
    np.testing.assert_allclose(np.asarray(out), hvd.size())
    assert callable(khvd.callbacks.BroadcastGlobalVariablesCallback)


def test_partial_local_scaling_keeps_indexed_slices(hvd):
    """Local-gradient scaling must not densify IndexedSlices (embedding
    grads — the canonical local layer); reference scales .values."""
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    k = hvd.size()
    v = tf.Variable(tf.ones((10, 4)))
    with tf.GradientTape() as tape:
        rows = tf.gather(v, [1, 3])
        loss = tf.reduce_sum(rows)
    dtape = tfvd.DistributedGradientTape(tape)
    dtape.register_local_source(v)
    g = dtape.gradient(loss, v)
    assert isinstance(g, tf.IndexedSlices), "local grad was densified"
    np.testing.assert_allclose(g.values.numpy(),
                               np.ones((2, 4)) / k)


def test_partial_optimizer_unbuilt_layer_resolves_lazily(hvd):
    """local_layers passed BEFORE the layer builds must still be treated
    as local at apply time (review finding: silent degrade to full
    allreduce)."""
    import keras
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as tfvd

    k = hvd.size()
    layer = keras.layers.Dense(1, use_bias=False,
                               kernel_initializer="ones")
    # NOT built yet when the optimizer wraps it
    opt = tfvd.PartialDistributedOptimizer(
        keras.optimizers.SGD(1.0), local_layers=[layer])
    assert type(opt).__name__ == "PartialDistributedSGD"
    layer.build((None, 1))  # builds after wrapping
    w = layer.trainable_weights[0]
    opt.apply([tf.ones_like(w)], [w])
    # local semantics: grad scaled by 1/k -> w = 1 - 1/k
    np.testing.assert_allclose(w.numpy(), [[1.0 - 1.0 / k]], rtol=1e-6)

    # same laziness through the tape wrapper
    layer2 = keras.layers.Dense(1, use_bias=False,
                                kernel_initializer="ones")
    with tf.GradientTape() as t:
        pass
    dtape = tfvd.PartialDistributedGradientTape(t, local_layers=[layer2])
    layer2.build((None, 1))
    assert dtape._is_local(layer2.trainable_weights[0])
