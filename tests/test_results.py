"""Per-rank result/error collection (runner/results.py) — the shared logic
behind the Spark/Ray integrations' driver-side error reporting (reference:
spark/runner.py task error surfacing, ray/elastic_v2.py retry limits)."""

import pytest

from horovod_tpu.runner.results import (PerRankResults, RemoteJobError,
                                        RestartPolicy, capture)


def test_capture_roundtrips_result_and_traceback():
    ok, val = capture(lambda x: x + 1, 41)
    assert ok and val == 42
    ok, tb = capture(lambda: 1 / 0)
    assert not ok
    assert "ZeroDivisionError" in tb


def test_per_rank_results_ordered():
    r = PerRankResults(3)
    for rank in (2, 0, 1):  # out-of-order arrival
        r.add(rank, True, f"v{rank}")
    assert r.values() == ["v0", "v1", "v2"]


def test_per_rank_results_names_failures():
    r = PerRankResults(3)
    r.add(0, True, "ok")
    r.add(1, False, "Traceback ... boom")
    r.add(2, True, "ok")
    with pytest.raises(RemoteJobError) as ei:
        r.values()
    assert "rank 1 failed" in str(ei.value)
    assert "boom" in str(ei.value)


def test_per_rank_results_names_missing():
    r = PerRankResults(2)
    r.add(0, True, "ok")
    with pytest.raises(RemoteJobError) as ei:
        r.values()
    assert "[1]" in str(ei.value)


def test_restart_policy_limits():
    p = RestartPolicy(max_restarts=2)
    assert p.should_restart(0)
    p.record_restart(0)
    p.record_restart(0)
    assert not p.should_restart(0)
    assert p.should_restart(1)  # per-rank accounting
    assert p.restarts(0) == 2
