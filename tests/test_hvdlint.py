"""hvdlint unit suite: fixture snippets for every rule (positive,
negative, suppression), the driver/CLI surface, the HVD-ENV project
rule, the fingerprint verifier against a fake KV, and the stall-
watchdog message integration (docs/static_analysis.md)."""

import pathlib
import textwrap

import pytest

from horovod_tpu.analysis import env_rule
from horovod_tpu.analysis.driver import lint_paths, lint_source, run_cli
from horovod_tpu.analysis.verifier import FingerprintVerifier
from horovod_tpu.common.exceptions import (CollectiveDivergenceError,
                                           HorovodInternalError)

REPO = pathlib.Path(__file__).resolve().parent.parent


def ids(findings):
    return [f.rule_id for f in findings]


def src(text):
    return textwrap.dedent(text)


# ---------------------------------------------------------------- HVD001

def test_hvd001_rank_guarded_collective():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)
    """))
    assert ids(findings) == ["HVD001"]
    assert "rank-dependent" in findings[0].message


def test_hvd001_else_branch_and_while():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                pass
            else:
                hvd.allreduce(x)
            while hvd.local_rank() != 0:
                hvd.barrier()
    """))
    assert ids(findings) == ["HVD001", "HVD001"]


def test_hvd001_negative_no_collective_under_guard():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            y = hvd.allreduce(x, name="t")
            if hvd.rank() == 0:
                print(y)
    """))
    assert findings == []


def test_hvd001_negative_nested_def_not_flagged():
    # A def inside the guard only runs if called; the callsite is the
    # thing to flag.
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                def helper():
                    return hvd.allreduce(x)
            return 0
    """))
    assert findings == []


def test_hvd001_suppression_with_rationale():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)  # hvdlint: disable=HVD001 -- every rank reaches this branch via a synced flag
    """))
    assert findings == []


def test_suppression_without_rationale_is_hvd000():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)  # hvdlint: disable=HVD001
    """))
    assert ids(findings) == ["HVD000"]


def test_foreign_receivers_not_collectives():
    findings = lint_source(src("""
        import numpy as np, jax.numpy as jnp, horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                np.broadcast(x, x)
                jnp.broadcast(x, x)
    """))
    assert findings == []


# ---------------------------------------------------------------- HVD002

def test_hvd002_set_iteration_naming():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(tensors):
            for k in {"a", "b"}:
                hvd.allreduce(tensors[k], name="grad." + k)
    """))
    assert ids(findings) == ["HVD002"]
    assert "unordered" in findings[0].message


def test_hvd002_set_call_and_fstring():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(d):
            for k in set(d):
                hvd.allreduce(d[k], name=f"g.{k}")
    """))
    assert ids(findings) == ["HVD002"]


def test_hvd002_negative_ordered_iteration():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(d):
            for k in sorted(d):
                hvd.allreduce(d[k], name=f"g.{k}")
            for k in ["a", "b"]:
                hvd.allreduce(d[k], name=f"g.{k}")
    """))
    assert findings == []


def test_hvd002_negative_name_not_from_loop_var():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(d):
            for i, k in enumerate(sorted({"a", "b"})):
                hvd.allreduce(d[k], name="fixed")
    """))
    assert findings == []


# ---------------------------------------------------------------- HVD003

def test_hvd003_unnamed_in_loop():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(ts):
            for t in ts:
                hvd.allreduce(t)
    """))
    assert ids(findings) == ["HVD003"]


def test_hvd003_negative_named_or_wrapper():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(ts, params):
            for i, t in enumerate(ts):
                hvd.allreduce(t, name=f"t{i}")
            for p in params:
                hvd.broadcast_parameters(p, root_rank=0)
            hvd.allreduce(ts[0])  # not in a loop
    """))
    assert findings == []


def test_hvd003_negative_positional_name():
    # name is the 3rd positional parameter of allreduce/broadcast and
    # the 2nd of allgather — positionally-named calls are named calls.
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(ts):
            for i, t in enumerate(ts):
                hvd.allreduce(t, None, f"t{i}")
                hvd.broadcast(t, 0, f"b{i}")
                hvd.allgather(t, f"g{i}")
    """))
    assert findings == []


def test_hvd003_suppression():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(ts):
            for t in ts:
                hvd.allreduce(t)  # hvdlint: disable=HVD003 -- single-iteration loop in this config
    """))
    assert findings == []


# ---------------------------------------------------------------- HVD004

def test_hvd004_process_set_differs():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x, cond, ps_a, ps_b):
            if cond:
                hvd.allreduce(x, name="t", process_set=ps_a)
            else:
                hvd.allreduce(x, name="t", process_set=ps_b)
    """))
    assert ids(findings) == ["HVD004"]


def test_hvd004_missing_in_one_branch():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x, cond, ps_a):
            if cond:
                hvd.allreduce(x, name="t", process_set=ps_a)
            else:
                hvd.allreduce(x, name="t")
    """))
    assert ids(findings) == ["HVD004"]


def test_hvd004_negative_same_process_set():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x, cond, ps_a):
            if cond:
                hvd.allreduce(x, name="t", process_set=ps_a)
            else:
                hvd.allreduce(x * 2, name="t", process_set=ps_a)
    """))
    assert findings == []


# ------------------------------------------- HVD001/004 interprocedural

def test_hvd001_interprocedural_helper():
    """The fixture the lexical pass provably misses: the collective
    lives in a helper, the rank guard wraps only the callsite."""
    code = src("""
        import horovod_tpu as hvd
        def sync(x):
            return hvd.allreduce(x, name="s")
        def f(x):
            if hvd.rank() == 0:
                sync(x)
    """)
    # Lexically there is no collective under the guard...
    assert lint_source(code, select=["HVD001"]) != [], \
        "interprocedural HVD001 must flag the helper callsite"
    findings = lint_source(code)
    assert "HVD001" in ids(findings)
    f = [x for x in findings if x.rule_id == "HVD001"][0]
    assert "sync" in f.message and "allreduce" in f.message
    assert f.line == 7  # anchored at the callsite, not the helper body


def test_hvd001_interprocedural_two_hops():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def inner(x):
            return hvd.barrier()
        def outer(x):
            return inner(x)
        def f(x):
            if hvd.rank() == 0:
                outer(x)
    """))
    assert "HVD001" in ids(findings)


def test_hvd001_interprocedural_negative_clean_helper():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def log(x):
            print(x)
        def f(x):
            if hvd.rank() == 0:
                log(x)
    """))
    assert findings == []


def test_hvd001_interprocedural_method():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        class Trainer:
            def _sync(self, x):
                return hvd.allreduce(x, name="s")
            def run(self, x):
                if hvd.rank() == 0:
                    self._sync(x)
    """))
    assert ids(findings) == ["HVD001"]


def test_hvd004_across_call_sites():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def sync(x, ps):
            return hvd.allreduce(x, name="t", process_set=ps)
        def f(x, cond, ps_a, ps_b):
            if cond:
                sync(x, ps_a)
            else:
                sync(x, ps_b)
    """))
    assert "HVD004" in ids(findings)


def test_hvd004_across_call_sites_negative_same_ps():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def sync(x, ps):
            return hvd.allreduce(x, name="t", process_set=ps)
        def f(x, cond, ps_a):
            if cond:
                sync(x, ps_a)
            else:
                sync(x * 2, ps_a)
    """))
    assert findings == []


def test_hvd001_module_alias_respects_module_and_foreign_roots(tmp_path):
    """`np.broadcast` (FOREIGN_ROOTS) and an alias of an UNLINTED
    module must not resolve to unrelated same-named linted helpers."""
    (tmp_path / "helpers.py").write_text(src("""
        import horovod_tpu as hvd
        def broadcast(x):
            return hvd.broadcast(x, root_rank=0)
        def sync(x):
            return hvd.allreduce(x, name="s")
    """))
    (tmp_path / "b.py").write_text(src("""
        import numpy as np
        import othermod
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                np.broadcast(x, x)
                othermod.sync(x)
    """))
    assert lint_paths([str(tmp_path)], env_rule=False) == []
    # ...while an alias of the LINTED module still resolves.
    (tmp_path / "c.py").write_text(src("""
        import helpers
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                helpers.sync(x)
    """))
    findings = lint_paths([str(tmp_path)], env_rule=False)
    assert [f.rule_id for f in findings] == ["HVD001"]
    assert findings[0].path.endswith("c.py")


def test_hvd005_async_def_scope():
    """async def bodies carry the same divergence bug class."""
    findings = lint_source(src("""
        import horovod_tpu as hvd
        async def f(x):
            return hvd.allreduce(x, name=f"g{hvd.rank()}")
    """))
    assert ids(findings) == ["HVD005"]


def test_hvd001_from_import_respects_source_module(tmp_path):
    """A name imported from an UNLINTED module must not resolve to an
    unrelated same-named linted function (cross-module false positive)."""
    (tmp_path / "a.py").write_text(src("""
        import horovod_tpu as hvd
        def sync(x):
            return hvd.allreduce(x, name="s")
    """))
    (tmp_path / "b.py").write_text(src("""
        import horovod_tpu as hvd
        from mymath import sync
        def f(x):
            if hvd.rank() == 0:
                sync(x)
    """))
    assert lint_paths([str(tmp_path)], env_rule=False) == []


def test_hvd001_from_import_matching_module_resolves(tmp_path):
    (tmp_path / "helpers.py").write_text(src("""
        import horovod_tpu as hvd
        def sync(x):
            return hvd.allreduce(x, name="s")
    """))
    (tmp_path / "b.py").write_text(src("""
        import horovod_tpu as hvd
        from helpers import sync
        def f(x):
            if hvd.rank() == 0:
                sync(x)
    """))
    findings = lint_paths([str(tmp_path)], env_rule=False)
    assert [f.rule_id for f in findings] == ["HVD001"]
    assert findings[0].path.endswith("b.py")


# ---------------------------------------------------------------- HVD005

def test_hvd005_direct_rank_in_name():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            return hvd.allreduce(x, name=f"g{hvd.rank()}")
    """))
    assert ids(findings) == ["HVD005"]
    assert "rank-dependent" in findings[0].message


def test_hvd005_through_local_variable():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            r = hvd.rank()
            tag = "worker-%d" % r
            return hvd.allreduce(x, name=tag)
    """))
    assert ids(findings) == ["HVD005"]


def test_hvd005_interprocedural_param():
    """The lexical pass can't see this: the tainted value enters the
    name through a helper's parameter."""
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def helper(x, tag):
            return hvd.allreduce(x, name=f"g.{tag}")
        def f(x):
            return helper(x, hvd.rank())
    """))
    assert "HVD005" in ids(findings)
    f = [x for x in findings if x.rule_id == "HVD005"][0]
    assert "tag" in f.message and f.line == 6  # at the tainting callsite


def test_hvd005_module_global_tainted_through_helper_return():
    """Module-scope taint must see FINAL helper summaries: a global
    assigned from a rank-returning helper taints names in functions
    below (guards the taint-env cache against half-built fixpoints)."""
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def myrank():
            return hvd.rank()
        R = myrank()
        def f(x):
            return hvd.allreduce(x, name="g%d" % R)
    """))
    assert ids(findings) == ["HVD005"]


def test_hvd005_through_tainted_return():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def myrank():
            return hvd.rank()
        def f(x):
            r = myrank()
            return hvd.allreduce(x, name=f"g{r}")
    """))
    assert ids(findings) == ["HVD005"]


def test_hvd005_negative_clean_names():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def title(s):
            return s.upper()
        def f(x, step):
            r = hvd.rank()
            if r == 0:
                print("chief")
            hvd.broadcast(x, 0, "epoch")
            return hvd.allreduce(x, name=title("grad"))
    """))
    assert findings == []


def test_hvd005_suppression():
    findings = lint_source(src("""
        import horovod_tpu as hvd
        def f(x):
            return hvd.allgather(x, f"g{hvd.rank()}")  # hvdlint: disable=HVD005 -- per-rank shards gathered under distinct names by design
    """))
    assert findings == []


# ---------------------------------------------------------------- HVD101

def test_hvd101_guarded_attr_outside_lock():
    findings = lint_source(src("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock
            def bad(self):
                return self._d.get(1)
            def good(self):
                with self._lock:
                    return self._d.get(1)
    """))
    assert ids(findings) == ["HVD101"]
    assert "_d" in findings[0].message and "_lock" in findings[0].message


def test_hvd101_init_exempt_and_cross_object_lock():
    findings = lint_source(src("""
        import threading
        class H:
            store = {}  # guarded-by: lock
            lock = threading.Lock()
            def touch(self):
                with self.lock:
                    self.store["k"] = 1
        class S:
            def __init__(self, h):
                self._h = h
            def put(self, k, v):
                with self._h.lock:
                    self._h.store[k] = v
    """))
    assert findings == []


def test_hvd101_module_global():
    findings = lint_source(src("""
        import threading
        _lk = threading.Lock()
        _state = {}  # guarded-by: _lk
        def bad():
            _state["x"] = 1
        def good():
            with _lk:
                _state["x"] = 1
    """))
    assert ids(findings) == ["HVD101"]


def test_hvd101_suppression_with_rationale():
    findings = lint_source(src("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock
            def fast(self):
                return self._d.get(1)  # hvdlint: disable=HVD101 -- racy read is benign: add-only dict, atomic get under the GIL
    """))
    assert findings == []


# ---------------------------------------------------------------- HVD102

def test_hvd102_thread_without_daemon():
    findings = lint_source(src("""
        import threading
        def f():
            t = threading.Thread(target=f)
            t.start()
    """))
    assert ids(findings) == ["HVD102"]


def test_hvd102_negative_daemon_given():
    findings = lint_source(src("""
        import threading
        def f():
            threading.Thread(target=f, daemon=True).start()
            threading.Thread(target=f, daemon=False).start()
    """))
    assert findings == []


def test_hvd102_other_thread_classes_ignored():
    findings = lint_source(src("""
        import foo
        def f():
            foo.Thread(target=f)
    """))
    assert findings == []


# ---------------------------------------------------------------- HVD103

def test_hvd103_sleep_under_lock():
    findings = lint_source(src("""
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                time.sleep(1)
    """))
    assert ids(findings) == ["HVD103"]


def test_hvd103_negative_outside_lock_or_non_lock_cm():
    findings = lint_source(src("""
        import threading, time
        lock = threading.Lock()
        def f(path):
            with lock:
                x = 1
            time.sleep(1)
            with open(path) as fh:
                time.sleep(0.1)  # not under a lock-ish context
    """))
    assert findings == []


def test_hvd103_subprocess_run_and_popen_wait_under_lock():
    findings = lint_source(src("""
        import subprocess, threading
        lock = threading.Lock()
        def f(proc):
            with lock:
                subprocess.run(["hostname"])
                subprocess.check_output(["hostname"])
                proc.wait(timeout=5)
    """))
    assert ids(findings) == ["HVD103", "HVD103", "HVD103"]
    assert "subprocess.run" in findings[0].message


def test_hvd103_subprocess_run_negative_outside_lock():
    findings = lint_source(src("""
        import subprocess
        def f(run):
            subprocess.run(["hostname"])
            run()  # bare `run` callables are not subprocess.run
    """))
    assert findings == []


def test_hvd103_queue_get_put_without_timeout_under_lock():
    findings = lint_source(src("""
        import queue, threading
        lock = threading.Lock()
        q = queue.Queue()
        def f(item):
            with lock:
                q.get()
                q.put(item)
    """))
    assert ids(findings) == ["HVD103", "HVD103"]
    assert "without a timeout" in findings[0].message


def test_hvd103_queue_nonblocking_negative():
    """block=False queue calls raise Empty/Full immediately — they
    cannot wait, so they must not be flagged."""
    findings = lint_source(src("""
        import queue, threading
        lock = threading.Lock()
        q = queue.Queue()
        def f(item):
            with lock:
                q.get(False)
                q.get(block=False)
                q.put(item, False)
                q.put(item, block=False)
    """))
    assert findings == []


def test_hvd103_queue_with_timeout_and_dicts_negative():
    findings = lint_source(src("""
        import queue, threading
        lock = threading.Lock()
        q = queue.Queue()
        def f(item, d, kv):
            with lock:
                q.get(timeout=1.0)
                q.put(item, True, 2.0)
                d.get("key")          # dict.get: not a queue
                kv.put("scope", "k")  # KV client: not queue-named
            q.get()  # queue op, but not under a lock
    """))
    assert findings == []


def test_hvd103_wait_and_urlopen_under_lock():
    findings = lint_source(src("""
        import threading
        from urllib.request import urlopen
        lock = threading.Lock()
        def f(ev):
            with lock:
                ev.wait(5)
                urlopen("http://x")
    """))
    assert ids(findings) == ["HVD103", "HVD103"]


# ------------------------------------------------------------- HVD-ENV

def _mk_repo(tmp_path, code, docs):
    (tmp_path / "horovod_tpu").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "horovod_tpu" / "m.py").write_text(code)
    (tmp_path / "docs" / "env_vars.md").write_text(docs)
    return tmp_path


def test_env_rule_flags_undocumented(tmp_path):
    root = _mk_repo(tmp_path,
                    'import os\nv = os.environ.get("HOROVOD_MYSTERY")\n',
                    "| `HOROVOD_OTHER` | x |\n")
    findings = env_rule.check_project(str(root))
    assert [f.rule_id for f in findings] == ["HVD-ENV"]
    assert "HOROVOD_MYSTERY" in findings[0].message


def test_env_rule_documented_and_composed_pass(tmp_path):
    root = _mk_repo(
        tmp_path,
        'import os\n'
        'a = os.environ.get("HOROVOD_MYSTERY")\n'
        'b = os.environ.get("HOROVOD_KV_RETRY_MAX_ATTEMPTS")\n',
        "`HOROVOD_MYSTERY` and `HOROVOD_KV_RETRY` prefix\n")
    assert env_rule.check_project(str(root)) == []


def test_env_rule_outside_repo_is_noop(tmp_path):
    assert env_rule.check_project(str(tmp_path)) == []


def test_env_rule_respects_suppression(tmp_path):
    root = _mk_repo(
        tmp_path,
        'import os\n'
        'v = os.environ.get("HOROVOD_SECRET_KNOB")'
        '  # hvdlint: disable=HVD-ENV -- internal-only knob, not a supported surface\n',
        "nothing documented\n")
    assert env_rule.check_project(str(root)) == []


def test_env_rule_suppression_without_rationale_is_hvd000(tmp_path):
    root = _mk_repo(
        tmp_path,
        'import os\n'
        'v = os.environ.get("HOROVOD_SECRET_KNOB")'
        '  # hvdlint: disable=HVD-ENV\n',
        "nothing documented\n")
    findings = env_rule.check_project(str(root))
    assert [f.rule_id for f in findings] == ["HVD000"]


# ------------------------------------------------------- driver surface

def test_driver_output_format_and_exit(tmp_path, capsys):
    bad = tmp_path / "train.py"
    bad.write_text(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)
    """))
    rc = run_cli([str(bad), "--no-env"])
    out = capsys.readouterr().out
    assert rc == 1
    line = [ln for ln in out.splitlines() if "HVD001" in ln][0]
    # Uniform `file:line rule-id message` output.
    loc, rule, *_ = line.split(" ", 2)
    assert loc.endswith("train.py:5") and rule == "HVD001"


def test_driver_select_and_clean_exit(tmp_path, capsys):
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert run_cli([str(ok), "--no-env"]) == 0
    assert "clean" in capsys.readouterr().out


def test_driver_list_rules(capsys):
    assert run_cli(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("HVD001", "HVD002", "HVD003", "HVD004", "HVD101",
                 "HVD102", "HVD103", "HVD-ENV", "HVD000"):
        assert rule in out


def test_select_and_ignore_cover_hvd000(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(src("""
        import horovod_tpu as hvd
        def g(ts):
            for t in ts:
                hvd.allreduce(t)  # hvdlint: disable=HVD003
    """))
    # Bare suppression → HVD000 by default...
    assert [x.rule_id for x in lint_paths([str(f)], env_rule=False)] \
        == ["HVD000"]
    # ...but --ignore/--select apply to HVD000 like any other rule.
    assert lint_paths([str(f)], ignore=["HVD000"], env_rule=False) == []
    assert lint_paths([str(f)], select=["HVD001"], env_rule=False) == []


def test_env_rule_hvd000_not_duplicated(tmp_path):
    """A bare HVD-ENV suppression inside the linted tree must yield ONE
    HVD000, not one from the AST pass plus one from check_project."""
    root = _mk_repo(
        tmp_path,
        'X = "HOROVOD_SECRET_KNOB"  # hvdlint: disable=HVD-ENV\n',
        "nothing documented\n")
    findings = lint_paths([str(root / "horovod_tpu")], root=str(root))
    assert [f.rule_id for f in findings] == ["HVD000"]


def test_syntax_error_becomes_hvd999(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(:\n")
    findings = lint_paths([str(bad)], env_rule=False)
    assert [f.rule_id for f in findings] == ["HVD999"]


def test_nonexistent_path_fails_the_gate(tmp_path):
    """A typo'd path must fail lint, not silently report clean — this
    command fronts CI."""
    for bogus in (tmp_path / "no_such_dir", tmp_path / "nope.py"):
        findings = lint_paths([str(bogus)], env_rule=False)
        assert [f.rule_id for f in findings] == ["HVD999"], bogus
        assert "does not exist" in findings[0].message


def test_driver_json_format(tmp_path, capsys):
    bad = tmp_path / "train.py"
    bad.write_text(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)
    """))
    rc = run_cli([str(bad), "--no-env", "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 1
    import json
    payload = json.loads(out)
    assert payload["count"] == 1
    f = payload["findings"][0]
    assert f["rule"] == "HVD001" and f["line"] == 5
    assert f["path"].endswith("train.py")


def test_driver_baseline_filters_known_findings(tmp_path, capsys):
    """--baseline: a checked-in json dump absorbs existing findings so
    CI gates on NEW ones only; a new finding still fails."""
    bad = tmp_path / "train.py"
    bad.write_text(src("""
        import horovod_tpu as hvd
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)
    """))
    baseline = tmp_path / "baseline.json"
    rc = run_cli([str(bad), "--no-env", "--format", "json"])
    baseline.write_text(capsys.readouterr().out)
    assert rc == 1
    # Same findings + baseline → clean exit, nothing printed as new.
    rc = run_cli([str(bad), "--no-env", "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 0 and "HVD001" not in out
    # Introduce a NEW finding (line numbers shift too — the baseline
    # match must survive that): only the new one gates.
    bad.write_text(src("""
        import horovod_tpu as hvd

        def g(ts):
            for t in ts:
                hvd.allreduce(t)
        def f(x):
            if hvd.rank() == 0:
                hvd.broadcast(x, root_rank=0)
    """))
    rc = run_cli([str(bad), "--no-env", "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD003" in out and "HVD001" not in out


def test_driver_baseline_unreadable_fails(tmp_path, capsys):
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    rc = run_cli([str(ok), "--no-env", "--baseline",
                  str(tmp_path / "missing.json")])
    assert rc == 2
    # Valid JSON of the wrong SHAPE is equally unreadable (exit 2, not
    # an AttributeError traceback).
    for payload in ("[1, 2]", '{"findings": "oops"}'):
        bad = tmp_path / "bad.json"
        bad.write_text(payload)
        rc = run_cli([str(ok), "--no-env", "--baseline", str(bad)])
        assert rc == 2, payload


def test_checked_in_baseline_is_empty_and_loadable():
    """The repo baseline ships empty (the tree lints clean); the file
    exists so `make lint --baseline` never 404s and regenerating it is
    a reviewable diff."""
    from horovod_tpu.analysis.driver import load_baseline
    baseline = load_baseline(str(REPO / "scripts" /
                                 "hvdlint_baseline.json"))
    assert sum(baseline.values()) == 0


def test_repo_lints_clean():
    """The acceptance bar: hvdlint over horovod_tpu/ + examples/ with
    every rule enabled reports nothing (fixes + rationaled
    suppressions)."""
    findings = lint_paths([str(REPO / "horovod_tpu"),
                           str(REPO / "examples")], root=str(REPO))
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------- fingerprint verifier

class FakeKV:
    """Dict-backed stand-in for runner.rendezvous.KVClient."""

    def __init__(self, store):
        self.store = store

    def put(self, scope, key, value):
        self.store[f"{scope}/{key}"] = value

    def get(self, scope, key, timeout=0.0):
        return self.store.get(f"{scope}/{key}")

    def delete(self, scope, key):
        self.store.pop(f"{scope}/{key}", None)


def _pair(interval=2):
    store = {}
    v0 = FingerprintVerifier(FakeKV(store), 0, 2, "e1", interval=interval)
    v1 = FingerprintVerifier(FakeKV(store), 1, 2, "e1", interval=interval)
    return v0, v1


def test_verifier_identical_sequences_agree():
    v0, v1 = _pair()
    for i in range(8):
        v0.record(f"allreduce(shape=(2,))|name=t{i}")
        v1.record(f"allreduce(shape=(2,))|name=t{i}")
    # Each rank verifies peer checkpoints one interval behind its own
    # newest (see _checkpoint), so agreement trails by one interval.
    assert v0.last_agreed_index() == 6
    assert v1.last_agreed_index() == 6
    assert v0.divergence is None and v1.divergence is None


def test_verifier_skipped_call_named_with_index():
    v0, v1 = _pair()
    with pytest.raises(CollectiveDivergenceError) as ei:
        for i in range(8):
            v0.record(f"allreduce|name=t{i}")
            if i != 2:  # rank 1 silently skips call #2
                v1.record(f"allreduce|name=t{i}")
    msg = str(ei.value)
    assert "rank 0" in msg and "rank 1" in msg
    assert "first divergent call #2" in msg
    assert "t2" in msg and "t3" in msg
    assert "fingerprint" in msg


def test_verifier_shape_skew_detected():
    v0, v1 = _pair(interval=1)
    v0.record("allreduce(shape=(4,),dtype=float32)|name=g")
    v1.record("allreduce(shape=(8,),dtype=float32)|name=g")
    # Detection happens one checkpoint later (deterministic lag).
    with pytest.raises(CollectiveDivergenceError) as ei:
        v0.record("allreduce(shape=(4,),dtype=float32)|name=g2")
    msg = str(ei.value)
    assert "shape=(4,)" in msg and "shape=(8,)" in msg


def test_verifier_stall_context_names_lagging_rank():
    v0, v1 = _pair()
    for i in range(6):
        v0.record(f"a|t{i}")
    for i in range(2):
        v1.record(f"a|t{i}")
    ctx = v0.stall_context()
    assert "rank(s) [1]" in ctx
    assert "agree through call #2" in ctx


def test_verifier_stall_context_reports_divergence():
    v0, v1 = _pair()
    for i in range(2):
        v0.record(f"a|t{i}")
    v1.record("a|t0")
    v1.record("a|DIFFERENT")  # publishes a divergent checkpoint
    # The stalled survivor's watchdog context reads the freshest peer
    # checkpoints (no interval lag — the watchdog has time to spare)
    # and reports the divergence.
    ctx = v0.stall_context()
    assert "out of step" in ctx


def test_verifier_subset_process_set_not_divergent():
    """A subset-set collective is a separate sequence: rank 0 issuing
    extra calls on a [0]-only process set must NOT trip the world
    fingerprint (mirrors scenario_consistency_subset)."""
    v0, v1 = _pair()
    for i in range(8):
        v0.record(f"allreduce|name=t{i}")
        if i % 2 == 0:
            v0.record(f"allreduce(ps=1)|name=s{i}", ranks=[0],
                      group="ps1-abc")
        v1.record(f"allreduce|name=t{i}")
    assert v0.divergence is None and v1.divergence is None
    assert v0.last_agreed_index() == 6
    # The subset group has no peers for rank 0, so it trivially agrees
    # and never compares against rank 1.
    assert v0.last_agreed_index("ps1-abc") >= 0


def test_verifier_gc_waits_for_peer_acks():
    """GC must key off what peers ACKNOWLEDGED verifying, not this
    rank's own watermark — a lagging peer pauses GC instead of losing
    the fingerprints it still needs."""
    store = {}
    v0 = FingerprintVerifier(FakeKV(store), 0, 2, "e1", interval=1)
    v1 = FingerprintVerifier(FakeKV(store), 1, 2, "e1", interval=1)
    # Both keep pace: old keys get collected past the ack floor.
    for i in range(30):
        v0.record(f"a|t{i}")
        v1.record(f"a|t{i}")
    assert "checkfp/e1/world/fp/0/10" not in store  # GC'd
    assert "checkfp/e1/world/fp/0/25" in store      # recent, kept
    # Lagging peer: no acks beyond its progress → nothing GC'd.
    store2 = {}
    v0 = FingerprintVerifier(FakeKV(store2), 0, 2, "e1", interval=1)
    v1 = FingerprintVerifier(FakeKV(store2), 1, 2, "e1", interval=1)
    for i in range(3):
        v1.record(f"a|t{i}")
    for i in range(30):
        v0.record(f"a|t{i}")
    assert "checkfp/e1/world/fp/0/1" in store2  # still there for v1


def test_verifier_ring_catches_divergence_at_three_ranks():
    """Ring verification: any divergent rank differs from a ring
    neighbor, so adjacent-pair checks catch what all-pairs would."""
    store = {}
    vs = [FingerprintVerifier(FakeKV(store), r, 3, "e1", interval=2)
          for r in range(3)]
    with pytest.raises(CollectiveDivergenceError) as ei:
        for i in range(8):
            for r, v in enumerate(vs):
                if r == 1 and i == 2:
                    continue  # rank 1 skips a call
                v.record(f"a|t{i}")
    assert "rank 1" in str(ei.value)


def test_verifier_expired_window_not_counted_as_agreed():
    """A peer more than `window` calls behind: the lost compares are
    surfaced in stall_context, never silently folded into agreement."""
    store = {}
    v0 = FingerprintVerifier(FakeKV(store), 0, 2, "e1", interval=1,
                             window=1)
    v1 = FingerprintVerifier(FakeKV(store), 1, 2, "e1", interval=1,
                             window=1)
    for i in range(20):
        v0.record(f"a|t{i}")
    for i in range(20):
        v1.record(f"a|t{i}")
    # v1 verified v0 fine (v0's keys were all there); v0 catches up on
    # v1's checkpoints only now, after pruning its own early windows.
    ctx = v0.stall_context()
    assert "expired unverified" in ctx


def test_verifier_kv_outage_never_fails_the_collective():
    """A rendezvous-KV blip degrades the diagnostic, not training:
    record() must swallow KV transport failures entirely."""
    class DownKV:
        def put(self, *a, **k):
            raise OSError("connection refused")

        def get(self, *a, **k):
            raise OSError("connection refused")

        def delete(self, *a, **k):
            raise OSError("connection refused")

    v = FingerprintVerifier(DownKV(), 0, 2, "e1", interval=1)
    for i in range(5):
        v.record(f"a|t{i}")  # must not raise
    assert v.divergence is None


def test_verifier_metrics_exported():
    from horovod_tpu.observability import metrics as m
    v0, v1 = _pair()
    for i in range(4):
        v0.record(f"a|t{i}")
        v1.record(f"a|t{i}")
    snap = m.registry().snapshot()
    fams = snap["families"]
    assert "horovod_check_collectives_checkpoints_total" in fams


# ------------------------------------------- stall watchdog integration

def test_stall_watchdog_message_includes_fingerprint_context(monkeypatch):
    import time

    from horovod_tpu.analysis import verifier as vf
    from horovod_tpu.ops.collectives import StallWatchdog

    class FakeInspector:
        def submit(self, name):
            pass

        def done(self, name):
            pass

        def check(self):
            return ["allreduce.t3"], False

    class FakeVerifier:
        def stall_context(self):
            return ("collective fingerprints agree through call #40 of "
                    "44 issued here; rank(s) [1] have not published "
                    "checkpoint #42")

    monkeypatch.setattr(vf, "_verifier", FakeVerifier())
    wd = StallWatchdog(FakeInspector(), warn_sec=0.02, shutdown_sec=0.08,
                       poll_interval=0.01)
    with pytest.raises(HorovodInternalError) as ei:
        wd.guard("allreduce.t3", lambda: time.sleep(30))
    msg = str(ei.value)
    assert "stalled past" in msg
    assert "agree through call #40" in msg
    assert "rank(s) [1]" in msg


def test_stall_watchdog_message_without_verifier(monkeypatch):
    import time

    from horovod_tpu.analysis import verifier as vf
    from horovod_tpu.ops.collectives import StallWatchdog

    class FakeInspector:
        def submit(self, name):
            pass

        def done(self, name):
            pass

        def check(self):
            return [], False

    monkeypatch.setattr(vf, "_verifier", None)
    wd = StallWatchdog(FakeInspector(), warn_sec=0.02, shutdown_sec=0.08,
                       poll_interval=0.01)
    with pytest.raises(HorovodInternalError) as ei:
        wd.guard("x", lambda: time.sleep(30))
    assert "stalled past" in str(ei.value)
