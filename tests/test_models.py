"""Model zoo smoke + correctness tests (reference analog: the synthetic
benchmark models, examples/pytorch/pytorch_synthetic_benchmark.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import mlp, resnet
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel import MeshSpec, build_mesh


def test_mlp_trains():
    params = mlp.init(jax.random.PRNGKey(0), (16, 32, 4))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 4)
    loss0 = float(mlp.loss_fn(params, (x, y)))
    g = jax.grad(mlp.loss_fn)(params, (x, y))
    params = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, params, g)
    assert float(mlp.loss_fn(params, (x, y))) < loss0


def test_resnet50_forward_backward():
    params, stats = resnet.init(jax.random.PRNGKey(0), depth=50,
                                num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3), jnp.float32)
    y = jnp.asarray([1, 2])

    def loss(p):
        l, ns = resnet.loss_fn(p, stats, (x, y), depth=50, train=True)
        return l, ns

    (l, ns), g = jax.jit(jax.value_and_grad(loss, has_aux=True))(params)
    assert np.isfinite(float(l))
    # BN stats updated.
    assert float(jnp.abs(ns["stem"]["mean"]).sum()) > 0
    # Every param got a gradient.
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(x))) for x in leaves)


def test_resnet_eval_mode_uses_running_stats():
    params, stats = resnet.init(jax.random.PRNGKey(0), depth=50,
                                num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3), jnp.float32)
    logits, ns = resnet.apply(params, stats, x, depth=50, train=False)
    assert logits.shape == (2, 10)
    # Eval mode must not mutate stats.
    same = jax.tree_util.tree_map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)), stats, ns)
    assert all(jax.tree_util.tree_leaves(same))


def test_transformer_forward_shapes():
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, d_ff=64,
                                n_layers=2, max_seq=64)
    params = tfm.init(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(MeshSpec(), jax.devices()[:1])
    fwd = jax.jit(tfm.build_forward(cfg, mesh))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
    logits = fwd(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_transformer_flash_attention_matches_local():
    """attn='flash' (Pallas kernel, ops/flash_attention.py) must produce
    the same logits and gradients as the exact 'local' attention."""
    import jax.numpy as jnp
    mk = lambda attn: tfm.TransformerConfig(  # noqa: E731
        vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2, max_seq=64,
        attn=attn)
    params = tfm.init(jax.random.PRNGKey(0), mk("local"))
    mesh = build_mesh(MeshSpec(), jax.devices()[:1])
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)

    out = {}
    for attn in ("local", "flash"):
        fwd = jax.jit(tfm.build_forward(mk(attn), mesh))
        out[attn] = np.asarray(fwd(params, tokens))
    np.testing.assert_allclose(out["flash"], out["local"],
                               rtol=2e-4, atol=2e-4)

    grads = {}
    for attn in ("local", "flash"):
        cfg = mk(attn)
        fwd = tfm.build_forward(cfg, mesh)

        def loss(p):
            return jnp.mean(jnp.square(fwd(p, tokens)))
        grads[attn] = jax.jit(jax.grad(loss))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4),
        grads["flash"], grads["local"])


def test_graft_entry_hooks():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 3
    ge.dryrun_multichip(8)


def test_vgg16_forward_backward():
    """VGG-16 (reference headline scaling model, README.rst:108): fwd
    shapes and a gradient step at a small image size."""
    from horovod_tpu.models import vgg

    params = vgg.init(jax.random.PRNGKey(0), depth=16, num_classes=10,
                      dtype=jnp.float32, image_size=32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 32, 32, 3)),
                    jnp.float32)
    y = jnp.asarray([1, 7])
    logits = vgg.apply(params, x, depth=16)
    assert logits.shape == (2, 10)
    g = jax.grad(lambda p: vgg.loss_fn(p, (x, y), depth=16))(params)
    gn = sum(float(jnp.sum(jnp.abs(a)))
             for a in jax.tree_util.tree_leaves(g))
    assert np.isfinite(gn) and gn > 0
    # VGG-16 @224/1000 classes is the classic 138M-parameter model
    p224 = vgg.init(jax.random.PRNGKey(0), depth=16, num_classes=1000,
                    dtype=jnp.float32, image_size=224)
    n = sum(int(np.prod(a.shape)) for a in jax.tree_util.tree_leaves(p224))
    assert abs(n - 138_357_544) < 1e6, n


def test_inception_v3_forward_backward():
    """Inception V3 (THE reference headline model, README.rst:102): fwd
    shapes, param-count parity with the canonical model, BN stats
    update, gradient step."""
    from horovod_tpu.models import inception

    params, stats = inception.init(jax.random.PRNGKey(0), num_classes=1000,
                                   dtype=jnp.float32)
    n = sum(int(np.prod(a.shape))
            for a in jax.tree_util.tree_leaves(params))
    # torchvision inception_v3 (aux_logits excluded): 23,834,568
    assert abs(n - 23_834_568) < 5e5, n

    params, stats = inception.init(jax.random.PRNGKey(0), num_classes=7,
                                   dtype=jnp.float32)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 299, 299, 3)),
        jnp.float32)
    y = jnp.asarray([1, 4])
    # ONE 299x299 pass covers loss, gradients, logits path, and the BN
    # stats refresh (aux) — a separate apply() would double the test cost
    (l, ns), g = jax.value_and_grad(
        lambda p: inception.loss_fn(p, stats, (x, y)), has_aux=True)(params)
    assert np.isfinite(float(l))
    assert not np.allclose(np.asarray(ns["stem"]["c0"]["mean"]),
                           np.asarray(stats["stem"]["c0"]["mean"]))
    gn = sum(float(jnp.sum(jnp.abs(a)))
             for a in jax.tree_util.tree_leaves(g))
    assert np.isfinite(gn) and gn > 0
