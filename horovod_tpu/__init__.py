"""horovod_tpu: a TPU-native distributed training framework.

Brand-new framework with the capabilities of Horovod (reference:
dalian-ai/horovod), re-designed for TPU: collectives are XLA programs over a
`jax.sharding.Mesh` (ICI/DCN) instead of NCCL/MPI calls, fusion is trace-time
bucketing instead of a runtime staging buffer, and the response cache is a
compiled-executable cache. See SURVEY.md for the full design mapping.

Public API mirrors `horovod.torch` / `horovod.tensorflow`
(reference: horovod/torch/__init__.py, horovod/tensorflow/__init__.py).
"""

from horovod_tpu.common.compat import ensure_jax_api

ensure_jax_api()  # before any module builds a jit(shard_map(...)) program

from horovod_tpu.common.types import (  # noqa: F401, E402
    Adasum, Average, Max, Min, Product, ReduceOp, Status, Sum,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    CollectiveDivergenceError, DuplicateNameError, HorovodInternalError,
    HorovodTpuError, HostsUpdatedInterrupt, TensorShapeMismatchError,
    VersionMismatchError,
)
from horovod_tpu.core.topology import (  # noqa: F401
    ccl_built, cross_rank, cross_size, cuda_built, ddl_built, gloo_built,
    gloo_enabled, hybrid_mesh, init, is_homogeneous, is_initialized,
    local_rank, local_size, local_slot_ranks, mesh, mesh_spec, mpi_built,
    mpi_enabled, mpi_threads_supported, nccl_built, rank, rocm_built,
    shutdown, size, tpu_built,
)
from horovod_tpu.core.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, axis_process_set, get_process_set,
    global_process_set, remove_process_set,
)
from horovod_tpu.ops.collectives import (  # noqa: F401
    allgather, allgather_async, allreduce, allreduce_async, alltoall,
    alltoall_async, barrier, broadcast, broadcast_async,
    bucketed_allreduce, bucketed_allreduce_async, bucket_overlap_stats,
    grouped_allgather, grouped_allreduce, grouped_allreduce_async,
    grouped_reducescatter, poll, reducescatter, reducescatter_async,
    synchronize,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu.optim.optimizer import (  # noqa: F401
    DistributedOptimizer, DistributedGradientTransform,
)
from horovod_tpu.optim.functions import (  # noqa: F401
    broadcast_object, broadcast_optimizer_state, broadcast_parameters,
    broadcast_variables, allgather_object,
)
from horovod_tpu.core import join as _join_mod  # noqa: F401
from horovod_tpu.core.join import join  # noqa: F401
from horovod_tpu import elastic  # noqa: F401  (hvd.elastic.run / State)

__version__ = "0.1.0"

# hvdrace (analysis/race.py, docs/static_analysis.md): with
# HOROVOD_RACE_CHECK=1 the runtime's `# guarded-by:`-annotated classes
# are instrumented HERE, at import time — before any runtime instance
# exists — so every lock they create is tracked from birth. Without the
# env var nothing is imported or patched.
import os as _os  # noqa: E402

if _os.environ.get("HOROVOD_RACE_CHECK"):  # presence sniff: zero cost
    # when unset; race.env_enabled() owns the truthy-value parse.
    from horovod_tpu.analysis import race as _race
    _race.maybe_enable_from_env()


def metrics() -> dict:
    """This process's metrics registry as a plain-JSON snapshot
    (docs/observability.md has the catalog). Works before init();
    after init() the snapshot carries this process's rank."""
    from horovod_tpu.core import topology
    from horovod_tpu.observability import metrics as m
    return m.registry().snapshot(topology.rank_or_none())


def metrics_text() -> str:
    """This process's metrics in Prometheus text exposition format —
    what the rendezvous server's `/metrics` route serves job-wide."""
    from horovod_tpu.core import topology
    from horovod_tpu.observability import metrics as m
    return m.registry().render(topology.rank_or_none())


def perfscope():
    """The process-wide step-phase profiler (profiler/perfscope.py,
    docs/perf.md): delimit steps with `with hvd.perfscope().step():` and
    mark host input waits with `.phase("input_wait")`; comms, compile
    and optimizer time are attributed automatically through
    `DistributedOptimizer`. A no-op shell under HOROVOD_PERFSCOPE=0."""
    from horovod_tpu.profiler import perfscope as _ps
    return _ps.get()


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Start runtime timeline capture (reference: operations.cc:1077)."""
    from horovod_tpu.profiler.timeline import Timeline
    from horovod_tpu.core import topology
    st = topology.state()
    if st.timeline is None:
        st.timeline = Timeline(file_path, mark_cycles=mark_cycles)
    st.timeline.start()


def stop_timeline() -> None:
    """Stop timeline capture (reference: horovod_stop_timeline)."""
    from horovod_tpu.core import topology
    st = topology.state()
    if st.timeline is not None:
        st.timeline.stop()
