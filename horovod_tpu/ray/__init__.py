"""Ray orchestration (thin).

Reference: horovod/ray/runner.py RayExecutor (:168) — colocated actor
placement, Gloo rendezvous driven by a Coordinator actor (:45), and an
elastic variant (elastic_v2.py). The thin TPU integration maps one Ray
actor to one worker process; rendezvous is our KV server on the driver.

Import-gated: only needs ray when actually used.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


def _require_ray():
    try:
        import ray
        return ray
    except ImportError as e:
        raise ImportError("horovod_tpu.ray requires ray (reference extra: "
                          "horovod[ray])") from e


class RayExecutor:
    """Reference: RayExecutor (ray/runner.py:168) — start() creates the
    worker actors, run() executes a function on all of them, shutdown()
    tears down."""

    def __init__(self, num_workers: int,
                 cpus_per_worker: int = 1,
                 use_current_placement_group: bool = True,
                 placement_group_strategy: Optional[str] = None,
                 env_vars: Optional[dict] = None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        # "PACK"/"SPREAD"/"STRICT_PACK"/"STRICT_SPREAD" creates a fresh
        # placement group for the actors (reference: ray/runner.py
        # colocated placement groups); None schedules loose (or inside
        # the caller's current pg, which Ray applies by default).
        self.placement_group_strategy = placement_group_strategy
        self.use_current_placement_group = use_current_placement_group
        self.env_vars = dict(env_vars or {})
        self._actors: List[Any] = []
        self._rdv = None
        self._pg = None
        self._pg_ours = False  # created by us (per-rank bundles) vs
        # the caller's current placement group (no bundle pinning)

    def start(self) -> None:
        ray = _require_ray()

        from horovod_tpu.runner import secret as secret_mod
        from horovod_tpu.runner.launch import _local_ip
        from horovod_tpu.runner.rendezvous import RendezvousServer

        job_secret = secret_mod.make_secret_key()
        self.env_vars[secret_mod.SECRET_ENV] = job_secret
        self._rdv = RendezvousServer(secret=job_secret.encode())
        port = self._rdv.start()
        addr = _local_ip()

        @ray.remote(num_cpus=self.cpus_per_worker)
        class Worker:
            def __init__(self, rank: int, size: int, env: dict):
                import os
                os.environ.update(env)
                os.environ["HOROVOD_RANK"] = str(rank)
                os.environ["HOROVOD_SIZE"] = str(size)
                os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = addr
                os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)

            def execute(self, fn, *args, **kwargs):
                from horovod_tpu.runner.results import capture
                return capture(fn, *args, **kwargs)

        self._worker_cls = Worker
        if self.placement_group_strategy:
            self._pg = _maybe_placement_group(
                ray, self.num_workers, self.cpus_per_worker,
                self.placement_group_strategy)
            self._pg_ours = True
        elif self.use_current_placement_group:
            # Schedule inside the caller's placement group when one is
            # active (reference: RayExecutor use_current_placement_group).
            try:
                from ray.util import get_current_placement_group
                self._pg = get_current_placement_group()
            except (ImportError, AttributeError):
                self._pg = None
        self._actors = [self._make_actor(i) for i in range(self.num_workers)]

    def _make_actor(self, rank: int):
        cls = self._worker_cls
        if self._pg is not None:
            from ray.util.scheduling_strategies import \
                PlacementGroupSchedulingStrategy

            opts = {"placement_group": self._pg}
            if self._pg_ours:  # our pg has one bundle per rank
                opts["placement_group_bundle_index"] = rank
            cls = cls.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    **opts))
        return cls.remote(rank, self.num_workers, self.env_vars)

    def _collect(self, fn, args, kwargs):
        """Submit `fn` to every actor; gather (results, dead_ranks).

        Uses ray.wait so an actor DEATH is observed even while survivors
        are blocked inside a collective against the dead peer (peer death
        does not reliably surface as an error in the survivors — the same
        reality is_comm_failure handles in the elastic launcher path).
        Returns as soon as a death is seen; the caller decides whether to
        fail the job or restart the ring."""
        ray = _require_ray()

        from horovod_tpu.runner.results import PerRankResults
        futures = {a.execute.remote(fn, *args, **kwargs): rank
                   for rank, a in enumerate(self._actors)}
        collected = PerRankResults(self.num_workers)
        pending = list(futures)
        dead: List[int] = []
        while pending and not dead:
            done, pending = ray.wait(pending, num_returns=1)
            for fut in done:
                rank = futures[fut]
                try:
                    ok, payload = ray.get(fut)
                    collected.add(rank, ok, payload)
                except Exception:  # RayActorError — the actor process died
                    dead.append(rank)
        return collected, dead

    def _restart_ring(self) -> None:
        """Kill every actor and recreate the full ring: survivors may be
        blocked inside a collective against a dead peer and cannot accept
        new work (reference: elastic reset re-forms the whole Gloo ring)."""
        ray = _require_ray()
        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        old_n = self.num_workers
        self._resize_for_restart()
        if self._pg_ours and self.num_workers != old_n:
            # Bundle count must match the ring: recreate the placement
            # group at the new size (stale bundles would either reject
            # out-of-range bundle_index on grow or strand reservations
            # on shrink).
            try:
                from ray.util.placement_group import remove_placement_group
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = _maybe_placement_group(
                ray, self.num_workers, self.cpus_per_worker,
                self.placement_group_strategy)
        self._actors = [self._make_actor(i)
                        for i in range(self.num_workers)]

    def _resize_for_restart(self) -> None:
        """Hook: elastic subclass recomputes num_workers from discovery."""

    def run(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        """Execute `fn` on every worker; per-rank results in rank order.
        A failing rank raises RemoteJobError naming it with its remote
        traceback (reference: run_remote + ray.get surface task errors)."""
        from horovod_tpu.runner.results import RemoteJobError
        collected, dead = self._collect(fn, args, kwargs or {})
        if dead:
            self._restart_ring()  # unblock survivors; job has failed
            raise RemoteJobError(
                f"worker actor(s) for rank(s) {sorted(dead)} died "
                f"(preemption or crash); surviving workers were restarted")
        return collected.values()

    def execute_single(self, fn: Callable, rank: int = 0,
                       args=(), kwargs=None) -> Any:
        """Run `fn` on one worker (reference: RayExecutor.execute_single)."""
        ray = _require_ray()
        ok, payload = ray.get(
            self._actors[rank].execute.remote(fn, *(args or ()),
                                              **(kwargs or {})))
        if not ok:
            from horovod_tpu.runner.results import RemoteJobError
            raise RemoteJobError(f"rank {rank} failed:\n{payload}")
        return payload

    def shutdown(self) -> None:
        ray = _require_ray()
        for a in self._actors:
            ray.kill(a)
        self._actors = []
        if self._pg_ours and self._pg is not None:
            try:
                from ray.util.placement_group import remove_placement_group
                remove_placement_group(self._pg)
            except Exception:
                pass
        self._pg = None
        self._pg_ours = False
        if self._rdv is not None:
            self._rdv.stop()
            self._rdv = None


class ElasticRayExecutor(RayExecutor):
    """Elastic variant: dead actors are recreated and the function retried
    (reference: ray/elastic_v2.py — workers lost to preemption are
    restarted from the autoscaler pool within retry limits). State recovery
    rides the same hvd.elastic.run/State machinery as the launcher path."""

    def __init__(self, *args, max_restarts: int = 3,
                 discovery: Optional["RayHostDiscovery"] = None,
                 min_workers: int = 1,
                 max_workers: Optional[int] = None, **kwargs):
        super().__init__(*args, **kwargs)
        from horovod_tpu.runner.results import RestartPolicy
        self.policy = RestartPolicy(max_restarts=max_restarts)
        # With a discovery object the ring RESIZES on restart to what the
        # cluster currently offers (reference: elastic_v2's autoscaler-
        # driven host set), instead of insisting on the original size.
        self.discovery = discovery
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max_workers

    def _resize_for_restart(self) -> None:
        if self.discovery is None:
            return
        slots = sum(self.discovery.find_available_hosts_and_slots()
                    .values())
        if self.max_workers is not None:
            slots = min(slots, self.max_workers)
        if slots < self.min_workers:
            from horovod_tpu.runner.results import RemoteJobError
            raise RemoteJobError(
                f"cluster offers {slots} worker slots, below "
                f"min_workers={self.min_workers}")
        self.num_workers = slots

    def run(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        from horovod_tpu.runner.results import RemoteJobError
        kwargs = kwargs or {}
        while True:
            collected, dead = self._collect(fn, args, kwargs)
            if not dead:
                return collected.values()
            for rank in dead:
                if not self.policy.should_restart(rank):
                    raise RemoteJobError(
                        f"rank {rank} exceeded {self.policy.max_restarts} "
                        f"restarts (reference: elastic_v2 retry limits)")
                self.policy.record_restart(rank)
            # The whole ring restarts (survivors are blocked against the
            # dead peer); in-actor state recovers through the user's
            # hvd.elastic.State commit/restore like the launcher path.
            self._restart_ring()


class RayHostDiscovery:
    """Host/slot discovery from Ray's cluster state (reference:
    ray/elastic_v2.py:40 RayHostDiscovery over ray.nodes()).

    Slots per host = available CPUs // cpus_per_worker, optionally clamped
    by GPUs or TPUs per worker. The TPU resource key is the TPU-first
    addition: on Ray-on-GKE TPU pods each host advertises a "TPU"
    resource, so `tpus_per_worker=4` maps one worker per chip-group.
    Duck-typed to elastic.discovery.HostDiscovery so it drops into
    HostManager unchanged.
    """

    def __init__(self, use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: int = 1, tpus_per_worker: int = 0):
        self.use_gpu = use_gpu
        self.cpus_per_worker = max(1, int(cpus_per_worker))
        self.gpus_per_worker = max(1, int(gpus_per_worker))
        self.tpus_per_worker = int(tpus_per_worker)

    def find_available_hosts_and_slots(self) -> dict:
        ray = _require_ray()
        mapping: dict = {}
        for node in ray.nodes():
            if not node.get("alive"):
                continue
            res = node.get("Resources", {}) or {}
            slots = int(res.get("CPU", 0)) // self.cpus_per_worker
            if self.use_gpu:
                slots = min(slots,
                            int(res.get("GPU", 0)) // self.gpus_per_worker)
            if self.tpus_per_worker:
                slots = min(slots,
                            int(res.get("TPU", 0)) // self.tpus_per_worker)
            if slots > 0:
                mapping[node["NodeManagerAddress"]] = int(slots)
        return mapping


def _maybe_placement_group(ray, num_workers: int, cpus_per_worker: int,
                           strategy: str):
    """Create (pg, ready) for colocated scheduling (reference:
    ray/runner.py create_placement_group usage in RayExecutor.start)."""
    from ray.util.placement_group import placement_group

    bundles = [{"CPU": cpus_per_worker} for _ in range(num_workers)]
    pg = placement_group(bundles, strategy=strategy)
    ray.get(pg.ready())
    return pg
