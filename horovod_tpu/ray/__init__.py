"""Ray orchestration (thin).

Reference: horovod/ray/runner.py RayExecutor (:168) — colocated actor
placement, Gloo rendezvous driven by a Coordinator actor (:45), and an
elastic variant (elastic_v2.py). The thin TPU integration maps one Ray
actor to one worker process; rendezvous is our KV server on the driver.

Import-gated: only needs ray when actually used.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


def _require_ray():
    try:
        import ray
        return ray
    except ImportError as e:
        raise ImportError("horovod_tpu.ray requires ray (reference extra: "
                          "horovod[ray])") from e


class RayExecutor:
    """Reference: RayExecutor (ray/runner.py:168) — start() creates the
    worker actors, run() executes a function on all of them, shutdown()
    tears down."""

    def __init__(self, num_workers: int,
                 cpus_per_worker: int = 1,
                 use_current_placement_group: bool = True,
                 env_vars: Optional[dict] = None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.env_vars = dict(env_vars or {})
        self._actors: List[Any] = []
        self._rdv = None

    def start(self) -> None:
        ray = _require_ray()

        from horovod_tpu.runner.launch import _local_ip
        from horovod_tpu.runner.rendezvous import RendezvousServer

        self._rdv = RendezvousServer()
        port = self._rdv.start()
        addr = _local_ip()

        @ray.remote(num_cpus=self.cpus_per_worker)
        class Worker:
            def __init__(self, rank: int, size: int, env: dict):
                import os
                os.environ.update(env)
                os.environ["HOROVOD_RANK"] = str(rank)
                os.environ["HOROVOD_SIZE"] = str(size)
                os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = addr
                os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)

            def execute(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

        self._actors = [Worker.remote(i, self.num_workers, self.env_vars)
                        for i in range(self.num_workers)]

    def run(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        ray = _require_ray()
        kwargs = kwargs or {}
        return ray.get([a.execute.remote(fn, *args, **kwargs)
                        for a in self._actors])

    def shutdown(self) -> None:
        ray = _require_ray()
        for a in self._actors:
            ray.kill(a)
        self._actors = []
        if self._rdv is not None:
            self._rdv.stop()
            self._rdv = None
