"""Ray orchestration (thin).

Reference: horovod/ray/runner.py RayExecutor (:168) — colocated actor
placement, Gloo rendezvous driven by a Coordinator actor (:45), and an
elastic variant (elastic_v2.py). The thin TPU integration maps one Ray
actor to one worker process; rendezvous is our KV server on the driver.

Import-gated: only needs ray when actually used.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


def _require_ray():
    try:
        import ray
        return ray
    except ImportError as e:
        raise ImportError("horovod_tpu.ray requires ray (reference extra: "
                          "horovod[ray])") from e


class RayExecutor:
    """Reference: RayExecutor (ray/runner.py:168) — start() creates the
    worker actors, run() executes a function on all of them, shutdown()
    tears down."""

    def __init__(self, num_workers: int,
                 cpus_per_worker: int = 1,
                 use_current_placement_group: bool = True,
                 env_vars: Optional[dict] = None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.env_vars = dict(env_vars or {})
        self._actors: List[Any] = []
        self._rdv = None

    def start(self) -> None:
        ray = _require_ray()

        from horovod_tpu.runner import secret as secret_mod
        from horovod_tpu.runner.launch import _local_ip
        from horovod_tpu.runner.rendezvous import RendezvousServer

        job_secret = secret_mod.make_secret_key()
        self.env_vars[secret_mod.SECRET_ENV] = job_secret
        self._rdv = RendezvousServer(secret=job_secret.encode())
        port = self._rdv.start()
        addr = _local_ip()

        @ray.remote(num_cpus=self.cpus_per_worker)
        class Worker:
            def __init__(self, rank: int, size: int, env: dict):
                import os
                os.environ.update(env)
                os.environ["HOROVOD_RANK"] = str(rank)
                os.environ["HOROVOD_SIZE"] = str(size)
                os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = addr
                os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)

            def execute(self, fn, *args, **kwargs):
                from horovod_tpu.runner.results import capture
                return capture(fn, *args, **kwargs)

        self._worker_cls = Worker
        self._actors = [Worker.remote(i, self.num_workers, self.env_vars)
                        for i in range(self.num_workers)]

    def _collect(self, fn, args, kwargs):
        """Submit `fn` to every actor; gather (results, dead_ranks).

        Uses ray.wait so an actor DEATH is observed even while survivors
        are blocked inside a collective against the dead peer (peer death
        does not reliably surface as an error in the survivors — the same
        reality is_comm_failure handles in the elastic launcher path).
        Returns as soon as a death is seen; the caller decides whether to
        fail the job or restart the ring."""
        ray = _require_ray()

        from horovod_tpu.runner.results import PerRankResults
        futures = {a.execute.remote(fn, *args, **kwargs): rank
                   for rank, a in enumerate(self._actors)}
        collected = PerRankResults(self.num_workers)
        pending = list(futures)
        dead: List[int] = []
        while pending and not dead:
            done, pending = ray.wait(pending, num_returns=1)
            for fut in done:
                rank = futures[fut]
                try:
                    ok, payload = ray.get(fut)
                    collected.add(rank, ok, payload)
                except Exception:  # RayActorError — the actor process died
                    dead.append(rank)
        return collected, dead

    def _restart_ring(self) -> None:
        """Kill every actor and recreate the full ring: survivors may be
        blocked inside a collective against a dead peer and cannot accept
        new work (reference: elastic reset re-forms the whole Gloo ring)."""
        ray = _require_ray()
        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        self._actors = [self._worker_cls.remote(i, self.num_workers,
                                                self.env_vars)
                        for i in range(self.num_workers)]

    def run(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        """Execute `fn` on every worker; per-rank results in rank order.
        A failing rank raises RemoteJobError naming it with its remote
        traceback (reference: run_remote + ray.get surface task errors)."""
        from horovod_tpu.runner.results import RemoteJobError
        collected, dead = self._collect(fn, args, kwargs or {})
        if dead:
            self._restart_ring()  # unblock survivors; job has failed
            raise RemoteJobError(
                f"worker actor(s) for rank(s) {sorted(dead)} died "
                f"(preemption or crash); surviving workers were restarted")
        return collected.values()

    def execute_single(self, fn: Callable, rank: int = 0,
                       args=(), kwargs=None) -> Any:
        """Run `fn` on one worker (reference: RayExecutor.execute_single)."""
        ray = _require_ray()
        ok, payload = ray.get(
            self._actors[rank].execute.remote(fn, *(args or ()),
                                              **(kwargs or {})))
        if not ok:
            from horovod_tpu.runner.results import RemoteJobError
            raise RemoteJobError(f"rank {rank} failed:\n{payload}")
        return payload

    def shutdown(self) -> None:
        ray = _require_ray()
        for a in self._actors:
            ray.kill(a)
        self._actors = []
        if self._rdv is not None:
            self._rdv.stop()
            self._rdv = None


class ElasticRayExecutor(RayExecutor):
    """Elastic variant: dead actors are recreated and the function retried
    (reference: ray/elastic_v2.py — workers lost to preemption are
    restarted from the autoscaler pool within retry limits). State recovery
    rides the same hvd.elastic.run/State machinery as the launcher path."""

    def __init__(self, *args, max_restarts: int = 3, **kwargs):
        super().__init__(*args, **kwargs)
        from horovod_tpu.runner.results import RestartPolicy
        self.policy = RestartPolicy(max_restarts=max_restarts)

    def run(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        from horovod_tpu.runner.results import RemoteJobError
        kwargs = kwargs or {}
        while True:
            collected, dead = self._collect(fn, args, kwargs)
            if not dead:
                return collected.values()
            for rank in dead:
                if not self.policy.should_restart(rank):
                    raise RemoteJobError(
                        f"rank {rank} exceeded {self.policy.max_restarts} "
                        f"restarts (reference: elastic_v2 retry limits)")
                self.policy.record_restart(rank)
            # The whole ring restarts (survivors are blocked against the
            # dead peer); in-actor state recovers through the user's
            # hvd.elastic.State commit/restore like the launcher path.
            self._restart_ring()
