"""Cross-rank collective fingerprint verifier (HOROVOD_CHECK_COLLECTIVES).

The static rules in this package catch divergence patterns *before*
launch; this is the cheap runtime companion for the ones they can't
see (data-dependent control flow, config skew). With
``HOROVOD_CHECK_COLLECTIVES=1`` every rank hashes its rolling sequence
of ``(op, name, shape, dtype, process_set)`` tuples at the dispatch
choke point in ``ops/collectives.py`` and, every
``HOROVOD_CHECK_COLLECTIVES_INTERVAL`` calls, publishes the fingerprint
to the launcher's rendezvous KV and compares its ring successor's
already-published checkpoints (see _GroupState: adjacent-pair equality
is enough, and it keeps KV load at O(1) per rank per interval). A
divergent rank therefore raises an actionable
:class:`CollectiveDivergenceError` — naming the rank, the call index,
both fingerprints, and (from a retained window of recent call
descriptors) the first divergent call — instead of tripping the PR 1
stall watchdog blind.

Sequences are scoped PER PROCESS SET, exactly like the consistency
checker (core/consistency.py): only member ranks dispatch collectives
on a subset set, so each set carries its own call-order contract —
fingerprinting them into one global sequence would declare a correct
program divergent the first time a subset collective ran.

Contrast with ``core/consistency.py`` (HOROVOD_CONSISTENCY_CHECK):
that is a *synchronous* per-call agreement round (two KV combines per
collective, needs the native KV server). This verifier is asymptotically
free — one hash update per call, a few small KV ops per interval, no
barrier — so it can stay on for production jobs, at the cost of
detection lagging up to two intervals behind the divergence.

When the stall watchdog fires while the verifier is active, its
``stall_context()`` is appended to the ``HorovodInternalError`` so the
operator sees *which* rank fell out of step and where, not just that a
timeout elapsed.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.common.exceptions import CollectiveDivergenceError

#: Rendezvous-KV scope all verifier keys live under.
SCOPE = "checkfp"

#: Checkpoints kept behind the cluster-wide acknowledged watermark
#: before this rank garbage-collects its own KV keys.
_GC_LAG = 8

_verifier: Optional["FingerprintVerifier"] = None
_init_count = 0


class _GroupState:
    """One process set's rolling fingerprint + cross-check bookkeeping.

    Verification is a RING, not all-pairs: each member verifies only
    its successor among the group's members. If any two ranks' call
    sequences differ, some adjacent pair along the ring differs
    (equality is transitive), so the divergent rank is still caught —
    at O(1) KV reads per checkpoint per rank instead of O(size),
    which is what keeps the verifier production-viable at 256+ ranks
    against a single rendezvous server.
    """

    __slots__ = ("members", "peers", "readers", "calls", "rolling",
                 "pending", "segments", "next_verify", "last_agreed",
                 "oldest_kept", "skipped")

    def __init__(self, members: Tuple[int, ...], rank: int,
                 interval: int) -> None:
        self.members = members
        pos = members.index(rank)
        succ = members[(pos + 1) % len(members)]
        pred = members[(pos - 1) % len(members)]
        # Whom this rank verifies, and who verifies (reads) this rank —
        # the GC floor follows the READERS' acks, since they are the
        # ones still needing our keys.
        self.peers = (succ,) if succ != rank else ()
        self.readers = (pred,) if pred != rank else ()
        self.calls = 0
        self.rolling = hashlib.sha256()
        self.pending: List[str] = []
        # checkpoint idx -> (fingerprint hex, [desc per call in the
        # preceding interval]); pruned to ~window calls.
        self.segments: Dict[int, Tuple[str, List[str]]] = {}
        # next checkpoint index to verify, per peer.
        self.next_verify: Dict[int, int] = {p: interval for p in self.peers}
        # newest checkpoint this rank has verified against every peer.
        self.last_agreed = 0
        # oldest own checkpoint whose KV keys have not been GC'd yet.
        self.oldest_kept = interval
        # checkpoints that could no longer be compared because our
        # retained window had already been pruned (peer > window calls
        # behind) — surfaced in stall_context, never counted as agreed
        # silently.
        self.skipped = 0


class FingerprintVerifier:
    """Rolling per-process-set fingerprints with periodic KV cross-checks.

    ``record()`` is the hot path: a sha256 update and a list append
    under a short lock. KV traffic happens only at checkpoint
    boundaries, outside the lock, and peer reads are single-attempt
    (non-blocking): a peer that has not published yet is the stall
    watchdog's problem, not a reason to stall *this* rank.
    """

    def __init__(self, kv, rank: int, size: int, epoch: str,
                 interval: int = 10, window: int = 512,
                 diagnose_timeout: float = 5.0) -> None:
        self._kv = kv
        self.rank = rank
        self.size = size
        self.interval = max(1, interval)
        self.window = max(self.interval, window)
        self.diagnose_timeout = diagnose_timeout
        self._pfx = f"{epoch}"
        self._lock = threading.Lock()
        self._groups: Dict[str, _GroupState] = {}  # guarded-by: _lock
        # Serializes cross-check bookkeeping (next_verify / last_agreed
        # / oldest_kept walks) between the dispatch thread's checkpoint
        # path and the stall watchdog's stall_context() probe. Distinct
        # from _lock: KV reads happen under it, and the record() hot
        # path must never wait on the network.
        self._check_lock = threading.Lock()
        self.divergence: Optional[str] = None
        self._kv_down_logged = False
        self._mx_cache = None

    # ----------------------------------------------------------- metrics
    def _mx(self):
        from horovod_tpu.observability import metrics as m
        reg = m.registry()
        if self._mx_cache is None or self._mx_cache[0] is not reg:
            self._mx_cache = (reg, {
                "checkpoints": reg.counter(
                    "horovod_check_collectives_checkpoints_total",
                    "Fingerprint checkpoints published"),
                "agreed": reg.gauge(
                    "horovod_check_collectives_last_agreed_index",
                    "Newest call index all ranks' fingerprints agree on",
                    labelnames=("group",)),
                "mismatch": reg.counter(
                    "horovod_check_collectives_mismatches_total",
                    "Cross-rank fingerprint mismatches detected"),
            })
        return self._mx_cache[1]

    def last_agreed_index(self, group: str = "world") -> int:
        """Newest call index of `group` verified against every peer."""
        with self._lock:
            gs = self._groups.get(group)
            return gs.last_agreed if gs is not None else 0

    # ------------------------------------------------------------- record
    def record(self, desc: str, ranks: Optional[Sequence[int]] = None,
               group: str = "world") -> None:
        """Fold one dispatched collective into `group`'s fingerprint.

        `desc` is the full call descriptor
        ``op(signature)|name=...``; `ranks` are the process set's member
        ranks (None ⇒ the whole world), the same scoping the
        consistency checker uses. Raises CollectiveDivergenceError when
        a checkpoint cross-check catches a peer whose fingerprint for
        this group differs.
        """
        members: Tuple[int, ...] = (tuple(ranks) if ranks is not None
                                    else tuple(range(self.size)))
        if self.rank not in members:
            return  # defensive: non-members never dispatch on the set
        with self._lock:
            gs = self._groups.get(group)
            if gs is None:
                gs = _GroupState(members, self.rank, self.interval)
                self._groups[group] = gs
            gs.rolling.update(desc.encode("utf-8"))
            gs.rolling.update(b"\x00")
            gs.pending.append(desc)
            gs.calls += 1
            if gs.calls % self.interval:
                return
            idx = gs.calls
            fp = gs.rolling.hexdigest()
            gs.segments[idx] = (fp, gs.pending)
            gs.pending = []
            # Prune retained segments beyond the window (plus slack for
            # peers lagging up to the GC horizon).
            horizon = idx - max(self.window, _GC_LAG * self.interval)
            for old in [i for i in gs.segments if i <= horizon]:
                del gs.segments[old]
        self._checkpoint(group, gs, idx, fp)

    # --------------------------------------------------------- checkpoint
    def _key(self, group: str, kind: str, rank: int, idx: int) -> str:
        return f"{self._pfx}/{group}/{kind}/{rank}/{idx}"

    def _ack_key(self, group: str, rank: int) -> str:
        return f"{self._pfx}/{group}/ack/{rank}"

    def _checkpoint(self, group: str, gs: _GroupState, idx: int,
                    fp: str) -> None:
        """Publish checkpoint `idx`, then verify peer checkpoints at
        least one interval OLDER (single-attempt reads).

        The one-interval lag is what makes detection deterministic and
        hang-free on synchronous backends: by the time this rank records
        call `idx` it has completed collective `idx-1`, which required
        every group member to have *dispatched* its own call `idx-1` —
        so every member's checkpoint `idx - interval` is already
        published. Comparing the same-index checkpoint instead would
        race: the first rank to detect would stop dispatching while a
        peer still has an unpaired collective in flight, turning a clean
        diagnosis back into the stall it was meant to prevent.
        """
        with self._lock:
            segment = gs.segments.get(idx, (fp, []))[1]
        # A rendezvous-KV blip must degrade the DIAGNOSTIC, never fail
        # the training step it rides on: skip the checkpoint (peers see
        # a missing fingerprint and simply stop advancing at it).
        try:
            self._kv.put(SCOPE, self._key(group, "fp", self.rank, idx),
                         fp.encode("ascii"))
            self._kv.put(SCOPE, self._key(group, "win", self.rank, idx),
                         json.dumps(segment).encode("utf-8"))
        except Exception as e:
            self._kv_trouble(f"checkpoint publish failed: {e}")
            return
        self._mx()["checkpoints"].inc()
        self._verify_available(group, gs, upto=idx - self.interval)

    def _kv_trouble(self, what: str) -> None:
        if self._kv_down_logged:
            return
        self._kv_down_logged = True
        try:
            from horovod_tpu.common.hvd_logging import get_logger
            get_logger().warning(
                "HOROVOD_CHECK_COLLECTIVES: rendezvous KV unavailable "
                "(%s); fingerprint cross-checking degraded until it "
                "recovers", what)
        except Exception:
            pass

    def _peer_fp(self, group: str, peer: int, j: int,
                 timeout: float) -> Optional[bytes]:
        """One peer fingerprint read; transport trouble reads as
        'not published yet' rather than failing the collective."""
        try:
            v = self._kv.get(SCOPE, self._key(group, "fp", peer, j),
                             timeout=timeout)
            self._kv_down_logged = False
            return v
        except Exception as e:
            self._kv_trouble(f"fingerprint read failed: {e}")
            return None

    def _verify_available(self, group: str, gs: _GroupState, upto: int,
                          peer_timeout: float = 0.0) -> None:
        """Compare every peer checkpoint published so far (≤ `upto`)
        against ours; advance the agreement watermark or raise.

        Serialized by _check_lock: the stall watchdog thread probes the
        same per-group bookkeeping via stall_context() while the
        dispatch thread checkpoints."""
        with self._check_lock:
            for peer in gs.peers:
                while gs.next_verify[peer] <= upto:
                    j = gs.next_verify[peer]
                    theirs = self._peer_fp(group, peer, j, peer_timeout)
                    if theirs is None:
                        break  # peer not there yet — never block on it
                    with self._lock:
                        seg = gs.segments.get(j)
                    if seg is None:
                        # Our window for j was pruned (peer is >window
                        # calls behind): the compare is lost forever.
                        # Advance (nothing left to hold for) but count
                        # it — these calls are NOT agreed, and
                        # stall_context says so.
                        gs.skipped += 1
                    elif theirs.decode("ascii") != seg[0]:
                        self._mx()["mismatch"].inc()
                        self._raise_divergence(group, gs, peer, j,
                                               seg[0],
                                               theirs.decode("ascii"))
                    gs.next_verify[peer] = j + self.interval
            agreed = min((v - self.interval
                          for v in gs.next_verify.values()),
                         default=upto)
            if agreed > gs.last_agreed:
                gs.last_agreed = agreed
                self._mx()["agreed"].labels(group=group).set(agreed)
                # Publish how far WE have verified, so peers can GC
                # keys we no longer need (and vice versa).
                try:
                    self._kv.put(SCOPE, self._ack_key(group, self.rank),
                                 str(agreed).encode("ascii"))
                except Exception:
                    pass
                self._gc(group, gs)

    def _gc(self, group: str, gs: _GroupState) -> None:
        """Drop this rank's own KV keys below the watermark every peer
        has ACKNOWLEDGED verifying (their published ack), minus slack.

        This rank's own `last_agreed` says nothing about how far peers
        have read — GC keyed on it alone could delete fingerprints a
        lagging peer still needs, silently disabling its cross-checks.
        Missing acks simply pause GC; correctness never depends on it.
        """
        floor = gs.last_agreed
        try:
            for reader in gs.readers:
                raw = self._kv.get(SCOPE, self._ack_key(group, reader),
                                   timeout=0.0)
                if raw is None:
                    return  # our reader hasn't verified anything yet
                floor = min(floor, int(raw.decode("ascii")))
        except Exception:
            return  # GC is best-effort; never fail a collective on it
        floor -= _GC_LAG * self.interval
        while gs.oldest_kept <= floor:
            idx = gs.oldest_kept
            try:
                self._kv.delete(SCOPE,
                                self._key(group, "fp", self.rank, idx))
                self._kv.delete(SCOPE,
                                self._key(group, "win", self.rank, idx))
            except Exception:
                return
            gs.oldest_kept = idx + self.interval

    # --------------------------------------------------------- divergence
    def _first_divergent(self, group: str, gs: _GroupState, peer: int,
                         idx: int) -> Optional[Tuple[int, str, str]]:
        """(call index, our desc, their desc) of the first differing
        call in checkpoint `idx`'s window, if the peer's window segment
        is still fetchable."""
        raw = self._kv.get(SCOPE, self._key(group, "win", peer, idx),
                           timeout=self.diagnose_timeout)
        if raw is None:
            return None
        try:
            their_seg = json.loads(raw.decode("utf-8"))
        except ValueError:
            return None
        with self._lock:
            seg = gs.segments.get(idx)
        our_seg = seg[1] if seg is not None else []
        base = idx - self.interval
        for off in range(max(len(our_seg), len(their_seg))):
            mine = our_seg[off] if off < len(our_seg) else "<no call>"
            theirs = their_seg[off] if off < len(their_seg) else "<no call>"
            if mine != theirs:
                return base + off, mine, theirs
        return None

    def _raise_divergence(self, group: str, gs: _GroupState, peer: int,
                          idx: int, ours: str, theirs: str) -> None:
        detail = ""
        div = self._first_divergent(group, gs, peer, idx)
        if div is not None:
            call_idx, mine, their_desc = div
            detail = (f"; first divergent call #{call_idx}: rank "
                      f"{self.rank} issued '{mine}', rank {peer} "
                      f"issued '{their_desc}'")
        where = "" if group == "world" else f" on process set '{group}'"
        msg = (
            f"cross-rank collective divergence detected by "
            f"HOROVOD_CHECK_COLLECTIVES{where}: rank {peer} is out of "
            f"step with rank {self.rank} at call #{idx} — fingerprint "
            f"{ours[:16]} (rank {self.rank}) != {theirs[:16]} "
            f"(rank {peer}); last agreed checkpoint call "
            f"#{gs.last_agreed}{detail}. Every rank must issue the "
            f"same collectives in the same order (run "
            f"'python -m horovod_tpu.analysis' on the training script "
            f"to find the rank-dependent call)")
        self.divergence = msg
        # Divergence is a flight-dump trigger: every rank's ring holds
        # the exact call sequence that disagreed, and the doctor can
        # merge the dumps into the full cross-rank story
        # (observability/flight.py; never let a broken dump mask the
        # divergence itself).
        try:
            from horovod_tpu.observability import flight as _fl
            _fl.record("divergence", msg)
            _fl.dump("divergence")
            msg += _fl.dump_hint()
        except Exception:
            pass
        raise CollectiveDivergenceError(msg)

    # -------------------------------------------------------------- stall
    def stall_context(self) -> str:
        """One-line diagnosis for the stall watchdog: who is behind or
        divergent, as of the freshest KV state (bounded, best-effort
        reads — the watchdog has seconds to spare, the hot path does
        not)."""
        if self.divergence is not None:
            return self.divergence
        with self._lock:
            groups = list(self._groups.items())
        parts: List[str] = []
        for group, gs in groups:
            with self._lock:
                calls = gs.calls
            try:
                self._verify_available(
                    group, gs, upto=calls - (calls % self.interval),
                    peer_timeout=min(1.0, self.diagnose_timeout))
            except CollectiveDivergenceError as e:
                return str(e)
            except Exception:
                pass
            lagging = [p for p, nxt in gs.next_verify.items()
                       if nxt + self.interval <= calls]
            tag = "" if group == "world" else f" [{group}]"
            base = (f"collective fingerprints{tag} agree through call "
                    f"#{gs.last_agreed} of {calls} issued here")
            if gs.skipped:
                base += (f" ({gs.skipped} checkpoint(s) expired "
                         f"unverified — a peer fell more than "
                         f"{self.window} calls behind)")
            if lagging:
                parts.append(
                    f"{base}; rank(s) {sorted(lagging)} have not "
                    f"published checkpoint "
                    f"#{min(gs.next_verify[p] for p in lagging)} — "
                    f"likely a missing or extra collective on those "
                    f"ranks")
            else:
                parts.append(f"{base}; no peer checkpoint disagrees yet")
        return "; ".join(parts) if parts else \
            "no collectives fingerprinted yet"

    def close(self) -> None:
        pass  # KVClient holds no persistent connection


# ------------------------------------------------------------- process api

def maybe_init(cfg, rank: int, size: int
               ) -> Optional[FingerprintVerifier]:
    """Build the process-wide verifier from launcher-injected env.

    Needs the launcher rendezvous KV (HOROVOD_GLOO_RENDEZVOUS_ADDR /
    _PORT); logs and disables otherwise — unlike the consistency
    checker it has no native-KV dependency.
    """
    global _verifier, _init_count
    if _verifier is not None:
        return _verifier
    if size <= 1:
        return None
    from horovod_tpu.common.hvd_logging import get_logger
    if not cfg.rendezvous_addr or not cfg.rendezvous_port:
        get_logger().warning(
            "HOROVOD_CHECK_COLLECTIVES=1 but no rendezvous KV address "
            "was injected (manual launch?); fingerprint verification "
            "disabled")
        return None
    from horovod_tpu.common.resilience import RetryPolicy
    from horovod_tpu.runner.rendezvous import KVClient
    # Single-attempt, tightly-bounded transport: verifier KV traffic
    # rides the collective dispatch path, so a rendezvous blip must
    # cost at most ~2s once — not the KV retry policy's 30s deadline
    # per op. Failures degrade the diagnostic (see _kv_trouble), so
    # retrying is the server's problem, not ours.
    kv = KVClient(cfg.rendezvous_addr, cfg.rendezvous_port,
                  retry_policy=RetryPolicy(max_attempts=1),
                  request_timeout=2.0)
    _init_count += 1
    round_env = os.environ.get("HOROVOD_ELASTIC_ROUND")
    # Same epoch rule as core/consistency.py: the launcher-assigned
    # elastic round is rank-agreed across survivors and joiners; in a
    # static launch every rank's Nth init() pairs under the SPMD
    # contract.
    epoch = f"r{round_env}" if round_env else f"i{_init_count}"
    _verifier = FingerprintVerifier(
        kv, rank, size, epoch,
        interval=cfg.check_collectives_interval,
        window=cfg.check_collectives_window,
        diagnose_timeout=cfg.check_collectives_timeout)
    get_logger().info(
        "collective fingerprint verifier active (interval=%d calls, "
        "window=%d)", _verifier.interval, _verifier.window)
    return _verifier


def get() -> Optional[FingerprintVerifier]:
    return _verifier


def reset() -> None:
    global _verifier
    if _verifier is not None:
        _verifier.close()
    _verifier = None


def stall_context() -> str:
    """Empty string when inactive; the watchdog appends this verbatim."""
    v = _verifier
    if v is None:
        return ""
    try:
        return "; " + v.stall_context()
    except Exception:
        return ""
