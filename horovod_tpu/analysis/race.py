"""hvdrace: runtime lockset race detector enforcing ``# guarded-by:``.

HVD101 checks the ``# guarded-by: <lock>`` convention *lexically* — an
annotation whose lock is never actually held at runtime still passes
lint, and a lock handed through a helper is invisible to it. This
module closes the loop at runtime, following the Eraser lockset
algorithm (Savage et al., SOSP '97) specialized by the annotations:
instead of inferring candidate locksets, the annotation *declares* the
required lock, so the detector only has to answer "was the declared
lock held by this thread when the guarded attribute was touched?".

Enabled by ``HOROVOD_RACE_CHECK=1`` (read at ``horovod_tpu`` import
time), the detector:

* parses the runtime modules' ``# guarded-by:`` annotations with the
  same extractor HVD101 uses (``concurrency_rules._collect_annotations``)
  and binds each to its enclosing class;
* instruments those classes: ``__getattribute__``/``__setattr__`` hooks
  observe every touch of a guarded attribute, and ``threading.Lock`` /
  ``RLock`` objects stored under a declared lock name are wrapped in
  :class:`TrackedLock` so each thread's held-lock set is known;
* applies Eraser's ownership state machine per (object, attribute):
  the first accessing thread owns the state silently (``__init__`` and
  single-threaded use never report); the moment a second thread
  touches it, every access without the declared lock produces a
  :class:`RaceReport` naming the attribute, the declared lock, the
  current thread+stack and the previous conflicting access;
* honors the lexical suppression grammar at runtime: an access line
  carrying ``hvdlint: disable=HVD101 -- rationale`` (the
  double-checked-locking reads in observability/metrics.py) never
  reports;
* flags *stale* annotations — attributes touched from a second thread
  (provably past creation) while their declared lock was never once
  held — via :func:`stale_annotations`;
* feeds ``hvdrace_reports_total{site}`` into the PR 2 metrics registry.

``HOROVOD_RACE_CHECK_FAIL=1`` promotes each report to an immediate
:class:`RaceError`; ``HOROVOD_RACE_CHECK_MAX_REPORTS`` caps retained
reports (per site AND total). ``make race`` runs the concurrency/hammer
suites under the detector with reports promoted to test failures
(tests/conftest.py drains after every test).

Overhead exists only when enabled: without ``HOROVOD_RACE_CHECK=1`` no
class is ever instrumented and the runtime is byte-for-byte untouched.
"""

from __future__ import annotations

import dataclasses
import linecache
import os
import sys
import threading
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

HOROVOD_RACE_CHECK = "HOROVOD_RACE_CHECK"
HOROVOD_RACE_CHECK_FAIL = "HOROVOD_RACE_CHECK_FAIL"
HOROVOD_RACE_CHECK_MAX_REPORTS = "HOROVOD_RACE_CHECK_MAX_REPORTS"

#: Runtime modules scanned for ``# guarded-by:`` annotations when the
#: detector is enabled — the multithreaded coordination core.
DEFAULT_MODULES: Tuple[str, ...] = (
    "horovod_tpu.profiler.timeline",
    "horovod_tpu.profiler.perfscope",
    "horovod_tpu.observability.metrics",
    "horovod_tpu.observability.flight",
    "horovod_tpu.observability.tracing",
    "horovod_tpu.observability.watch",
    "horovod_tpu.elastic.driver",
    "horovod_tpu.runner.rendezvous",
    "horovod_tpu.runner.kv_ha",
    "horovod_tpu.analysis.verifier",
    "horovod_tpu.core.topology",
    "horovod_tpu.core.process_sets",
    "horovod_tpu.serve.batching",
    "horovod_tpu.serve.pool",
    "horovod_tpu.ckpt.async_ckpt",
    "horovod_tpu.observability.perfboard",
    "horovod_tpu.analysis.schedule",
    "horovod_tpu.analysis.numerics",
)

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))

#: Frames kept per access record — enough to name the caller chain
#: without paying a full traceback per touch.
_STACK_DEPTH = 6


class RaceError(RuntimeError):
    """Raised at the access site under HOROVOD_RACE_CHECK_FAIL=1."""


@dataclasses.dataclass
class RaceReport:
    """One guarded-by violation observed at runtime."""

    cls: str
    attr: str
    lock: str
    access: str                 # "read" | "write"
    site: str                   # "path:lineno" of the touching line
    thread: str
    stack: List[str]            # innermost-last "path:line in func"
    lockset: List[str]          # tracked locks held instead
    other_thread: Optional[str] = None
    other_site: Optional[str] = None
    other_stack: Optional[List[str]] = None

    def render(self) -> str:
        head = (f"hvdrace: '{self.cls}.{self.attr}' is guarded-by "
                f"'{self.lock}' but {self.access} at {self.site} on "
                f"thread '{self.thread}' without it "
                f"(held locks: {self.lockset or 'none'})")
        lines = [head, "  this access:"]
        lines += [f"    {f}" for f in self.stack]
        if self.other_site is not None:
            lines.append(f"  previous access: thread "
                         f"'{self.other_thread}' at {self.other_site}")
            lines += [f"    {f}" for f in (self.other_stack or [])]
        return "\n".join(lines)


_token_counter = [0]
_token_mu = threading.Lock()


class _Held(threading.local):
    """Per-thread multiset of held TrackedLocks (id -> count), plus a
    NEVER-REUSED thread token: ``threading.get_ident()`` is recycled
    once a thread dies, which would let a later thread masquerade as a
    dead owner in the Eraser state machine."""

    def __init__(self) -> None:
        self.locks: Dict[int, int] = {}
        self.names: Dict[int, str] = {}
        with _token_mu:
            _token_counter[0] += 1
            self.token = _token_counter[0]


_held = _Held()

_obj_token_counter = [0]


def _obj_token(obj) -> int:
    """A never-reused identity for `obj` (``id()`` is recycled after
    collection, which would let a fresh object inherit a dead object's
    Eraser state). Stamped on the object on first use; objects that
    refuse attributes (__slots__) fall back to id()."""
    tok = getattr(obj, "_hvdrace_token", None)
    if tok is not None:
        return tok
    with _token_mu:
        tok = getattr(obj, "_hvdrace_token", None)
        if tok is None:
            _obj_token_counter[0] += 1
            tok = _obj_token_counter[0]
            try:
                object.__setattr__(obj, "_hvdrace_token", tok)
            except Exception:
                tok = id(obj)
    return tok


class TrackedLock:
    """Transparent Lock/RLock proxy that maintains the per-thread
    held-lock set. Wraps the ORIGINAL lock object, so references taken
    before instrumentation still synchronize with wrapped ones."""

    def __init__(self, inner, name: str) -> None:
        self._inner = inner
        self.name = name
        self.ever_acquired = False

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self.ever_acquired = True
            _held.locks[id(self)] = _held.locks.get(id(self), 0) + 1
            _held.names[id(self)] = self.name
        return got

    def release(self) -> None:
        n = _held.locks.get(id(self), 0)
        if n <= 1:
            _held.locks.pop(id(self), None)
            _held.names.pop(id(self), None)
        else:
            _held.locks[id(self)] = n - 1
        self._inner.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_current_thread(self) -> bool:
        return _held.locks.get(id(self), 0) > 0

    def __getattr__(self, item):
        # Uncommon surface (e.g. Condition internals) falls through to
        # the real lock; such paths bypass held-set tracking.
        return getattr(self._inner, item)


def _current_lockset() -> List[str]:
    return sorted(set(_held.names.values()))


class _AttrState:
    """Eraser ownership state for one (object/class, attribute)."""

    __slots__ = ("owner_tid", "shared", "last")

    def __init__(self) -> None:
        self.owner_tid: Optional[int] = None
        self.shared = False
        # (thread name, site, stack) of the most recent access
        self.last: Optional[Tuple[str, str, List[str]]] = None


class _AnnStat:
    """Aggregated runtime evidence for one annotation (stale check)."""

    __slots__ = ("lock", "accesses", "post_accesses", "held_accesses",
                 "shared_seen")

    def __init__(self, lock: str) -> None:
        self.lock = lock
        self.accesses = 0
        self.post_accesses = 0   # accesses from a non-owner thread
        self.held_accesses = 0
        self.shared_seen = False


class _ClassAnnotation:
    __slots__ = ("cls", "attr", "lock", "class_level", "line")

    def __init__(self, cls: str, attr: str, lock: str,
                 class_level: bool, line: int) -> None:
        self.cls = cls
        self.attr = attr
        self.lock = lock
        self.class_level = class_level
        self.line = line


class Detector:
    """Process-wide hvdrace state (singleton: module-level `_detector`)."""

    def __init__(self) -> None:
        self.enabled = False
        self.fail_fast = False
        self.max_reports = 100
        self.reports: List[RaceReport] = []
        self._sink: Optional[List[RaceReport]] = None  # capture() target
        self._mu = threading.Lock()  # internal — deliberately untracked
        self._state: Dict[Tuple[int, str], _AttrState] = {}
        self._ann_stats: Dict[Tuple[str, str], _AnnStat] = {}
        self._site_counts: Dict[str, int] = {}
        self._suppressed_sites: Dict[str, bool] = {}
        self._instrumented: Set[type] = set()

    # ------------------------------------------------------------- config
    def configure_from_env(self) -> None:
        self.fail_fast = os.environ.get(
            HOROVOD_RACE_CHECK_FAIL, "").strip().lower() in (
                "1", "true", "yes", "on")
        try:
            self.max_reports = int(os.environ.get(
                HOROVOD_RACE_CHECK_MAX_REPORTS, "") or 100)
        except ValueError:
            self.max_reports = 100

    # ------------------------------------------------------------ reports
    def _emit(self, report: RaceReport) -> None:
        with self._mu:
            n = self._site_counts.get(report.site, 0) + 1
            self._site_counts[report.site] = n
            target = self._sink if self._sink is not None else self.reports
            if n <= self.max_reports and len(target) < self.max_reports:
                target.append(report)
        try:
            from horovod_tpu.observability import metrics as m
            m.registry().counter(
                "hvdrace_reports_total",
                "guarded-by violations observed by hvdrace",
                labelnames=("site",)).labels(site=report.site).inc()
        except Exception:
            pass
        if self.fail_fast:
            raise RaceError(report.render())

    # ------------------------------------------------------------- checks
    def check_access(self, obj, cls: type, ann: _ClassAnnotation,
                     access: str) -> None:
        if not self.enabled:
            return
        held = self._lock_held(obj, ann.lock)
        key_obj = cls if ann.class_level else obj
        key = (_obj_token(key_obj), ann.attr)
        thread = threading.current_thread()
        site, stack = _caller_site()
        report: Optional[RaceReport] = None
        with self._mu:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = _AttrState()
            stat = self._ann_stats.get((ann.cls, ann.attr))
            if stat is None:
                stat = self._ann_stats[(ann.cls, ann.attr)] = \
                    _AnnStat(ann.lock)
            stat.accesses += 1
            if held:
                stat.held_accesses += 1
            tid = _held.token  # ident-reuse-proof thread identity
            if st.owner_tid is None:
                st.owner_tid = tid
            elif tid != st.owner_tid:
                st.shared = True
                # Provably beyond the creation scope: another thread.
                # (Owner-thread touches are NOT counted — __init__ may
                # legitimately touch its own state repeatedly unlocked,
                # and that must not read as a stale annotation.)
                stat.post_accesses += 1
            if st.shared:
                stat.shared_seen = True
            if st.shared and held is False \
                    and not self._site_suppressed(site):
                prev = st.last
                report = RaceReport(
                    cls=ann.cls, attr=ann.attr, lock=ann.lock,
                    access=access, site=site, thread=thread.name,
                    stack=stack, lockset=_current_lockset(),
                    other_thread=prev[0] if prev else None,
                    other_site=prev[1] if prev else None,
                    other_stack=prev[2] if prev else None)
            st.last = (thread.name, site, stack)
        if report is not None:
            self._emit(report)

    def _lock_held(self, obj, lock_name: str) -> Optional[bool]:
        """True/False when determinable; None (treated as held) when
        the lock object exposes no ownership probe."""
        try:
            lk = object.__getattribute__(obj, lock_name)
        except AttributeError:
            return False
        if isinstance(lk, TrackedLock):
            return lk.held_by_current_thread()
        probe = getattr(lk, "_is_owned", None)
        if probe is not None:  # raw RLock acquired before wrapping
            try:
                return bool(probe())
            except Exception:
                return None
        if isinstance(lk, _LOCK_TYPES):
            return None  # raw Lock: ownership unknowable — never report
        return False if lk is None else None

    def _site_suppressed(self, site: str) -> bool:
        """Honor `hvdlint: disable=HVD101/HVDRACE -- why` on the
        touching source line, so lexically-audited benign races (the
        metrics fast path) stay silent at runtime too."""
        cached = self._suppressed_sites.get(site)
        if cached is not None:
            return cached
        ok = False
        path, _, lineno = site.rpartition(":")
        try:
            from horovod_tpu.analysis.driver import (parse_suppression,
                                                     suppression_covers)
            entry = parse_suppression(linecache.getline(path, int(lineno)))
            ok = (suppression_covers(entry, "HVD101")
                  or suppression_covers(entry, "HVDRACE"))
        except Exception:
            ok = False
        self._suppressed_sites[site] = ok
        return ok

    # ------------------------------------------------------ lock wrapping
    def wrap_lock_in_place(self, obj, cls: type, lock_name: str) -> None:
        """Swap a raw lock stored at `lock_name` (instance dict or class
        attribute) for a TrackedLock wrapping the SAME inner lock, so
        instances created before enable() still get tracked."""
        try:
            lk = object.__getattribute__(obj, lock_name)
        except AttributeError:
            return
        if not isinstance(lk, _LOCK_TYPES):
            return
        with self._mu:
            try:  # re-check under the mutex: another thread may have won
                lk = object.__getattribute__(obj, lock_name)
            except AttributeError:
                return
            if not isinstance(lk, _LOCK_TYPES):
                return
            wrapped = TrackedLock(lk, lock_name)
            try:
                inst = object.__getattribute__(obj, "__dict__")
            except AttributeError:
                inst = None
            if inst is not None and lock_name in inst:
                object.__setattr__(obj, lock_name, wrapped)
                return
            for klass in type(obj).__mro__:
                if lock_name in klass.__dict__:
                    setattr(klass, lock_name, wrapped)
                    return

    # ------------------------------------------------------------- stale
    def stale_annotations(self) -> List[str]:
        out = []
        with self._mu:
            for (cls, attr), s in sorted(self._ann_stats.items()):
                if s.post_accesses > 0 and s.held_accesses == 0:
                    out.append(
                        f"{cls}.{attr}: annotated guarded-by "
                        f"'{s.lock}' but the lock was never held "
                        f"across {s.accesses} observed access(es) — "
                        f"stale annotation or missing locking")
        return out


_detector = Detector()


def _caller_site() -> Tuple[str, List[str]]:
    """(file:line of the touching code, short caller stack) — the first
    frame outside this module going up."""
    frame = sys._getframe(1)
    here = __file__
    while frame is not None and frame.f_code.co_filename == here:
        frame = frame.f_back
    site = "<unknown>:0"
    stack: List[str] = []
    depth = 0
    while frame is not None and depth < _STACK_DEPTH:
        code = frame.f_code
        entry = f"{code.co_filename}:{frame.f_lineno} in {code.co_name}"
        if depth == 0:
            site = f"{code.co_filename}:{frame.f_lineno}"
        stack.append(entry)
        frame = frame.f_back
        depth += 1
    return site, stack


# -------------------------------------------------------- instrumentation

def annotations_from_source(text: str, path: str = "<string>"
                            ) -> Dict[str, List[_ClassAnnotation]]:
    """class name -> guarded-by annotations, using the HVD101 extractor."""
    from horovod_tpu.analysis.concurrency_rules import _collect_annotations
    from horovod_tpu.analysis.driver import SourceFile
    by_cls: Dict[str, List[_ClassAnnotation]] = {}
    for a in _collect_annotations(SourceFile(path, text)):
        if a.cls is None:
            continue  # module-level globals: no class to instrument
        by_cls.setdefault(a.cls, []).append(_ClassAnnotation(
            a.cls, a.attr, a.lock, a.class_level, a.line))
    return by_cls


def instrument_class(cls: type,
                     anns: Sequence[_ClassAnnotation]) -> None:
    """Install guarded-attribute hooks on `cls` (idempotent)."""
    d = _detector
    if cls in d._instrumented or not anns:
        return
    d._instrumented.add(cls)
    guarded: Dict[str, _ClassAnnotation] = {a.attr: a for a in anns}
    locknames: Set[str] = {a.lock for a in anns}
    watched = set(guarded) | locknames
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__

    def __getattribute__(self, name):
        if name in watched:
            ann = guarded.get(name)
            if ann is not None:
                d.check_access(self, cls, ann, "read")
            elif d.enabled:
                d.wrap_lock_in_place(self, cls, name)
        return orig_get(self, name)

    def __setattr__(self, name, value):
        if name in locknames and isinstance(value, _LOCK_TYPES):
            value = TrackedLock(value, name)
        elif name in guarded:
            d.check_access(self, cls, guarded[name], "write")
        orig_set(self, name, value)

    cls.__getattribute__ = __getattribute__  # type: ignore[assignment]
    cls.__setattr__ = __setattr__            # type: ignore[assignment]
    # Class-level declared locks (e.g. the rendezvous KV handler) can be
    # wrapped right now — no instance required.
    for lock_name in locknames:
        raw = cls.__dict__.get(lock_name)
        if isinstance(raw, _LOCK_TYPES):
            setattr(cls, lock_name, TrackedLock(raw, lock_name))


def instrument_module(module) -> List[str]:
    """Instrument every annotated class defined in `module`; returns the
    instrumented class names."""
    path = getattr(module, "__file__", None)
    if not path or not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    done: List[str] = []
    for cls_name, anns in annotations_from_source(text, path).items():
        cls = getattr(module, cls_name, None)
        if isinstance(cls, type):
            instrument_class(cls, anns)
            done.append(cls_name)
    return done


def enable(modules: Sequence[str] = DEFAULT_MODULES) -> None:
    """Turn the detector on and instrument the runtime (idempotent).

    Called from ``horovod_tpu/__init__`` when ``HOROVOD_RACE_CHECK=1``;
    callable directly from tests/tools. Instruments each module's
    annotated classes, so instances created afterwards get wrapped
    locks; pre-existing instances are handled lazily (raw locks are
    swapped in place on first guarded access, and raw RLocks are
    ownership-probed even unwrapped)."""
    import importlib
    d = _detector
    d.configure_from_env()
    for name in modules:
        try:
            instrument_module(importlib.import_module(name))
        except Exception as e:  # never let the debug tool break import
            print(f"hvdrace: could not instrument {name}: {e}",
                  file=sys.stderr)
    d.enabled = True


def disable() -> None:
    _detector.enabled = False


def active() -> bool:
    return _detector.enabled


def reports() -> List[RaceReport]:
    with _detector._mu:
        return list(_detector.reports)


def drain() -> List[RaceReport]:
    """Return-and-clear the accumulated reports (the `make race` gate)."""
    with _detector._mu:
        out = list(_detector.reports)
        _detector.reports.clear()
        _detector._site_counts.clear()
        return out


def stale_annotations() -> List[str]:
    return _detector.stale_annotations()


@contextmanager
def capture(fail: bool = False) -> Iterator[List[RaceReport]]:
    """Scoped detection for tests: enables the detector, routes reports
    into the yielded list (the global report log is untouched), and
    restores the previous mode on exit."""
    d = _detector
    sink: List[RaceReport] = []
    with d._mu:
        prev = (d.enabled, d.fail_fast, d._sink)
        d._sink = sink
    d.enabled = True
    d.fail_fast = fail
    try:
        yield sink
    finally:
        with d._mu:
            d.enabled, d.fail_fast, d._sink = prev


def env_enabled() -> bool:
    return os.environ.get(HOROVOD_RACE_CHECK, "").strip().lower() in (
        "1", "true", "yes", "on")


def maybe_enable_from_env() -> bool:
    """The import-time hook: enable iff HOROVOD_RACE_CHECK is set."""
    if env_enabled():
        enable()
        return True
    return False
