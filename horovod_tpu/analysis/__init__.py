"""hvdlint — static analysis for collective consistency and concurrency
discipline, plus the cross-rank fingerprint verifier.

The reference Horovod's background runtime exists largely to catch one
failure class at runtime: ranks submitting collectives in different
orders or with mismatched shapes, which otherwise manifests as a silent
stall (controller.cc:74-447 mismatch checks, stall_inspector.cc). This
package moves that detection LEFT of the job launch:

* ``hvdlint`` (``python -m horovod_tpu.analysis``, the single
  ``make lint`` entrypoint) runs two AST rule families over Python
  source — collective-consistency rules (HVD0xx) on user/training code
  and the repo's examples, and concurrency-discipline rules (HVD1xx,
  including the ``# guarded-by:`` lock annotation convention) on the
  runtime itself — plus the HVD-ENV documentation-drift rule that
  subsumes the old ``scripts/check_env_docs.py``. The HVD0xx rules are
  interprocedural: ``callgraph`` builds a module-level call graph with
  transitive-collective and rank-taint summaries over every linted
  file, so helpers no longer hide divergence patterns. ``--format
  json`` and ``--baseline`` make CI gate on *new* findings only.

* ``race`` (**hvdrace**, ``HOROVOD_RACE_CHECK=1`` / ``make race``) is
  the runtime enforcement of ``# guarded-by:``: an Eraser-style
  lockset detector that instruments the annotated runtime classes at
  import time and reports any guarded attribute touched without its
  declared lock held — including stale annotations whose lock is never
  held at all.

* ``hlo`` / ``hlo_rules`` (**hvdhlo**, ``--hlo`` / ``--hlo-step`` /
  ``make hlo-lint``) lint the *lowered* XLA step program (HVD2xx:
  giant-allreduce plans, host round-trips, missing donation, lane
  padding, bf16 upcasts) — perf contracts invisible to an AST linter.

* ``shard`` / ``shard_rules`` (**hvdshard**, ``--shard`` /
  ``--hlo-step lm_sharded`` / ``make shard-lint``) are the
  sharding-aware layer over the same lowered forms (HVD3xx):
  replicated tables, partitioner-inserted resharding collectives, a
  donation-aware static per-device peak-HBM estimate gating
  compile-time OOM, unused mesh axes, and
  all-reduce-that-should-be-reduce-scatter — the static gate in front
  of the GSPMD backend (ROADMAP item 3).

* ``schedule`` / ``sched_rules`` (**hvdsched**, ``--sched`` /
  ``--hlo-step lm_sharded`` / ``make sched-lint``) reconstruct the
  per-device *collective schedule* from the same lowered forms —
  every collective with its replica groups (explicit, V2 iota,
  permute source-target pairs), channel id and payload bytes, in
  scheduled order — and verify cross-device matching (HVD4xx):
  group members reaching different collectives or positions (the
  static deadlock the runtime verifier only catches live), permute
  chains that are not unions of disjoint cycles (the 1F1B hazard),
  inconsistently-ordered overlapping subset collectives, flat
  cross-slice all-reduces where ICI/DCN staging is available, and
  predicted exposed comms from the analytic per-axis cost model that
  bench.py stamps beside the measured ``comms_by_axis``.

* ``verifier`` is the runtime companion (``HOROVOD_CHECK_COLLECTIVES=1``):
  each rank hashes its rolling sequence of
  ``(op, name, shape, dtype, process_set)`` tuples at the dispatch choke
  point in ``ops/collectives.py`` and periodically cross-checks the
  fingerprint through the rendezvous KV, so a divergent rank raises an
  actionable mismatch error (rank, call index, both fingerprints)
  instead of tripping the stall watchdog blind.

See docs/static_analysis.md for the rule catalog and suppression syntax.

The analysis modules themselves import only the standard library, but
``python -m horovod_tpu.analysis`` necessarily executes the parent
package's ``__init__`` (which needs jax). Environments without the
runtime stack get the same rules dependency-free by stubbing the parent
package first — ``scripts/check_env_docs.py`` shows the pattern.
"""

from horovod_tpu.analysis.driver import (  # noqa: F401
    Finding, lint_paths, lint_source, main, run_cli,
)
