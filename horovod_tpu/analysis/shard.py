"""hvdshard: static sharding & per-device memory analysis (HVD3xx).

The GSPMD path (ROADMAP item 3; Xu et al., arXiv:2105.04663) makes
parallelism *annotation-driven*: the program you write is global, the
partitioner decides what every device holds and which collectives move
data between them. That is exactly why its classic failure modes are
statically visible long before a 40-minute compile-and-OOM run:

* a 700 M-param table nobody annotated is silently **replicated** on
  every device (HVD301);
* two inconsistent annotations make the partitioner **insert** an
  all-gather/all-to-all nobody asked for, moving whole-tensor payloads
  inside the step body (HVD302);
* the per-device working set quietly exceeds HBM — discovered at run
  time today, computable at lint time from the post-SPMD module
  (HVD303);
* a mesh axis is paid for (devices reserved, collectives sized for it)
  but shards nothing (HVD304);
* an ``all_reduce`` whose consumers each keep only their own shard
  should have been a ``reduce_scatter``/``psum_scatter`` — the
  Megatron-LM resharding-traffic observation (HVD305).

This module is the sharding-aware layer over the same two textual
forms ``analysis/hlo.py`` already parses:

* **StableHLO MLIR** (pre-partition): sharding arrives as
  ``mhlo.sharding`` attributes on function arguments and on
  ``custom_call @Sharding`` ops (``with_sharding_constraint``); shapes
  are *global*.
* **post-SPMD HLO text** (``lowered.compile().as_text()``): shapes are
  already *per-device*, entry parameters keep their ``sharding={...}``
  attrs, the module is ``is_scheduled`` — its printed instruction
  order is the schedule the donation-aware liveness pass walks to
  produce the static per-device peak-HBM estimate.

Rules live in ``analysis/shard_rules.py``; findings ride the shared
driver machinery (``--format json``/``--baseline``/``--list-rules``)
and feed ``hvdshard_findings_total{rule}``. ``make shard-lint`` gates
the canonical 2-D (batch x model) mesh step program
(``--hlo-step lm_sharded``) against ``scripts/hvdshard_baseline.json``.
See docs/static_analysis.md for the catalog and the peak-memory model.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis.hlo import (
    HloOp, HloProgram, TensorType, op_sharding, parse,
)

_MB = 1024 * 1024


def _bytes_env(name: str, default: Optional[int]) -> Optional[int]:
    """Byte-count env knob accepting plain ints or K/M/G suffixes
    (``HOROVOD_HLO_LINT_HBM_BUDGET=16G``). Unset -> default; a
    malformed value raises — silently falling back would disarm the
    very gate (HVD303 and friends) the knob was set to arm, in exactly
    the runs that set it (the flops.py loud-on-garbage policy)."""
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    m = re.fullmatch(r"(\d+(?:\.\d+)?)\s*([kKmMgG]?)[bB]?", v)
    if not m:
        raise ValueError(
            f"{name}={v!r} is not a byte count (use plain bytes or a "
            "K/M/G suffix, e.g. 16G)")
    mult = {"": 1, "k": 1024, "m": _MB, "g": 1024 * _MB}[m.group(2).lower()]
    return int(float(m.group(1)) * mult)


# ---------------------------------------------------- sharding strings

@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """One parsed HLO sharding annotation.

    ``tile_dims`` are the per-tensor-dimension shard counts;
    ``replicate_factor`` is how many devices hold each shard (the
    trailing ``last_tile_dim_replicate`` group, or every device for
    ``{replicated}``); ``assignment`` is the flat device-id order over
    the C-order tile grid (+ the replication dim innermost), or None
    when the kind carries no grid (replicated/maximal/manual).
    """

    kind: str                     # replicated | tiled | maximal | manual
    tile_dims: Tuple[int, ...] = ()
    replicate_factor: int = 1
    assignment: Optional[Tuple[int, ...]] = None

    @property
    def shard_factor(self) -> int:
        n = 1
        for d in self.tile_dims:
            n *= d
        return n

    @property
    def fully_replicated(self) -> bool:
        return self.kind == "replicated" or (
            self.kind == "tiled" and self.shard_factor == 1)

    def shard_of(self, num_devices: int) -> Optional[Tuple[int, ...]]:
        """device id -> shard index, as a tuple indexed by device id;
        devices in the same replication group share a shard index.
        None when the annotation doesn't describe `num_devices` devices
        (foreign dump) or carries no grid to map."""
        if self.kind == "replicated":
            return tuple(0 for _ in range(num_devices))
        if self.assignment is None or len(self.assignment) != num_devices:
            return None
        out = [0] * num_devices
        rep = max(self.replicate_factor, 1)
        for flat, dev in enumerate(self.assignment):
            if not 0 <= dev < num_devices:
                return None
            out[dev] = flat // rep   # same shard for the rep-group
        return tuple(out)


def _iota_order(dims: Sequence[int], perm: Sequence[int]) -> List[int]:
    """Flat C-order device ids of ``iota(prod(dims)).reshape(dims)
    .transpose(perm)`` — the V2 tile-assignment ``<=[dims]T(perm)``
    encoding, expanded without numpy (lint must not need the runtime
    deps)."""
    strides = [1] * len(dims)
    for i in range(len(dims) - 2, -1, -1):
        strides[i] = strides[i + 1] * dims[i + 1]
    t_dims = [dims[p] for p in perm]
    t_strides = [strides[p] for p in perm]
    out = []
    for idx in itertools.product(*(range(d) for d in t_dims)):
        out.append(sum(i * s for i, s in zip(idx, t_strides)))
    return out


_TILED_RE = re.compile(
    r"devices=\[([\d,]+)\]"
    r"(?:<=\[([\d,]+)\](?:T\(([\d,]+)\))?|((?:\d+,?)+))")
_LAST_TILE_DIMS_RE = re.compile(r"last_tile_dims=\{([^{}]*)\}")


def parse_sharding(text: Optional[str]) -> Optional[ShardSpec]:
    """Parse one HLO sharding annotation string (either textual form
    prints the same grammar): ``{replicated}``, ``{maximal device=0}``,
    ``{manual}``, V1 explicit device lists ``{devices=[2,2]0,1,2,3}``
    and V2 iota forms ``{devices=[4,1,2]<=[2,4]T(1,0)
    last_tile_dim_replicate}``. None on no/unrecognized annotation
    (size-based rules must skip, not guess)."""
    if not text:
        return None
    body = text.strip()
    # Strip exactly ONE outer brace pair: .strip("{}") would also eat
    # the closing brace of a trailing `last_tile_dims={replicated}`.
    if body.startswith("{") and body.endswith("}"):
        body = body[1:-1].strip()
    if not body:
        return None
    if body.startswith("replicated"):
        return ShardSpec("replicated")
    if body.startswith("maximal"):
        return ShardSpec("maximal")
    if body.startswith("manual"):
        return ShardSpec("manual")
    m = _TILED_RE.search(body)
    if not m:
        return None
    printed = [int(d) for d in m.group(1).split(",") if d]
    if m.group(2):                           # V2 iota [+ transpose]
        reshape = [int(d) for d in m.group(2).split(",") if d]
        perm = ([int(p) for p in m.group(3).split(",") if p]
                if m.group(3) else list(range(len(reshape))))
        if sorted(perm) != list(range(len(reshape))):
            return None
        assignment = _iota_order(reshape, perm)
    else:                                    # V1 explicit device list
        assignment = [int(d) for d in m.group(4).split(",") if d]
    total = 1
    for d in printed:
        total *= d
    if total != len(assignment) or total == 0:
        return None
    # Trailing non-data tile dims: one for last_tile_dim_replicate,
    # len(list) for last_tile_dims={...}; all treated as replication
    # (a manual trailing dim still means "these devices hold the same
    # data-sharded tile").
    trailing = 0
    if "last_tile_dim_replicate" in body:
        trailing = 1
    else:
        lt = _LAST_TILE_DIMS_RE.search(body)
        if lt:
            trailing = len([t for t in lt.group(1).split(",") if t.strip()])
    if trailing >= len(printed):
        return None
    tile_dims = tuple(printed[:len(printed) - trailing])
    rep = 1
    for d in printed[len(printed) - trailing:]:
        rep *= d
    return ShardSpec("tiled", tile_dims, rep, tuple(assignment))


def per_device_bytes(ttype: Optional[TensorType],
                     spec: Optional[ShardSpec],
                     fmt: str) -> Optional[int]:
    """Bytes one device holds for a tensor under `spec`. Post-SPMD HLO
    shapes are already per-device — bytes pass through; StableHLO
    shapes are global and divide by the (ceil-per-dim) tiling."""
    if ttype is None:
        return None
    nb = ttype.nbytes
    if nb is None:
        return None
    if fmt == "hlo" or spec is None or spec.kind != "tiled":
        return nb
    itemsize = ttype.itemsize
    elems = 1
    for i, d in enumerate(ttype.dims):
        t = spec.tile_dims[i] if i < len(spec.tile_dims) else 1
        elems *= -(-d // max(t, 1))
    return elems * itemsize


# ----------------------------------------------- annotated tensor sweep

@dataclasses.dataclass(frozen=True)
class AnnotatedTensor:
    """One sharding-annotated value: an entry parameter or an explicit
    constraint (`custom_call @Sharding` / an op-level ``sharding=``)."""

    name: str
    type: Optional[TensorType]
    spec: Optional[ShardSpec]
    line: int
    origin: str                   # "param" | "constraint"


def annotated_tensors(prog: HloProgram) -> List[AnnotatedTensor]:
    out: List[AnnotatedTensor] = []
    for p in prog.entry_params:
        if p.sharding is not None:
            out.append(AnnotatedTensor(p.name, p.type,
                                       parse_sharding(p.sharding),
                                       p.line, "param"))
    for op in prog.ops:
        if op.scope != prog.entry_scope:
            continue
        s = op_sharding(op)
        if s is None:
            continue
        t = (op.result_types[0] if op.result_types else
             (op.operand_types[0] if op.operand_types else None))
        out.append(AnnotatedTensor(op.result or op.opcode, t,
                                   parse_sharding(s), op.line,
                                   "constraint"))
    return out


def partition_classes(tensors: Sequence[AnnotatedTensor],
                      num_devices: int) -> Optional[int]:
    """Number of distinct device classes under the common refinement of
    every tensor's shard partition: two devices in the same class hold
    identical shards of EVERY tensor in `tensors` — paid-for devices
    that add no parallelism. None when any annotation can't be mapped
    onto `num_devices` devices (foreign/partial dump: don't guess)."""
    if num_devices <= 1:
        return None
    keys: List[Tuple] = [() for _ in range(num_devices)]
    for t in tensors:
        if t.spec is None:
            return None
        shard = t.spec.shard_of(num_devices)
        if shard is None:
            return None
        keys = [k + (s,) for k, s in zip(keys, shard)]
    return len(set(keys))


# --------------------------------------- collective provenance (HVD302)

#: jax collective primitive names as they appear as the LAST component
#: of a post-opt ``metadata={op_name="jit(f)/.../psum"}`` path: a
#: collective carrying one of these was asked for by user code; one
#: carrying the op it was inserted FOR (dot_general, gather, ...) — or
#: no metadata at all — came from the SPMD partitioner.
USER_COLLECTIVE_MARKERS = frozenset({
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "reduce_scatter",
    "all_reduce", "collective_permute",
})

_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')


def traceable_to_user_collective(op: HloOp) -> bool:
    m = _OP_NAME_RE.search(op.attrs)
    if not m:
        return False
    last = m.group(1).rsplit("/", 1)[-1]
    last = re.split(r"[\[\s(]", last, 1)[0]
    return last in USER_COLLECTIVE_MARKERS


# ------------------------------------- per-axis comms attribution

#: Collective opcodes whose wire traffic the per-axis attribution
#: accounts (post-SPMD HLO names).
_COMMS_OPCODES = frozenset({
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "collective_permute",
})

_REPLICA_GROUPS_LIST_RE = re.compile(
    r"replica_groups=\{((?:\{[^{}]*\},?)*)\}")
_REPLICA_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_SOURCE_TARGET_RE = re.compile(
    r"source_target_pairs=\{((?:\{[^{}]*\},?)*)\}")


def _parse_replica_groups(attrs: str,
                          num_devices: int) -> Optional[List[List[int]]]:
    """The device-id groups one collective communicates over, from
    either textual form XLA prints: the explicit
    ``replica_groups={{0,1},{2,3}}`` list, the V2 iota
    ``replica_groups=[2,4]<=[8]`` form, or (collective-permute)
    ``source_target_pairs`` — whose connected components are the
    communicating sets. ``replica_groups={}`` / absent means one group
    of every device."""
    m = _REPLICA_GROUPS_IOTA_RE.search(attrs)
    if m:
        printed = [int(d) for d in m.group(1).split(",") if d]
        reshape = [int(d) for d in m.group(2).split(",") if d]
        perm = ([int(p) for p in m.group(3).split(",") if p]
                if m.group(3) else list(range(len(reshape))))
        if sorted(perm) != list(range(len(reshape))):
            return None
        flat = _iota_order(reshape, perm)
        if len(printed) != 2 or printed[0] * printed[1] != len(flat):
            return None
        g = printed[1]
        return [flat[i:i + g] for i in range(0, len(flat), g)]
    m = _REPLICA_GROUPS_LIST_RE.search(attrs)
    if m:
        inner = m.group(1)
        if not inner.strip():
            return [list(range(num_devices))]
        return [[int(x) for x in grp.strip("{}").split(",") if x.strip()]
                for grp in re.findall(r"\{[^{}]*\}", inner)]
    m = _SOURCE_TARGET_RE.search(attrs)
    if m:
        pairs = [tuple(int(x) for x in grp.strip("{}").split(","))
                 for grp in re.findall(r"\{[^{}]*\}", m.group(1))]
        # Union-find over the permute graph: the communicating sets.
        parent = list(range(num_devices))

        def find(a):
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        for s, t in pairs:
            if 0 <= s < num_devices and 0 <= t < num_devices:
                parent[find(s)] = find(t)
        comps: Dict[int, List[int]] = {}
        touched = {d for p in pairs for d in p}
        for d in sorted(touched):
            comps.setdefault(find(d), []).append(d)
        return list(comps.values()) or None
    if "replica_groups" in attrs:
        return None
    return [list(range(num_devices))]


def _axis_partitions(axis_sizes: Sequence[Tuple[str, int]]
                     ) -> Dict[frozenset, str]:
    """Canonical device-id partition -> axis label, for every non-empty
    subset of the size>1 axes. Devices are flat C-order indices over
    `axis_sizes` (outermost first) — exactly `build_mesh`'s device
    order, so flat index == Horovod rank == SPMD partition id."""
    sizes = [s for _, s in axis_sizes]
    names = [a for a, _ in axis_sizes]
    live = [i for i, s in enumerate(sizes) if s > 1]
    strides = [1] * len(sizes)
    for i in range(len(sizes) - 2, -1, -1):
        strides[i] = strides[i + 1] * sizes[i + 1]
    out: Dict[frozenset, str] = {}
    for r in range(1, len(live) + 1):
        for subset in itertools.combinations(live, r):
            moving = list(subset)
            fixed = [i for i in range(len(sizes)) if i not in subset]
            groups = []
            for fcoord in itertools.product(
                    *(range(sizes[i]) for i in fixed)):
                base = sum(c * strides[i]
                           for c, i in zip(fcoord, fixed))
                groups.append(frozenset(
                    base + sum(c * strides[i]
                               for c, i in zip(mcoord, moving))
                    for mcoord in itertools.product(
                        *(range(sizes[i]) for i in moving))))
            label = "+".join(names[i] for i in moving)
            out[frozenset(groups)] = label
    return out


def group_axis_label(groups: Optional[List[List[int]]],
                     partitions: Dict[frozenset, str]) -> Optional[str]:
    """Mesh-axis label one collective's parsed replica groups span — the
    ONE group-classification helper shared by :func:`comms_by_axis` and
    the hvdsched cost model (analysis/schedule.comms_model), so the two
    attributions can never disagree on what a group means.

    ``None`` means every group is a *degenerate single-device set*
    (size-1 groups from a size-1 mesh axis): no wire traffic moves, the
    caller must skip the op — distinct from ``replica_groups={}``,
    which parses to one full-mesh group upstream. Unparseable groups
    (``groups is None``) and real groups matching no axis partition
    land under ``"other"``.
    """
    if groups is None:
        return "other"
    norm = frozenset(frozenset(g) for g in groups if len(g) > 1)
    if not norm:
        return None  # degenerate single-device groups: no wire
    return partitions.get(norm, "other")


def comms_by_axis(text: str, axis_sizes: Sequence[Tuple[str, int]],
                  path: str = "<compiled>") -> Dict[str, Dict[str, object]]:
    """Attribute every collective's payload bytes in a post-SPMD module
    to the mesh axis (or axis combination) its replica groups span —
    the static dp-vs-tp wire-traffic split of the hybrid backend
    (docs/parallelism.md; the perfscope/bench ``comms_by_axis`` stamp).

    `axis_sizes`: ordered (axis, size) pairs outermost-first — i.e.
    ``zip(AXIS_ORDER, MeshSpec.sizes())``. Groups that match no single
    axis partition land under the joined label ("dp+tp" = a collective
    over the whole mesh); unclassifiable groups land under "other".
    Returns ``{label: {"bytes_per_step", "ops", "by_op"}}``.
    """
    prog = parse(text, path)
    ndev = 1
    for _, s in axis_sizes:
        ndev *= s
    partitions = _axis_partitions(axis_sizes)
    # Singleton groups (a one-device "collective") carry no traffic.
    out: Dict[str, Dict[str, object]] = {}
    from horovod_tpu.analysis import hlo_rules
    for op in prog.ops:
        if op.opcode not in _COMMS_OPCODES:
            continue
        groups = _parse_replica_groups(op.attrs, ndev)
        label = group_axis_label(groups, partitions)
        if label is None:
            continue  # degenerate single-device groups: no wire
        nb = hlo_rules._collective_payload(op)
        if nb is None:
            nb = _result_bytes(op)
        ent = out.setdefault(label, {"bytes_per_step": 0, "ops": 0,
                                     "by_op": {}})
        ent["bytes_per_step"] += int(nb or 0)
        ent["ops"] += 1
        by = ent["by_op"]
        by[op.opcode] = by.get(op.opcode, 0) + int(nb or 0)
    return out


# ------------------------------------------- per-device peak-HBM model

#: Result-aliases-operand opcodes: no new buffer is materialized.
_ALIAS_OPCODES = {"bitcast", "get_tuple_element", "tuple"}

_CALLEE_RE = re.compile(
    r"(?:to_apply|calls|body|condition)=(%[\w.-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^{}]*)\}")


@dataclasses.dataclass
class MemoryEstimate:
    """Static per-device peak-HBM estimate of one post-SPMD module."""

    peak_bytes: int
    peak_line: int
    args_bytes: int               # entry parameter buffers
    donated_bytes: int            # of which donated (reusable)
    out_bytes: int                # root/result buffers
    num_partitions: int
    #: largest live buffers at the peak program point, for messages
    top: List[Tuple[str, int]] = dataclasses.field(default_factory=list)

    def as_dict(self) -> Dict[str, object]:
        return {"peak_bytes": self.peak_bytes,
                "peak_mb": round(self.peak_bytes / _MB, 2),
                "args_bytes": self.args_bytes,
                "donated_bytes": self.donated_bytes,
                "out_bytes": self.out_bytes,
                "num_partitions": self.num_partitions,
                "top_live": [
                    {"buffer": n, "mb": round(b / _MB, 2)}
                    for n, b in self.top]}


def _result_bytes(op: HloOp) -> int:
    total = 0
    for t in op.result_types:
        if t is not None and t.nbytes is not None:
            total += t.nbytes
    return total


def _callees(op: HloOp) -> List[str]:
    names = [m.group(1) for m in _CALLEE_RE.finditer(op.attrs)]
    bm = _BRANCHES_RE.search(op.attrs)
    if bm:
        names.extend(t.strip() for t in bm.group(1).split(",")
                     if t.strip())
    return names


class _PeakWalker:
    """Donation-aware liveness over the post-opt (scheduled) printed
    instruction order. The model errs structural, not optimistic:

    * every op result materializes its full result bytes (except the
      alias opcodes and ``fusion``/``call``-wrapped fusions, whose
      interiors never hit HBM — that is what fusion means);
    * operands die at their last textual use; donated entry parameters
      die like temps (XLA reuses the buffer), undonated ones live to
      the end next to the outputs — the exact cost HVD203 describes;
    * a ``while``/``call``/conditional adds its callee's *interior*
      peak (params and root excluded — those alias the caller's
      buffers, already counted) on top of the caller's live set.
    """

    def __init__(self, prog: HloProgram) -> None:
        self.prog = prog
        self.by_scope: Dict[str, List[HloOp]] = {}
        for op in prog.ops:
            self.by_scope.setdefault(op.scope, []).append(op)
        self._interior: Dict[str, int] = {}
        self._visiting: Set[str] = set()

    def _interior_of(self, scope: str) -> int:
        if scope in self._interior:
            return self._interior[scope]
        if scope in self._visiting or scope not in self.by_scope:
            return 0
        self._visiting.add(scope)
        peak, _, root, _ = self._walk(scope, count_params=False)
        self._visiting.discard(scope)
        interior = max(0, peak - root)
        self._interior[scope] = interior
        return interior

    def _walk(self, scope: str, count_params: bool
              ) -> Tuple[int, int, int, Dict[str, int]]:
        """(peak bytes, peak line, root result bytes, live-at-peak
        snapshot) for one scope."""
        ops = self.by_scope.get(scope, [])
        params = {p.name: p for p in self.prog.params
                  if p.scope == scope}
        # Alias chains first (aliases are defined before their uses in
        # SSA order), so liveness is keyed on CANONICAL buffers — a
        # bitcast's last use must not free the underlying buffer while
        # the original name is still consumed later, and vice versa.
        # get-tuple-element resolves to the tupled ELEMENT when the
        # tuple is scope-local (a tuple aliases ALL its operands, not
        # just the first).
        root_of: Dict[str, str] = {}

        def root(name: str) -> str:
            seen = set()
            while name in root_of and name not in seen:
                seen.add(name)
                name = root_of[name]
            return name

        defs = {op.result: op for op in ops if op.result}
        for op in ops:
            if not op.result or not op.operands:
                continue
            if op.opcode == "bitcast":
                root_of[op.result] = op.operands[0]
            elif op.opcode == "get_tuple_element":
                d = defs.get(op.operands[0])
                im = re.search(r"index=(\d+)", op.attrs)
                idx = int(im.group(1)) if im else None
                if d is not None and d.opcode == "tuple" \
                        and idx is not None and idx < len(d.operands):
                    root_of[op.result] = d.operands[idx]
                else:
                    root_of[op.result] = op.operands[0]
        last_use: Dict[str, int] = {}
        for i, op in enumerate(ops):
            for o in op.operands:
                last_use[root(o)] = i
        # A live tuple keeps EVERY element alive: extend each element's
        # lifetime to the tuple's own last use (a gte at index i would
        # otherwise let the tuple op count as element i+1's last use
        # and free a buffer still reachable through the tuple).
        for op in ops:
            if op.opcode != "tuple" or not op.result:
                continue
            tl = last_use.get(op.result)
            if tl is None:
                continue
            for o in op.operands:
                r = root(o)
                last_use[r] = max(last_use.get(r, -1), tl)
        live: Dict[str, int] = {}
        if count_params:
            for p in params.values():
                nb = p.type.nbytes if p.type is not None else None
                live[p.name] = nb or 0
        peak = sum(live.values())
        peak_line = ops[0].line if ops else 0
        snapshot: Dict[str, int] = dict(live)
        for i, op in enumerate(ops):
            if op.opcode == "parameter":
                continue  # accounted above (or free in interior scopes)
            rb = (0 if op.opcode in _ALIAS_OPCODES
                  else _result_bytes(op))
            interior = 0
            if op.opcode not in ("fusion",):
                for callee in _callees(op):
                    # fusion computations reached through the CPU
                    # backend's parallel-call wrappers recurse to ~0
                    interior = max(interior, self._interior_of(callee))
            here = sum(live.values()) + rb + interior
            if here > peak:
                peak = here
                peak_line = op.line
                snapshot = dict(live)
                if rb and op.result:
                    snapshot[op.result] = rb
            if rb and op.result:
                live[op.result] = rb
            # free buffers whose last use was this op
            for o in op.operands:
                r = root(o)
                if last_use.get(r) != i:
                    continue
                if r in params:
                    p = params[r]
                    if not count_params or not p.donated:
                        continue  # undonated args live to program end
                live.pop(r, None)
            if op.result and root(op.result) not in last_use \
                    and op.opcode not in _ALIAS_OPCODES \
                    and i < len(ops) - 1:
                live.pop(op.result, None)  # unused result: short-lived
        root_bytes = 0
        if ops:
            last = ops[-1]
            root_bytes = (_result_bytes(last)
                          if last.opcode not in _ALIAS_OPCODES else 0)
        return peak, peak_line, root_bytes, snapshot

    def estimate(self) -> Optional[MemoryEstimate]:
        scope = self.prog.entry_scope
        if scope not in self.by_scope:
            return None
        peak, line, root_bytes, snapshot = self._walk(
            scope, count_params=True)
        args = donated = 0
        for p in self.prog.entry_params:
            nb = p.type.nbytes if p.type is not None else None
            args += nb or 0
            if p.donated:
                donated += nb or 0
        top = sorted(snapshot.items(), key=lambda kv: -kv[1])[:3]
        return MemoryEstimate(peak, line, args, donated, root_bytes,
                              self.prog.num_partitions, top)


def peak_memory(prog: HloProgram) -> Optional[MemoryEstimate]:
    """Static per-device peak-HBM estimate. Only meaningful on the
    post-SPMD form (per-device shapes, scheduled order); None on
    StableHLO input or an empty module."""
    if prog.fmt != "hlo":
        return None
    return _PeakWalker(prog).estimate()


def estimate_compiled_text(text: str) -> Optional[MemoryEstimate]:
    """Convenience for bench/serve stamping: parse + estimate one
    ``compiled.as_text()`` dump."""
    try:
        return peak_memory(parse(text, "<compiled>"))
    except Exception:
        return None


# ------------------------------------------------------------- linting

def registry() -> Dict[str, Tuple[str, object]]:
    from horovod_tpu.analysis import shard_rules
    return dict(shard_rules.RULES)


def lint_text(text: str, path: str = "<hlo>",
              select: Optional[Sequence[str]] = None,
              ignore: Sequence[str] = ()) -> List[Finding]:
    """Run the HVD3xx sharding rules over one lowered module's text
    (either form; each rule self-selects the form it can judge)."""
    prog = parse(text, path)
    return lint_program(prog, select=select, ignore=ignore)


def lint_program(prog: HloProgram,
                 select: Optional[Sequence[str]] = None,
                 ignore: Sequence[str] = ()) -> List[Finding]:
    wanted = {r.upper() for r in select} if select is not None else None
    ignored = {r.upper() for r in ignore}
    out: List[Finding] = []
    for rule_id, (_desc, check) in sorted(registry().items()):
        if wanted is not None and rule_id not in wanted:
            continue
        if rule_id in ignored:
            continue
        out.extend(check(prog))
    out.sort(key=lambda f: (f.line, f.rule_id))
    return out


def lint_files(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Sequence[str] = ()) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            findings.append(Finding(str(p), 1, "HVD999",
                                    f"unreadable: {e}"))
            continue
        findings.extend(lint_text(text, path=str(p), select=select,
                                  ignore=ignore))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def record_metrics(findings: Sequence[Finding]) -> None:
    """hvdshard_findings_total{rule}; analysis must work without the
    runtime deps, so failures are swallowed."""
    try:
        from horovod_tpu.observability import metrics as m
        counter = m.registry().counter(
            "hvdshard_findings_total", "hvdshard findings by rule",
            labelnames=("rule",))
        for f in findings:
            counter.labels(rule=f.rule_id).inc()
    except Exception:
        pass


# ---------------------------------------- canonical 2-D mesh step lower

def replicated_twin_forced() -> bool:
    """HOROVOD_SHARD_LINT_REPLICATED=1: lower `lm_sharded` with every
    parameter fully replicated — the acceptance twin that must trip
    HVD301 (replicated tables) + HVD302 (partitioner-inserted
    all-gather materializing the unsharded embedding gradient)."""
    from horovod_tpu.common.config import _env_bool
    return _env_bool("HOROVOD_SHARD_LINT_REPLICATED")


def lower_sharded_step_texts(replicated: Optional[bool] = None
                             ) -> Dict[str, str]:
    """Both textual forms of the canonical 2-D (batch x model) mesh
    train step — the program ``make shard-lint`` gates.

    A tied-embedding transformer LM is laid out on the
    ``parallel/mesh.py`` mesh (``MeshSpec.infer(8, tp=4)``: dp=2 x
    tp=4, the first real consumer of that module — deliberately
    scouting ROADMAP item 3): the embedding and FFN weights shard over
    ``tp``, the batch over ``dp``, the logits carry an explicit
    batch x model constraint. Under this config the compiled module is
    resharding-free and every per-device shard stays lane-aligned.
    The replicated twin (`replicated=True`, or
    HOROVOD_SHARD_LINT_REPLICATED=1) keeps the same step body but
    stores every parameter fully replicated — the "forgot to annotate
    the params" failure GSPMD makes so easy — which trips HVD301 on
    the 16 MB embedding and HVD302 on the all-gather the partitioner
    inserts to materialize its unsharded gradient.

    Returns ``{"stablehlo": ..., "hlo": ...}`` — the pre-partition
    MLIR (global shapes + annotations) and the post-SPMD scheduled
    module (per-device shapes; what HVD302/303 and the peak-HBM
    model consume).
    """
    if replicated is None:
        replicated = replicated_twin_forced()
    from horovod_tpu.analysis.hlo import _force_cpu_mesh
    jax = _force_cpu_mesh()
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import tied_lm
    from horovod_tpu.parallel.mesh import MeshSpec, build_mesh

    ndev = len(jax.devices())
    tp = 4 if ndev % 4 == 0 else 2
    mesh = build_mesh(MeshSpec.infer(ndev, tp=tp))

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))

    cfg = tied_lm.canonical_config()
    # The runtime model (models/tied_lm.py) supplies params AND layout:
    # the GSPMD twin and the DistributedOptimizer-driven runtime step
    # (lower_runtime_step_texts) lint the same shapes by construction.
    params = tied_lm.init(0, cfg)
    pspecs = (tied_lm.replicated_specs(cfg) if replicated
              else tied_lm.param_specs(cfg))
    shardings = {k: NamedSharding(mesh, s) for k, s in pspecs.items()}
    s_tok = sh("dp", None)
    s_logits = sh("dp", None, "tp")

    def loss(p, tok, tgt):
        return tied_lm.global_loss(
            p, tok, tgt, cfg,
            constrain_logits=lambda lg:
                jax.lax.with_sharding_constraint(lg, s_logits))

    def step(p, tok, tgt):
        g = jax.grad(loss)(p, tok, tgt)
        return jax.tree_util.tree_map(lambda a, b: a - 0.01 * b, p, g)

    tok, tgt = tied_lm.sample_batch(0, cfg, batch=16, seq=64)
    tok, tgt = jnp.asarray(tok), jnp.asarray(tgt)
    jit = jax.jit(step, in_shardings=(shardings, s_tok, s_tok),
                  out_shardings=shardings, donate_argnums=0)
    lowered = jit.lower(
        jax.device_put(params, shardings),
        jax.device_put(tok, s_tok), jax.device_put(tgt, s_tok))
    return {"stablehlo": lowered.as_text(),
            "hlo": lowered.compile().as_text()}


def lower_runtime_step_texts(replicated: Optional[bool] = None
                             ) -> Dict[str, str]:
    """Both textual forms of the RUNTIME hybrid train step — the
    program the GSPMD backend actually executes, gated by
    ``--hlo-step lm_runtime`` inside ``make shard-lint`` /
    ``make gspmd-smoke``.

    Where ``lower_sharded_step_texts`` is the GSPMD (annotation-driven)
    twin, this lowers the `DistributedOptimizer.sharded_step` path
    itself: `models/tied_lm.local_loss` under shard_map on the
    ``MeshSpec.infer(8, tp=4)`` mesh, gradients bucketed per axis group
    by `reduce_gradients_in_jit(axes=...)` and psum'd over ``dp`` only,
    optax SGD applied under GSPMD. Default config must lint HVD2xx +
    HVD3xx clean against the empty baseline; the replicated twin
    (`replicated=True` / HOROVOD_SHARD_LINT_REPLICATED=1 — params
    stored AND stepped fully replicated, the 'forgot the spec' runtime
    failure) trips HVD301 on the 16 MB embedding, while the GSPMD
    twin's forced-replication continues to pin HVD302's
    partitioner-inserted all-gather.
    """
    if replicated is None:
        replicated = replicated_twin_forced()
    from horovod_tpu.analysis.hlo import _force_cpu_mesh
    jax = _force_cpu_mesh()
    from jax.sharding import NamedSharding, PartitionSpec as P

    import optax

    from horovod_tpu.models import tied_lm
    from horovod_tpu.optim.optimizer import build_sharded_train_step
    from horovod_tpu.parallel.mesh import MeshSpec, build_mesh

    ndev = len(jax.devices())
    tp = 4 if ndev % 4 == 0 else 2
    mesh = build_mesh(MeshSpec.infer(ndev, tp=tp))
    cfg = tied_lm.canonical_config()
    pspecs = (tied_lm.replicated_specs(cfg) if replicated
              else tied_lm.param_specs(cfg))
    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tied_lm.init(0, cfg), pspecs)
    opt = optax.sgd(0.01)
    step = build_sharded_train_step(
        lambda p, b: tied_lm.local_loss(p, b[0], b[1], cfg),
        opt, mesh=mesh, param_specs=pspecs)
    batch = jax.device_put(tied_lm.sample_batch(0, cfg, batch=16, seq=64),
                           NamedSharding(mesh, P("dp")))
    lowered = step.lower(params, opt.init(params), batch)
    return {"stablehlo": lowered.as_text(),
            "hlo": lowered.compile().as_text()}
