"""hvdshard rules HVD301-HVD305: sharding contracts on the lowered
program — the static gate in front of the GSPMD backend (ROADMAP
item 3; docs/static_analysis.md).

GSPMD (Xu et al., arXiv:2105.04663) decides placement from
annotations, so every classic hybrid-parallel failure is visible in
the lowered text before anything runs. Megatron-LM's scaling analysis
(Narayanan et al., SC'21) names the two quantities that decide whether
a mesh config is viable — resharding traffic and per-device memory;
HVD302/HVD303 compute exactly those at lint time.

HVD301  a tensor >= HOROVOD_SHARD_LINT_MIN_REPLICATED_BYTES is fully
        replicated across a >1-partition mesh: every device pays full
        HBM for it and every update moves the full payload — the
        silently-replicated-table failure. (Replication across a
        *data* axis while sharded on the model axis is normal and not
        flagged; only shard_factor == 1 fires.)
HVD302  an all-gather / all-to-all / collective-permute the SPMD
        partitioner *inserted* (metadata traces to a dot/gather/...,
        not to a user collective) moving >=
        HOROVOD_SHARD_LINT_MIN_RESHARD_BYTES inside the step body:
        resharding traffic nobody asked for, usually two inconsistent
        annotations fighting.
HVD303  the static per-device peak-HBM estimate (donation-aware
        liveness over the post-opt schedule, analysis/shard.py)
        exceeds HOROVOD_HLO_LINT_HBM_BUDGET — the compile-time OOM
        gate. Silent when no budget is configured.
HVD304  the mesh carries more devices than the program's sharding can
        use: some devices hold identical shards of every annotated
        tensor >= HOROVOD_SHARD_LINT_MIN_SHARDED_BYTES — paid-for,
        unused parallelism (an axis that shards nothing).
HVD305  an all-reduce >= HOROVOD_SHARD_LINT_MIN_RESHARD_BYTES whose
        every consumer immediately slices out one shard: each device
        reduces and materializes the FULL tensor only to keep 1/k of
        it — that is a reduce-scatter (``lax.psum_scatter``) at k
        times less memory and (k-1)/k less wire traffic.

Rules self-select the textual form they can judge: HVD302/303 need
the post-SPMD module (per-device shapes, schedule, metadata), HVD304
needs the pre-partition annotations; HVD301 and HVD305 read both
(the psum+slice pattern is clearest pre-partition, where XLA hasn't
yet fused the slice away). Findings are baselined
(``scripts/hvdshard_baseline.json``), not suppressed inline — lowered
text has no comments.
"""

from __future__ import annotations

from typing import Iterable, Optional

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis.hlo import HloOp, HloProgram
from horovod_tpu.analysis import hlo_rules
from horovod_tpu.analysis import shard as S

HVD301 = "HVD301"
HVD302 = "HVD302"
HVD303 = "HVD303"
HVD304 = "HVD304"
HVD305 = "HVD305"

_MB = 1024 * 1024


def _min_replicated_bytes() -> int:
    return S._bytes_env("HOROVOD_SHARD_LINT_MIN_REPLICATED_BYTES",
                        4 * _MB)


def _min_reshard_bytes() -> int:
    return S._bytes_env("HOROVOD_SHARD_LINT_MIN_RESHARD_BYTES", _MB)


def _min_sharded_bytes() -> int:
    return S._bytes_env("HOROVOD_SHARD_LINT_MIN_SHARDED_BYTES", _MB)


def hbm_budget_bytes() -> Optional[int]:
    """HVD303 gate; None (rule silent) when unset. Also the budget the
    bench memory stamp reports against (bench.py, docs/perf.md)."""
    return S._bytes_env("HOROVOD_HLO_LINT_HBM_BUDGET", None)


def check_hvd301(prog: HloProgram) -> Iterable[Finding]:
    if prog.num_partitions <= 1:
        return
    floor = _min_replicated_bytes()
    for p in prog.entry_params:
        spec = S.parse_sharding(p.sharding)
        if spec is None or not spec.fully_replicated:
            continue
        nb = p.type.nbytes if p.type is not None else None
        if nb is None or nb < floor:
            continue
        yield Finding(
            prog.path, p.line, HVD301,
            f"input {p.name} ({p.type}, {nb / _MB:.1f} MB) is fully "
            f"replicated across the {prog.num_partitions}-partition "
            "mesh: every device pays the full HBM cost and every "
            "update moves the full payload — shard it over a model "
            "axis (NamedSharding/PartitionSpec, docs/parallelism.md)")


_RESHARD_OPCODES = {"all_gather", "all_to_all", "collective_permute"}


def check_hvd302(prog: HloProgram) -> Iterable[Finding]:
    if prog.fmt != "hlo" or prog.num_partitions <= 1:
        return
    floor = _min_reshard_bytes()
    for op in prog.ops:
        if op.opcode not in _RESHARD_OPCODES:
            continue
        if S.traceable_to_user_collective(op):
            continue
        nb = S._result_bytes(op)
        if nb < floor:
            continue
        yield Finding(
            prog.path, op.line, HVD302,
            f"partitioner-inserted {op.opcode} moving {nb / _MB:.1f} "
            "MB inside the step body (metadata traces to "
            f"'{_origin(op)}', not to a user collective): the SPMD "
            "partitioner is resharding to reconcile inconsistent "
            "annotations — align the producer/consumer shardings "
            "(docs/static_analysis.md)")


def _origin(op: HloOp) -> str:
    m = S._OP_NAME_RE.search(op.attrs)
    if not m:
        return "<no metadata>"
    return m.group(1).rsplit("/", 1)[-1] or "<no metadata>"


def check_hvd303(prog: HloProgram) -> Iterable[Finding]:
    budget = hbm_budget_bytes()
    if budget is None or prog.fmt != "hlo":
        return
    est = S.peak_memory(prog)
    if est is None or est.peak_bytes <= budget:
        return
    top = ", ".join(f"{n} {b / _MB:.1f} MB" for n, b in est.top)
    yield Finding(
        prog.path, est.peak_line, HVD303,
        f"static per-device peak-HBM estimate {est.peak_bytes / _MB:.1f}"
        f" MB exceeds the {budget / _MB:.1f} MB budget "
        "(HOROVOD_HLO_LINT_HBM_BUDGET) — this program OOMs at run "
        f"time; largest live buffers at the peak: {top}; donate dead "
        "inputs, shard the big tensors, or rematerialize "
        "(docs/static_analysis.md peak-memory model)")


def check_hvd304(prog: HloProgram) -> Iterable[Finding]:
    if prog.fmt != "stablehlo" or prog.num_partitions <= 1:
        return
    floor = _min_sharded_bytes()
    tensors = [t for t in S.annotated_tensors(prog)
               if t.type is not None and t.type.nbytes is not None
               and t.type.nbytes >= floor]
    if not tensors:
        return
    classes = S.partition_classes(tensors, prog.num_partitions)
    if classes is None or classes >= prog.num_partitions:
        return
    waste = prog.num_partitions // max(classes, 1)
    line = min(t.line for t in tensors)
    yield Finding(
        prog.path, line, HVD304,
        f"the mesh carries {prog.num_partitions} partitions but the "
        f"program's sharding only distinguishes {classes} device "
        f"group(s): {waste}x of the mesh holds identical shards of "
        f"every tensor >= {floor / _MB:.1f} MB — a mesh axis is paid "
        "for but shards nothing (drop the axis or shard a major "
        "tensor over it, docs/parallelism.md)")


_SLICE_OPCODES = {"dynamic_slice", "slice"}


def check_hvd305(prog: HloProgram) -> Iterable[Finding]:
    floor = _min_reshard_bytes()
    for op in prog.ops:
        if op.opcode != "all_reduce" or not op.result:
            continue
        nb = hlo_rules._collective_payload(op)
        if nb is None or nb < floor:
            continue
        uses = prog.uses(op.scope, op.result)
        if not uses:
            continue
        if all(u.opcode in _SLICE_OPCODES for u in uses):
            yield Finding(
                prog.path, op.line, HVD305,
                f"all_reduce of {nb / _MB:.1f} MB whose every consumer "
                "immediately slices out one shard: every device "
                "materializes the full reduction only to keep 1/k of "
                "it — use reduce_scatter (lax.psum_scatter) for k x "
                "less peak HBM and (k-1)/k less wire traffic "
                "(docs/parallelism.md)")


RULES = {
    HVD301: ("tensor above the replication threshold fully replicated "
             "across a >1-partition mesh", check_hvd301),
    HVD302: ("partitioner-inserted resharding collective (all-gather/"
             "all-to-all/collective-permute not traceable to a user "
             "collective) above the reshard threshold", check_hvd302),
    HVD303: ("static per-device peak-HBM estimate exceeds "
             "HOROVOD_HLO_LINT_HBM_BUDGET (compile-time OOM gate)",
             check_hvd303),
    HVD304: ("mesh axis paid for but sharding no tensor above the "
             "threshold (unused parallelism)", check_hvd304),
    HVD305: ("all-reduce whose every consumer keeps only its own "
             "shard (should be reduce-scatter/psum_scatter)",
             check_hvd305),
}
