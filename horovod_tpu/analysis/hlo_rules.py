"""hvdhlo rules HVD201-HVD205: perf contracts on the lowered program.

Each rule guards a property the ROADMAP's perf work depends on and an
AST linter cannot see (docs/static_analysis.md, docs/perf.md):

HVD201  a fused all-reduce payload above the bucket cap survived to
        HLO, or every collective in a computation forms one serialized
        dependency chain — both resurrect the pre-PR-6 "single giant
        allreduce after the backward" plan the bucketed-overlap rework
        (ops/fusion.py) exists to prevent. The payload limit is
        HOROVOD_HLO_LINT_MAX_COLLECTIVE_BYTES when set, else the live
        HOROVOD_BUCKET_CAP, else the 4 MiB default — a *lifted* cap
        deliberately falls back to the default, so the exact regression
        scenario (threshold raised, cap disabled) still gates.
HVD202  infeed/outfeed/host-callback/host-transfer inside the compiled
        step body: every one is a device<->host round-trip serializing
        the step on the slow host link.
HVD203  an entry buffer that is dead after its single use but not
        donated: XLA must keep the input alive alongside the output —
        an extra HBM copy of every such tensor, per step.
HVD204  a conv/dot operand whose channel/contracting dim is not a
        multiple of the 128-wide vector lanes: the MXU pads it up and
        the padding fraction is pure wasted FLOPs — the static face of
        the conv-MFU gap (PaLM's padding guidance; ROADMAP item 1).
HVD205  a bf16->f32 upcast whose value feeds a dot/conv rather than an
        accumulator (reduce/psum): matmuls on upcast activations run
        the MXU at the f32 rate for no precision benefit — keep MXU
        inputs bf16 and let XLA accumulate in f32.

Checks are heuristics over a parsed module (`analysis/hlo.py`); false
positives are baselined (`scripts/hvdhlo_baseline.json`), not
suppressed inline — lowered text has no comment to hang a suppression
on.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Set

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis.hlo import HloOp, HloProgram, TensorType

HVD201 = "HVD201"
HVD202 = "HVD202"
HVD203 = "HVD203"
HVD204 = "HVD204"
HVD205 = "HVD205"

#: MXU vector-lane width (minor-most dim) and sublane count: the tiling
#: every TPU generation to date pads operands up to
#: (/opt/skills guide values; the PaLM padding convention).
LANE = 128
SUBLANE = 8

_MB = 1024 * 1024


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def _payload_limit_bytes() -> int:
    """HVD201 limit; see module docstring for the fallback chain."""
    explicit = os.environ.get(
        "HOROVOD_HLO_LINT_MAX_COLLECTIVE_BYTES", "").strip()
    if explicit:
        try:
            return max(int(explicit), 1)
        except ValueError:
            pass
    from horovod_tpu.common.config import DEFAULT_BUCKET_CAP_BYTES
    cap = _env_int("HOROVOD_BUCKET_CAP", DEFAULT_BUCKET_CAP_BYTES)
    return cap if cap > 0 else DEFAULT_BUCKET_CAP_BYTES


def _op_bytes(types: Iterable[Optional[TensorType]]) -> Optional[int]:
    total = 0
    saw = False
    for t in types:
        if t is None:
            continue
        nb = t.nbytes
        if nb is None:
            continue
        total += nb
        saw = True
    return total if saw else None


def _collective_payload(op: HloOp) -> Optional[int]:
    """Wire bytes of one collective: operand types when the text carries
    them, else result types (identical for all-reduce)."""
    return _op_bytes(op.operand_types) or _op_bytes(op.result_types)


_PAYLOAD_COLLECTIVES = {"all_reduce"}
_CHAIN_COLLECTIVES = {"all_reduce", "reduce_scatter", "all_gather"}


def check_hvd201(prog: HloProgram) -> Iterable[Finding]:
    limit = _payload_limit_bytes()
    per_scope: dict = {}
    for op in prog.ops:
        if op.opcode in _CHAIN_COLLECTIVES:
            per_scope.setdefault(op.scope, []).append(op)
        if op.opcode not in _PAYLOAD_COLLECTIVES:
            continue
        nbytes = _collective_payload(op)
        if nbytes is not None and nbytes > limit:
            yield Finding(
                prog.path, op.line, HVD201,
                f"fused all-reduce payload {nbytes / _MB:.1f} MB exceeds "
                f"the {limit / _MB:.1f} MB bucket cap — the single-giant-"
                "allreduce plan; gradient bucketing (ops/fusion.py, "
                "docs/perf.md) is not in effect for this program")
    for scope, colls in sorted(per_scope.items()):
        if len(colls) < 2:
            continue
        # Only gradient-scale chains matter: a tiny inherently-serial
        # pair (softmax's max->sum psums, a scalar norm before a small
        # rescale) is not the overlap regression this rule guards, so
        # the chain must carry more than the bucket cap in total.
        total = sum(_collective_payload(op) or 0 for op in colls)
        if total <= limit:
            continue
        colls.sort(key=lambda o: o.line)
        if all(prog.depends_on(colls[i + 1], colls[i])
               for i in range(len(colls) - 1)):
            yield Finding(
                prog.path, colls[0].line, HVD201,
                f"all {len(colls)} collectives in '{scope}' "
                f"({total / _MB:.1f} MB total) form one serialized "
                "dependency chain — no collective can overlap compute "
                "or another collective (docs/perf.md)")


_HOST_OPCODES = {"infeed", "outfeed"}
_HOST_TRANSFER_OPCODES = {"send", "recv", "send_done", "recv_done"}
#: custom-call targets that are host round-trips. Matched as substrings
#: of the lowercased target so jax version renames
#: (xla_python_cpu_callback -> xla_ffi_python_cpu_callback, ...) keep
#: matching; partition/sharding custom calls contain none of these.
_HOST_TARGET_MARKERS = ("callback", "host_", "tohost", "fromhost",
                        "xla_python")


def _custom_call_target(op: HloOp) -> str:
    import re
    m = re.search(r'custom_call_target="([^"]+)"', op.attrs)
    if m:
        return m.group(1)
    m = re.search(r"@([\w.$-]+)", op.attrs)
    return m.group(1) if m else ""


def check_hvd202(prog: HloProgram) -> Iterable[Finding]:
    for op in prog.ops:
        if op.opcode in _HOST_OPCODES:
            yield Finding(
                prog.path, op.line, HVD202,
                f"{op.opcode} inside the compiled step body: a device<->"
                "host transfer serializes the step on the host link — "
                "move host I/O out of the step (docs/perf.md)")
        elif op.opcode in _HOST_TRANSFER_OPCODES \
                and "is_host_transfer=true" in op.attrs:
            yield Finding(
                prog.path, op.line, HVD202,
                f"host-transfer {op.opcode} inside the compiled step "
                "body (docs/perf.md)")
        elif op.opcode == "custom_call":
            target = _custom_call_target(op)
            low = target.lower()
            if any(mk in low for mk in _HOST_TARGET_MARKERS):
                yield Finding(
                    prog.path, op.line, HVD202,
                    f"host callback '{target}' inside the compiled step "
                    "body: each call is a device->host->device round-trip "
                    "per step — gate debug callbacks out of production "
                    "steps (docs/perf.md)")


def _min_donation_bytes() -> int:
    return _env_int("HOROVOD_HLO_LINT_MIN_DONATION_BYTES", 1 * _MB)


#: Shape-preserving wrappers the partitioner threads entry values
#: through before anything consumes them: liveness must be judged past
#: them, at the real consumer.
_SHARDING_WRAPPERS = ("Sharding", "SPMDFullToShardShape",
                      "SPMDShardToFullShape")


def _dead_after_single_use(prog: HloProgram, scope: str, name: str,
                           depth: int = 0) -> bool:
    """True when `name` has exactly one consumer and that consumer
    really ends its life. Follows single-use chains through the SPMD
    sharding wrappers and into `call`ed computations (shard_map bodies)
    — the entry parameter's liveness is decided wherever the value is
    actually consumed, not at the partitioning boilerplate."""
    if depth > 6:
        return False  # give up conservatively on deep wrapper chains
    uses = prog.uses(scope, name)
    if len(uses) != 1:
        return False  # unused (XLA drops it) or live past first use
    use = uses[0]
    if use.opcode in ("return", "func_return", "tuple", "copy"):
        return False  # passthrough outputs are not reducible copies
    if use.opcode == "custom_call" and use.result \
            and _custom_call_target(use) in _SHARDING_WRAPPERS:
        return _dead_after_single_use(prog, scope, use.result, depth + 1)
    if use.opcode == "call" and name in use.operands:
        import re
        cm = re.search(r"@([\w$.-]+)", use.attrs)
        if cm:
            callee = cm.group(1)
            pos = use.operands.index(name)
            for cp in prog.params:
                if cp.scope == callee and cp.index == pos:
                    return _dead_after_single_use(prog, callee, cp.name,
                                                  depth + 1)
        return False  # unresolvable callee: don't guess
    return True


def check_hvd203(prog: HloProgram) -> Iterable[Finding]:
    floor = _min_donation_bytes()
    for p in prog.entry_params:
        if p.donated or p.type is None:
            continue
        nb = p.type.nbytes
        if nb is None or nb < floor:
            continue
        if not _dead_after_single_use(prog, p.scope, p.name):
            continue
        yield Finding(
            prog.path, p.line, HVD203,
            f"input {p.name} ({p.type}, {nb / _MB:.1f} MB) is dead "
            "after its only use but not donated — XLA keeps the buffer "
            "alive next to the output, an extra HBM copy per step; "
            "donate it (jax.jit donate_argnums, docs/perf.md)")


def _min_pad_waste_pct() -> float:
    v = os.environ.get("HOROVOD_HLO_LINT_PAD_WASTE_MIN_PCT", "").strip()
    try:
        return float(v) if v else 10.0
    except ValueError:
        return 10.0


def _pad_waste_pct(dim: int, width: int) -> float:
    padded = -(-dim // width) * width
    return (1.0 - dim / padded) * 100.0


def _check_lane_dim(prog: HloProgram, op: HloOp, what: str,
                    dim: int) -> Iterable[Finding]:
    if dim <= 0 or dim % LANE == 0:
        return
    waste = _pad_waste_pct(dim, LANE)
    if waste < _min_pad_waste_pct():
        return
    yield Finding(
        prog.path, op.line, HVD204,
        f"{op.opcode} {what} = {dim} is not a multiple of the {LANE}-"
        f"wide vector lanes: ~{waste:.1f}% of the op's FLOPs are "
        "padding — pad the channel/feature dim to the lane width "
        "(docs/perf.md, ROADMAP conv-MFU item)")


def _dims_list(text: str) -> List[int]:
    import re
    return [int(t) for t in re.findall(r"\d+", text)]


def check_hvd204(prog: HloProgram) -> Iterable[Finding]:
    import re
    for op in prog.ops:
        if op.opcode in ("dot", "dot_general"):
            lhs, rhs = (op.operand_types + (None, None))[:2]
            sides = []
            if prog.fmt == "stablehlo":
                m = re.search(
                    r"contracting_dims\s*=\s*\[([\d, ]*)\]\s*x\s*"
                    r"\[([\d, ]*)\]", op.attrs)
                if m:
                    sides = [("lhs", lhs, m.group(1)),
                             ("rhs", rhs, m.group(2))]
            else:
                for side, t, pat in (
                        ("lhs", lhs, r"lhs_contracting_dims=\{([\d,]*)\}"),
                        ("rhs", rhs, r"rhs_contracting_dims=\{([\d,]*)\}")):
                    g = re.search(pat, op.attrs)
                    if g:
                        sides.append((side, t, g.group(1)))
            for side, t, dims_text in sides:
                if t is None:
                    continue
                # XLA collapses all contracting dims into ONE extent
                # before tiling, so the PRODUCT is what pads to the
                # lane width (a (16,64)x... backward dL/dW contraction
                # is a 1024-extent — aligned — not two unaligned dims).
                extent = 1
                known = False
                for d in _dims_list(dims_text):
                    if d < len(t.dims):
                        extent *= t.dims[d]
                        known = True
                if known:
                    yield from _check_lane_dim(
                        prog, op, f"{side} contracting extent", extent)
        elif op.opcode == "convolution":
            lhs, rhs = (op.operand_types + (None, None))[:2]
            if prog.fmt == "stablehlo":
                m = re.search(
                    r"dim_numbers\s*=\s*\[([^\]]*)\]x\[([^\]]*)\]",
                    op.attrs)
                if not m:
                    continue
                lspec = [t.strip() for t in m.group(1).split(",")]
                rspec = [t.strip() for t in m.group(2).split(",")]
            else:
                m = re.search(r"dim_labels=(\w+)_(\w+)->", op.attrs)
                if not m:
                    continue
                lspec, rspec = list(m.group(1)), list(m.group(2))
            if lhs is not None and "f" in lspec \
                    and len(lhs.dims) == len(lspec):
                yield from _check_lane_dim(
                    prog, op, "input channel dim",
                    lhs.dims[lspec.index("f")])
            if rhs is not None and len(rhs.dims) == len(rspec):
                for label, what in (("i", "kernel input-feature dim"),
                                    ("o", "kernel output-feature dim")):
                    if label in rspec:
                        yield from _check_lane_dim(
                            prog, op, what, rhs.dims[rspec.index(label)])


#: Ops a value flows through unchanged enough that an upcast before
#: them is really an upcast of whatever they feed.
_PASSTHROUGH = {
    "add", "subtract", "multiply", "divide", "negate", "abs", "tanh",
    "exponential", "exp", "log", "logistic", "power", "pow", "sqrt",
    "rsqrt", "maximum", "minimum", "max", "min", "select", "clamp",
    "broadcast", "broadcast_in_dim", "reshape", "transpose", "slice",
    "concatenate", "pad", "copy", "bitcast", "dynamic_slice",
    "dynamic_update_slice", "rem", "floor", "ceil", "round",
    "round_nearest_even", "sign", "expm1", "log_plus_one", "log1p",
}
_MXU_OPS = {"dot", "dot_general", "convolution"}
_SOURCE_DTYPES = {"bf16", "f16"}


def check_hvd205(prog: HloProgram) -> Iterable[Finding]:
    for op in prog.ops:
        if op.opcode != "convert" or not op.result:
            continue
        src = op.operand_types[0] if op.operand_types else None
        dst = op.result_types[0] if op.result_types else None
        if src is None or dst is None:
            continue
        if src.dtype.lower() not in _SOURCE_DTYPES \
                or dst.dtype.lower() != "f32":
            continue
        hit = _reaches_mxu(prog, op)
        if hit is not None:
            yield Finding(
                prog.path, op.line, HVD205,
                f"f32 upcast of {src} feeds {hit.opcode} (line "
                f"{hit.line}) rather than an accumulator: the matmul "
                "runs at the f32 MXU rate for no precision benefit — "
                "keep MXU inputs bf16 and accumulate in f32 "
                "(preferred_element_type; docs/perf.md)")


def _reaches_mxu(prog: HloProgram, op: HloOp,
                 max_visits: int = 256) -> Optional[HloOp]:
    """First dot/conv the upcast value reaches through passthrough ops;
    None when every path ends in an accumulator/other sink."""
    seen: Set[str] = set()
    frontier = [op]
    visits = 0
    while frontier and visits < max_visits:
        cur = frontier.pop()
        if not cur.result or cur.result in seen:
            continue
        seen.add(cur.result)
        visits += 1
        for use in prog.uses(cur.scope, cur.result):
            if use.opcode in _MXU_OPS:
                return use
            if use.opcode in _PASSTHROUGH and use.result:
                frontier.append(use)
    return None


RULES = {
    HVD201: ("fused all-reduce payload above the bucket cap, or all "
             "collectives serialized in one dependency chain",
             check_hvd201),
    HVD202: ("infeed/outfeed/host callback inside the compiled step",
             check_hvd202),
    HVD203: ("large input dead after first use but not donated",
             check_hvd203),
    HVD204: ("conv/dot channel or contracting dim not a multiple of "
             "the 128-lane MXU width (padding waste)", check_hvd204),
    HVD205: ("f32 upcast of a bf16 tensor feeding a matmul/conv "
             "instead of an accumulator", check_hvd205),
}
