"""hvdnum rules HVD501-HVD505: numerics & reduction-semantics contracts
on the lowered program (docs/static_analysis.md).

Each rule guards a property that corrupts training *silently* — no
hang, no crash, just a model that converges worse or resumes
differently — and that is checkable at compile time from the analysis
state ``analysis/numerics.py`` builds (dtype-flow lattice +
gradient-scale table):

HVD501  a dot/conv whose accumulation type is bf16/fp16/f8: every
        partial-product add rounds at the narrow precision, and with
        contraction extents in the thousands the accumulated error
        dwarfs the storage rounding. The fix is free on TPU —
        ``preferred_element_type=f32`` keeps MXU inputs narrow and
        accumulates wide.
HVD502  a precision-dropping convert on a gradient path *before* its
        reduce collective: downcast-then-reduce rounds every summand
        first and then accumulates k rounded values; reduce-then-
        downcast rounds once, after the sum. The ordering is a pure
        win and the wire cost is identical when the reduce runs on the
        narrow type post-sum.
HVD503  a gradient-scale mismatch: the explicit divide/multiply that
        normalizes a reduced gradient uses a constant equal to the
        world/partition count (or another group's size) instead of the
        *reducing group's* size — the classic Horovod sum-vs-mean
        footgun, including the elastic case where the baked constant
        goes stale on the first rescale and silently shifts the
        effective learning rate.
HVD504  determinism hazards that void bit-identical resume: a fused
        multi-operand fp reduction (combining order across the fused
        operands is schedule-dependent), a keyless rng op (implicit
        per-device generator state does not survive a restore), or a
        reduce whose replica groups have unequal sizes (per-device
        combining trees differ in shape, so fp rounding differs across
        replicas).
HVD505  cross-mesh gradient-scale inequivalence: two programs lowered
        from the SAME step under different mesh shapes (the
        different-mesh-restore pair) disagree on a reduction's
        effective multiplier — restoring a checkpoint between them
        changes the effective learning rate. Vacuous on a single
        program: arm it by linting the pair as one set
        (``--num a.hlo b.hlo``).

False positives are baselined (``scripts/hvdnum_baseline.json``), not
suppressed inline — lowered text has no comment to hang a suppression
on.
"""

from __future__ import annotations

from typing import Iterable, List

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis import numerics as N

HVD501 = "HVD501"
HVD502 = "HVD502"
HVD503 = "HVD503"
HVD504 = "HVD504"
HVD505 = "HVD505"

_MXU_OPS = ("dot", "dot_general", "convolution")


def check_hvd501(nset: "N.NumericsSet") -> Iterable[Finding]:
    allow = N.allowed_accum()
    for np_ in nset.programs:
        for op in np_.prog.ops:
            if op.opcode not in _MXU_OPS:
                continue
            out = N._fp_dtype(op.result_types[0]
                              if op.result_types else None)
            src = None
            for t in op.operand_types:
                src = N._fp_dtype(t)
                if src:
                    break
            if not out or not src:
                continue
            if src in N.LOW_PRECISION and out in N.LOW_PRECISION \
                    and out not in allow:
                yield Finding(
                    np_.path, op.line, HVD501,
                    f"{op.opcode} accumulates in {out}: {src} inputs "
                    "with no f32 accumulation type — every partial-"
                    f"product add rounds at {out} precision and the "
                    "contraction magnifies the error; request "
                    "preferred_element_type=f32 (narrow inputs, wide "
                    "accumulator) and downcast after the reduce")


def check_hvd502(nset: "N.NumericsSet") -> Iterable[Finding]:
    floor = N.min_reduce_bytes()
    for np_ in nset.programs:
        for r in np_.reductions:
            if r.nbytes < floor:
                continue
            for o in r.op.operands:
                f = np_.flow.get((r.op.scope, o))
                if f is None or f.narrowed_at is None \
                        or f.width is None or f.width >= f.max_width:
                    continue
                yield Finding(
                    np_.path, r.op.line, HVD502,
                    f"downcast-then-reduce: this {r.event.opcode} "
                    f"combines {r.dtype} values narrowed by the "
                    f"convert at line {f.narrowed_at.line} — every "
                    f"summand rounds BEFORE the {r.group_size}-way "
                    "reduction accumulates; reduce first and downcast "
                    "the single result after (reduce-then-downcast), "
                    "or keep the gradient path f32")
                break  # one finding per reduction


def check_hvd503(nset: "N.NumericsSet") -> Iterable[Finding]:
    floor = N.min_reduce_bytes()
    tol = N.scale_tol()
    for np_ in nset.programs:
        counts = {r.group_size for r in np_.reductions}
        if np_.prog.num_partitions > 1:
            counts.add(np_.prog.num_partitions)
        if np_.schedule.num_devices > 1:
            counts.add(np_.schedule.num_devices)
        for r in np_.reductions:
            if r.nbytes < floor or r.dynamic or r.divisor is None:
                continue
            k = r.group_size
            if N.close(r.divisor, k, tol):
                continue  # true mean over the reducing group
            hit = next((c for c in sorted(counts)
                        if c != k and N.close(r.divisor, c, tol)), None)
            if hit is None:
                continue  # arbitrary math constant, not a group count
            yield Finding(
                np_.path, r.op.line, HVD503,
                f"gradient-scale mismatch: this {r.event.opcode} "
                f"reduces over a {k}-member group but the scale at "
                f"line {r.divisor_line} divides by {r.divisor:g} — a "
                f"baked world/partition count ({hit}), not the "
                "reducing group's size; after an elastic rescale or "
                "process-set change the constant goes stale and the "
                f"effective learning rate shifts {k / r.divisor:g}x "
                "from the intended mean — divide by the live group "
                "size instead")


def check_hvd504(nset: "N.NumericsSet") -> Iterable[Finding]:
    for np_ in nset.programs:
        for r in np_.reductions:
            fp_operands = [t for t in r.op.operand_types
                           if N._fp_dtype(t)]
            if len(r.op.operands) >= 2 and len(fp_operands) >= 2:
                yield Finding(
                    np_.path, r.op.line, HVD504,
                    f"unordered multi-operand fp reduction: this "
                    f"{r.event.opcode} fuses {len(r.op.operands)} fp "
                    "operands into one combining step — the order the "
                    "fused buffers round in is schedule-dependent, so "
                    "a re-lowered or re-bucketed program resumes with "
                    "different bits; reduce per tensor (or pin the "
                    "bucket composition) for bit-identical resume")
            sizes = sorted({len(g) for g in r.event.groups})
            if len(sizes) > 1:
                yield Finding(
                    np_.path, r.op.line, HVD504,
                    f"reduction-tree shape divergence: this "
                    f"{r.event.opcode} partitions replicas into groups "
                    f"of sizes {sizes} — per-device schedules disagree "
                    "on the combining tree, fp rounding differs across "
                    "replicas, and a restore onto a differently-sized "
                    "group is not bit-identical; use equal-size groups "
                    "for gradient reductions")
        for op in np_.prog.ops:
            if op.opcode in N.KEYLESS_RNG_OPS:
                yield Finding(
                    np_.path, op.line, HVD504,
                    f"keyless rng: {op.opcode} draws from implicit "
                    "per-device generator state, which a checkpoint "
                    "restore does not replay — the resumed run "
                    "diverges bitwise at the first draw; thread an "
                    "explicit key (jax.random / rng_bit_generator) "
                    "through the step instead")


def check_hvd505(nset: "N.NumericsSet") -> Iterable[Finding]:
    progs = nset.programs
    if len(progs) < 2:
        return
    tol = N.scale_tol()
    for i in range(len(progs)):
        for j in range(i + 1, len(progs)):
            a, b = progs[i], progs[j]
            if not a.reductions \
                    or len(a.reductions) != len(b.reductions):
                continue  # not a lowering pair of one step
            for x, y in zip(a.reductions, b.reductions):
                mx, my = x.multiplier, y.multiplier
                if mx is None or my is None or N.close(mx, my, tol):
                    continue
                yield Finding(
                    b.path, y.op.line, HVD505,
                    "cross-mesh gradient-scale inequivalence: this "
                    f"{y.event.opcode} applies effective multiplier "
                    f"{my:g} (group {y.group_size}, divisor "
                    f"{y.divisor if y.divisor is not None else 'none'})"
                    f" but its mesh twin {a.path}:{x.op.line} applies "
                    f"{mx:g} (group {x.group_size}) — restoring a "
                    "checkpoint between these mesh shapes changes the "
                    f"effective learning rate {my / mx:g}x; normalize "
                    "each reduction by its own group's size (true "
                    "mean) so the invariant holds under any mesh")


RULES = {
    HVD501: ("dot/conv accumulating in bf16/fp16/f8 — no f32 "
             "accumulation type", check_hvd501),
    HVD502: ("precision-dropping convert on a gradient path before "
             "its reduce (downcast-then-reduce ordering)",
             check_hvd502),
    HVD503: ("gradient-scale divisor is a baked world/partition "
             "count, not the reducing group's size (stale on elastic "
             "rescale)", check_hvd503),
    HVD504: ("determinism hazard voiding bit-identical resume: "
             "multi-operand fp reduction, keyless rng, or divergent "
             "reduction-tree shape", check_hvd504),
    HVD505: ("cross-mesh gradient-scale inequivalence between "
             "programs lowered from one step (effective LR changes "
             "on restore)", check_hvd505),
}
