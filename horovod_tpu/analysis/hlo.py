"""hvdhlo: structural analysis of the lowered XLA step program.

hvdlint (PR 3-4) sees Python source; the perf properties the ROADMAP
cares about — gradient-comms overlap, buffer donation, layout padding,
host round-trips — are properties of the *lowered program* and invisible
to an AST linter. This module parses the two textual forms the toolchain
already produces for free and hands a uniform op/def-use model to the
HVD2xx rules (``analysis/hlo_rules.py``):

* **StableHLO MLIR** — ``jax.jit(f).lower(*args).as_text()``, the cheap
  pre-optimization form bench and perfscope already lower for cost
  analysis. Donation shows up as ``jax.buffer_donor``/
  ``tf.aliasing_output`` argument attributes.
* **HLO text** — ``lowered.compile().as_text()`` or a dumped
  ``*.before_optimizations.txt`` module. Donation shows up in the
  module-level ``input_output_alias`` map.

The parser is deliberately line-structural, not a grammar: it recovers
(result, opcode, operands, operand/result tensor types, attribute text)
per instruction plus entry parameters and their donation bits — exactly
what the rules consume — and ignores everything else. A formatting
drift in a field no rule reads therefore cannot break the lint.

Findings ride the existing driver machinery (`driver.Finding`,
``file:line RULE-ID msg``, ``--format json``, ``--baseline``); there are
no source comments in lowered text, so HLO findings are silenced via the
baseline file (``scripts/hvdhlo_baseline.json``), not inline
suppressions. Findings feed ``hvdhlo_findings_total{rule}``
(docs/observability.md). See docs/static_analysis.md for the rule
catalog and docs/perf.md for the CI gate (``make hlo-lint``).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from horovod_tpu.analysis.driver import Finding

#: Bytes per element for the dtypes XLA prints. Unknown dtypes parse to
#: itemsize None and size-based rules skip the value instead of guessing.
DTYPE_BYTES = {
    "pred": 1, "i1": 1, "s8": 1, "u8": 1, "i8": 1, "ui8": 1,
    "s16": 2, "u16": 2, "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "i32": 4, "ui32": 4, "f32": 4,
    "s64": 8, "u64": 8, "i64": 8, "ui64": 8, "f64": 8,
    "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
}


@dataclasses.dataclass(frozen=True)
class TensorType:
    """One tensor type: dtype token + static dims (None on dynamic)."""

    dtype: str
    dims: Tuple[int, ...]

    @property
    def elems(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def itemsize(self) -> Optional[int]:
        return DTYPE_BYTES.get(self.dtype.lower())

    @property
    def nbytes(self) -> Optional[int]:
        i = self.itemsize
        return None if i is None else self.elems * i

    def __str__(self) -> str:
        dims = "x".join(str(d) for d in self.dims)
        return f"{self.dtype}[{dims}]" if self.dims else f"{self.dtype}[]"


@dataclasses.dataclass
class HloOp:
    """One instruction, normalized across the two textual forms."""

    line: int                     # 1-based line in the analyzed text
    result: str                   # "%23" ("" for results-less ops)
    opcode: str                   # canonical: all_reduce, dot_general, ...
    operands: Tuple[str, ...]     # SSA names, '#i' projections stripped
    operand_types: Tuple[Optional[TensorType], ...]
    result_types: Tuple[Optional[TensorType], ...]
    attrs: str                    # raw remainder text for attr regexes
    scope: str                    # enclosing function / computation name
    #: Scalar value of a ``constant`` op's literal (both textual forms,
    #: incl. scientific notation, typed ``bf16[] 8`` spellings and MLIR
    #: ``dense<>`` splats); None for non-constants and non-scalars.
    literal: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class HloParam:
    """One computation parameter (entry, `call`ee, or shard_map body —
    sharding/donation attrs are recorded uniformly at every function
    boundary, not just the entry signature)."""

    index: int
    name: str                     # "%arg0" / "%p.1"
    type: Optional[TensorType]
    donated: bool
    scope: str
    line: int
    #: Raw sharding annotation text ("{replicated}",
    #: "{devices=[2,4]<=[8]}", ...) or None when unannotated. The
    #: sharding-aware layer (analysis/shard.py) interprets it.
    sharding: Optional[str] = None


class HloProgram:
    """Parsed module: op list + def/use indexes the rules query."""

    def __init__(self, path: str, ops: List[HloOp],
                 params: List[HloParam], entry_scope: str,
                 fmt: str, num_partitions: int = 1) -> None:
        self.path = path
        self.ops = ops
        self.params = params
        self.entry_scope = entry_scope
        self.fmt = fmt  # "stablehlo" | "hlo"
        #: SPMD partition count (mhlo.num_partitions module attr /
        #: HloModule header); 1 for unpartitioned programs.
        self.num_partitions = num_partitions
        self._defs: Dict[Tuple[str, str], HloOp] = {}
        self._uses: Dict[Tuple[str, str], List[HloOp]] = {}
        for op in ops:
            if op.result:
                self._defs.setdefault((op.scope, op.result), op)
            for o in op.operands:
                self._uses.setdefault((op.scope, o), []).append(op)

    @property
    def entry_params(self) -> List[HloParam]:
        return [p for p in self.params if p.scope == self.entry_scope]

    def defining(self, scope: str, name: str) -> Optional[HloOp]:
        return self._defs.get((scope, name))

    def uses(self, scope: str, name: str) -> List[HloOp]:
        return self._uses.get((scope, name), [])

    def depends_on(self, op: HloOp, target: HloOp,
                   max_visits: int = 4096) -> bool:
        """True when `op` transitively consumes `target`'s result
        (same-scope def-use reachability; the overlap-chain query)."""
        if op.scope != target.scope or not target.result:
            return False
        seen: Set[str] = set()
        frontier = list(op.operands)
        visits = 0
        while frontier and visits < max_visits:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            visits += 1
            if name == target.result:
                return True
            d = self.defining(op.scope, name)
            if d is not None:
                frontier.extend(d.operands)
        return False


# ------------------------------------------------------------- parsing

_TENSOR_RE = re.compile(r"tensor<([^<>]*?)>")
_HLO_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+[a-z0-9]*|pred)\[([0-9,]*)\]")
_SSA_RE = re.compile(r"%[\w.-]+")


def _parse_mlir_tensor(inner: str) -> Optional[TensorType]:
    """``2x8x8x64xbf16`` / ``f32`` / ``?x128xf32`` -> TensorType|None."""
    parts = inner.split("x")
    dims: List[int] = []
    for i, p in enumerate(parts):
        p = p.strip()
        if p.isdigit():
            dims.append(int(p))
            continue
        if p == "?":
            return None  # dynamic: size-based rules must skip
        dtype = "x".join(parts[i:]).strip()
        # complex<f32> etc. keep their full token; lookup just misses.
        return TensorType(dtype, tuple(dims))
    return None


def _mlir_types(segment: str) -> List[Optional[TensorType]]:
    """Every tensor<> type in `segment`, in order (non-tensor -> None
    is NOT emitted; callers align by count only when it matches)."""
    return [_parse_mlir_tensor(m.group(1))
            for m in _TENSOR_RE.finditer(segment)]


def _hlo_types(segment: str) -> List[Optional[TensorType]]:
    return [TensorType(m.group(1),
                       tuple(int(d) for d in m.group(2).split(",") if d))
            for m in _HLO_SHAPE_RE.finditer(segment)]


def _operand_names(segment: str) -> Tuple[str, ...]:
    return tuple(m.group(0).split("#")[0]
                 for m in _SSA_RE.finditer(segment))


# Constant literals, both textual forms. XLA prints scalars plain
# (``constant(8)``), in scientific notation (``constant(1.25e-05)``)
# and — for the narrow dtypes — typed (``constant(bf16[] 8)``,
# ``constant(f8e4m3fn[] 1.5e-2)``); StableHLO prints ``dense<>`` attrs
# (``dense<1.250000e-01>``). The number grammar must cover all of them:
# a literal the parser cannot read is a silently skipped operand, and
# the HVD503 divisor extraction then misses the baked scale constant.
_LITERAL_NUM_RE = re.compile(
    r"[-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?")
_MLIR_DENSE_RE = re.compile(r"dense<(.*)>\s*$", re.DOTALL)


def parse_literal(text: str) -> Optional[float]:
    """Scalar value of one constant literal, or None when the literal
    is non-scalar (array/tuple braces, hex-encoded dense blobs) — the
    caller must then skip the value rather than guess."""
    s = text.strip()
    m = _MLIR_DENSE_RE.match(s)
    if m:
        s = m.group(1).strip()
    # typed scalar literal: a leading `dtype[]` token before the value
    tm = _HLO_SHAPE_RE.match(s)
    if tm and tm.start() == 0:
        if tm.group(2).strip():
            return None  # shaped literal: `f32[2] {1, 2}` is not scalar
        s = s[tm.end():].strip()
    if not s or s[0] in "{[\"":
        return None  # array / tuple / hex-string literal
    low = s.lower()
    if low in ("true", "false"):
        return 1.0 if low == "true" else 0.0
    if low in ("inf", "+inf", "-inf", "nan"):
        return float(low)
    if _LITERAL_NUM_RE.fullmatch(s):
        return float(s)
    return None


def constant_value(op: "HloOp") -> Optional[float]:
    """The scalar a ``constant`` op defines; None for anything else.
    The HVD503 gradient-scale rules resolve explicit divide/multiply
    scale factors through this accessor."""
    return op.literal if op.opcode == "constant" else None


# StableHLO op header: `%23 = "stablehlo.all_reduce"(%22) <{...}> ({`
# or `%0 = stablehlo.dot_general %arg0, %arg1, ... : (T, T) -> T`
# or `stablehlo.return %25 : tensor<f32>` / `return %1 : tensor<...>`.
_MLIR_OP_RE = re.compile(
    r"^\s*(?:(%[\w]+)(?::\d+)?\s*=\s*)?"
    r'"?([a-zA-Z_][\w$]*\.)?([a-zA-Z_][\w$-]*)"?\s*(?=[ (%<"@]|$)')
_MLIR_FUNC_RE = re.compile(
    r"^\s*func\.func\s+(?:(public|private)\s+)?@([\w$-]+)\s*\((.*)$")
# The attr dict may nest braces two levels (mhlo.sharding strings like
# {jax.buffer_donor = true, mhlo.sharding = "{devices=[2,4]<=[8]
# last_tile_dims={replicated}}"}) — the donation bit and the sharding
# string must both survive riding alongside each other.
_MLIR_ARG_RE = re.compile(
    r"(%arg\d+):\s*"
    r"([^,){]+(?:\{(?:[^{}]|\{(?:[^{}]|\{[^{}]*\})*\})*\})?)")
_MLIR_SHARDING_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_MLIR_NUM_PARTITIONS_RE = re.compile(
    r"mhlo\.num_partitions\s*=\s*(\d+)")
# HLO text: `sharding={devices=[4,1,2]<=[2,4]T(1,0)
# last_tile_dim_replicate}` / `sharding={replicated}` instruction attr
# (entry parameters keep their annotation through SPMD partitioning).
_HLO_SHARDING_RE = re.compile(
    r"sharding=(\{(?:[^{}]|\{[^{}]*\})*\})")
_HLO_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)")


def op_sharding(op: HloOp) -> Optional[str]:
    """The raw sharding annotation carried by one instruction, for BOTH
    textual forms: ``mhlo.sharding = "..."`` on a StableHLO custom-call
    (`@Sharding` = `with_sharding_constraint`), ``sharding={...}`` on an
    HLO-text instruction. None when the op is unannotated."""
    m = _MLIR_SHARDING_RE.search(op.attrs)
    if m:
        return m.group(1)
    m = _HLO_SHARDING_RE.search(op.attrs)
    return m.group(1) if m else None

#: MLIR keywords the op regex would otherwise read as opcodes.
_MLIR_NOISE = {"module", "func", "}", "{", "^bb0", "cond", "do"}


def _parse_stablehlo(text: str, path: str) -> HloProgram:
    ops: List[HloOp] = []
    params: List[HloParam] = []
    entry_scope = ""
    scope = ""
    num_partitions = 1
    # stack of (op, brace_balance_at_open) for region ops whose result
    # type arrives on the closing `}) : (...) -> ...` line
    pending: List[HloOp] = []
    lines = text.splitlines()
    for lineno, raw in enumerate(lines, 1):
        line = raw.strip()
        if not line or line.startswith("//"):
            continue
        if line.startswith("module"):
            pm = _MLIR_NUM_PARTITIONS_RE.search(line)
            if pm:
                num_partitions = int(pm.group(1))
            continue
        fm = _MLIR_FUNC_RE.match(raw)
        if fm:
            vis, name, argtext = fm.group(1), fm.group(2), fm.group(3)
            scope = name
            if vis == "public" or (not entry_scope and name == "main"):
                entry_scope = name
            for i, am in enumerate(_MLIR_ARG_RE.finditer(argtext)):
                arg, typetext = am.group(1), am.group(2)
                types = _mlir_types(typetext)
                donated = ("jax.buffer_donor" in typetext
                           or "tf.aliasing_output" in typetext)
                sm = _MLIR_SHARDING_RE.search(typetext)
                params.append(HloParam(i, arg, types[0] if types else None,
                                       donated, scope, lineno,
                                       sm.group(1) if sm else None))
            continue
        if line.startswith("})"):
            # close of a region op: its functional type rides here
            _, _, typesig = line.partition(":")
            if pending:
                op = pending.pop()
                ins, _, outs = typesig.partition("->")
                op.operand_types = tuple(_mlir_types(ins))
                op.result_types = tuple(_mlir_types(outs))
            continue
        m = _MLIR_OP_RE.match(raw)
        if not m:
            continue
        result = m.group(1) or ""
        opcode = m.group(3)
        if opcode in _MLIR_NOISE or line.startswith("^"):
            continue
        opcode = opcode.replace("-", "_")
        rest = raw[m.end():]
        # the trailing ` : type` annotation (absent on region openers)
        body, _, typesig = rest.rpartition(" : ")
        if not body:
            body, typesig = rest, ""
        operand_types: Tuple[Optional[TensorType], ...] = ()
        result_types: Tuple[Optional[TensorType], ...] = ()
        if "->" in typesig:
            ins, _, outs = typesig.partition("->")
            operand_types = tuple(_mlir_types(ins))
            result_types = tuple(_mlir_types(outs))
        elif typesig:
            result_types = tuple(_mlir_types(typesig))
        op = HloOp(lineno, result, opcode, _operand_names(body),
                   operand_types, result_types, rest.strip(), scope,
                   parse_literal(body) if opcode == "constant" else None)
        ops.append(op)
        # `({` with no matching `})` on the same line opens a region
        if rest.count("({") > rest.count("})"):
            pending.append(op)
    return HloProgram(path, ops, params, entry_scope or "main",
                      "stablehlo", num_partitions)


# HLO text: `  %all-reduce.2 = f32[256,256]{1,0} all-reduce(f32[...] %x),
# channel_id=1, ...` inside `ENTRY %main ... {` ... `}` computations.
# XLA prints the `%` name sigil in some modes and omits it in others;
# both spellings are accepted.
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%?[\w.-]+)\s*=\s*(.+?)\s([a-z][a-z0-9-]*)\((.*)$")
_HLO_COMP_RE = re.compile(
    r"^\s*(ENTRY\s+)?(%?[\w.-]+)\s.*->\s.*\{\s*$")
_HLO_ALIAS_RE = re.compile(
    r"input_output_alias=\{([^{}]*(?:\{[^{}]*\}[^{}]*)*)\}")


def _hlo_alias_params(header: str) -> Set[int]:
    """Donated parameter numbers from the module-level alias map:
    ``{0}: (0, {}, may-alias)`` -> param 0."""
    m = _HLO_ALIAS_RE.search(header)
    if not m:
        return set()
    return {int(g) for g in re.findall(r"\(\s*(\d+)\s*,", m.group(1))}


def _split_args(segment: str) -> Tuple[str, str]:
    """(arg list, attr remainder) of an instruction tail, honoring
    nested parens: ``f32[2]{0} %a, %b), channel_id=1`` splits at the
    close paren matching the opcode's open."""
    depth = 0
    for i, ch in enumerate(segment):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            if ch == ")" and depth == 0:
                return segment[:i], segment[i + 1:]
            depth -= 1
    return segment, ""


def _parse_hlo_text(text: str, path: str) -> HloProgram:
    ops: List[HloOp] = []
    params: List[HloParam] = []
    entry_scope = ""
    scope = ""
    in_entry = False
    donated: Set[int] = set()
    num_partitions = 1
    lines = text.splitlines()
    for lineno, raw in enumerate(lines, 1):
        if raw.startswith("HloModule"):
            donated = _hlo_alias_params(raw)
            pm = _HLO_NUM_PARTITIONS_RE.search(raw)
            if pm:
                num_partitions = int(pm.group(1))
            continue
        im = _HLO_INSTR_RE.match(raw)
        if im:
            result, typetext, opcode, tail = im.groups()
            args, attrs = _split_args(tail)
            opcode = opcode.replace("-", "_")
            op = HloOp(lineno, result, opcode, _operand_names(args),
                       tuple(_hlo_types(args)), tuple(_hlo_types(typetext)),
                       attrs.strip(", "), scope,
                       parse_literal(args) if opcode == "constant"
                       else None)
            ops.append(op)
            if opcode == "parameter":
                pm = re.match(r"\s*(\d+)", args)
                idx = int(pm.group(1)) if pm else len(params)
                params.append(HloParam(
                    idx, result, op.result_types[0] if op.result_types
                    else None, in_entry and idx in donated, scope, lineno,
                    op_sharding(op)))
            continue
        cm = _HLO_COMP_RE.match(raw)
        if cm and "=" not in raw.split("->")[0]:
            in_entry = bool(cm.group(1))
            scope = cm.group(2)
            if in_entry:
                entry_scope = scope
    # parameters of non-entry computations are never donation candidates
    # (only the entry alias map carries donation bits), but they DO keep
    # their sharding attrs — call/shard_map boundaries are recorded
    # uniformly with the entry signature.
    return HloProgram(path, ops, params, entry_scope, "hlo",
                      num_partitions)


def parse(text: str, path: str = "<hlo>") -> HloProgram:
    """Parse either textual form; dispatch by content."""
    head = text[:4096]
    if "HloModule" in head:
        return _parse_hlo_text(text, path)
    return _parse_stablehlo(text, path)


# ------------------------------------------------------------- linting

def registry() -> Dict[str, Tuple[str, object]]:
    """rule_id -> (description, check(program) -> iterable[Finding])."""
    from horovod_tpu.analysis import hlo_rules
    return dict(hlo_rules.RULES)


def lint_text(text: str, path: str = "<hlo>",
              select: Optional[Sequence[str]] = None,
              ignore: Sequence[str] = ()) -> List[Finding]:
    """Run the HVD2xx rules over one lowered module's text."""
    prog = parse(text, path)
    wanted = {r.upper() for r in select} if select is not None else None
    ignored = {r.upper() for r in ignore}
    out: List[Finding] = []
    for rule_id, (_desc, check) in sorted(registry().items()):
        if wanted is not None and rule_id not in wanted:
            continue
        if rule_id in ignored:
            continue
        out.extend(check(prog))
    out.sort(key=lambda f: (f.line, f.rule_id))
    return out


def lint_files(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Sequence[str] = ()) -> List[Finding]:
    """Lint dumped modules; unreadable paths fail the gate (HVD999),
    mirroring the AST driver's contract."""
    findings: List[Finding] = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            findings.append(Finding(str(p), 1, "HVD999",
                                    f"unreadable: {e}"))
            continue
        findings.extend(lint_text(text, path=str(p), select=select,
                                  ignore=ignore))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def lint_enabled() -> bool:
    """HOROVOD_HLO_LINT gate (default on) for the bench-side stamping;
    the CLI/CI path runs unconditionally."""
    from horovod_tpu.common.config import _env_on
    return _env_on("HOROVOD_HLO_LINT", True)


#: Bench stamps at most this many findings per section (full details
#: always come from re-running the CLI on the dumped module).
_SUMMARY_MAX_FINDINGS = 20


def lint_summary(text: str, path: str = "<lowered>") -> Dict[str, object]:
    """The compact per-section stamp bench embeds in its JSON line."""
    findings = lint_text(text, path=path)
    record_metrics(findings)
    rules: Dict[str, int] = {}
    for f in findings:
        rules[f.rule_id] = rules.get(f.rule_id, 0) + 1
    out: Dict[str, object] = {"count": len(findings),
                              "clean": not findings}
    if findings:
        out["rules"] = rules
        out["findings"] = [f.render()
                           for f in findings[:_SUMMARY_MAX_FINDINGS]]
        if len(findings) > _SUMMARY_MAX_FINDINGS:
            out["truncated"] = len(findings) - _SUMMARY_MAX_FINDINGS
    return out


def record_metrics(findings: Sequence[Finding]) -> None:
    """hvdhlo_findings_total{rule} (PR 2 registry); lint must work in
    environments without the runtime deps, so failures are swallowed."""
    try:
        from horovod_tpu.observability import metrics as m
        counter = m.registry().counter(
            "hvdhlo_findings_total", "hvdhlo findings by rule",
            labelnames=("rule",))
        for f in findings:
            counter.labels(rule=f.rule_id).inc()
    except Exception:
        pass


# ------------------------------------------------- canonical step lower

def _force_cpu_mesh(min_devices: int = 2):
    """CPU backend with a multi-device virtual mesh (the perf_gate
    recipe: env alone doesn't switch platforms on images whose
    sitecustomize pins jax.config)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < min_devices:
        raise RuntimeError(
            f"hlo-lint needs >= {min_devices} CPU devices; the backend "
            "initialized before the device-count flag could apply "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "before starting python)")
    return jax


def lower_step_text(kind: str = "lm") -> str:
    """StableHLO text of the canonical DP train step under the CURRENT
    fusion config — the program `make hlo-lint` gates.

    `lm`: the tied-embedding transformer-LM shape from bench's
    lm_overlap section (an 8 MB embedding + 6 residual FFN blocks,
    ~25 MB of f32 gradients) through the framework's own in-jit
    bucketed reduction on the virtual CPU mesh. The 8 MB embedding
    gradient is the canary: with chunking + the bucket cap intact every
    all-reduce payload stays <= the cap; reverting ops/fusion.py to the
    pre-PR-6 single-giant-allreduce plan (or lifting the cap while
    raising the threshold) resurfaces a >cap payload and trips HVD201.
    """
    if kind == "resnet_block":
        return _resnet_block_step_text()
    if kind != "lm":
        raise ValueError(f"unknown --hlo-step program {kind!r}")
    jax = _force_cpu_mesh()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.common import config as C
    from horovod_tpu.common.compat import ensure_jax_api
    from horovod_tpu.ops import fusion
    from horovod_tpu.optim.optimizer import reduce_gradients_in_jit

    # The env-derived effective threshold, computed here rather than
    # through topology state so the gate needs no hvd.init(): both an
    # env simulation of the old plan (HOROVOD_FUSION_THRESHOLD=64MB +
    # HOROVOD_BUCKET_CAP=0) and a code revert of the chunking land in
    # the lowered program.
    thresh = fusion.effective_threshold(
        C._env_int(C.HOROVOD_FUSION_THRESHOLD,
                   C.DEFAULT_FUSION_THRESHOLD_BYTES),
        C._env_int(C.HOROVOD_BUCKET_CAP, C.DEFAULT_BUCKET_CAP_BYTES))

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("hvd",))
    rng = np.random.default_rng(0)
    D, F, V, NL = 256, 1024, 8192, 6
    params = {"emb": jnp.asarray(
        rng.standard_normal((V, D)) * 0.02, jnp.float32)}
    for i in range(NL):
        params[f"wi{i}"] = jnp.asarray(
            rng.standard_normal((D, F)) * 0.02, jnp.float32)
        params[f"wo{i}"] = jnp.asarray(
            rng.standard_normal((F, D)) * 0.02, jnp.float32)

    def local_step(p, tok, tgt):
        def loss(p):
            h = p["emb"][tok]
            for i in range(NL):
                h = h + jnp.tanh(h @ p[f"wi{i}"]) @ p[f"wo{i}"]
            logits = h @ p["emb"].T
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))

        g = jax.grad(loss)(p)
        g = reduce_gradients_in_jit(g, num_ranks=ndev,
                                    fusion_threshold_bytes=thresh)
        return jax.tree_util.tree_map(lambda a, b: a - 0.01 * b, p, g)

    B, S = 16, 64
    tok = jnp.asarray(rng.integers(0, V, (B * ndev, S)))
    tgt = jnp.roll(tok, -1, axis=1)
    ensure_jax_api()
    step = jax.shard_map(local_step, mesh=mesh,
                         in_specs=(P(), P("hvd"), P("hvd")), out_specs=P(),
                         check_vma=False)
    return jax.jit(step, donate_argnums=0).lower(params, tok, tgt).as_text()


def _resnet_block_step_text() -> str:
    """StableHLO text of a C=64 ResNet bottleneck-block train step under
    the CURRENT layout config — the `make conv-smoke` gate.

    The block is the live twin of the checked-in
    ``hvd204_resnet_block`` fixture (stage-0 shape: trunk 64, width 64
    — every conv channel dim at 50% MXU padding waste, the exact
    HVD204 canary). The layout pass (ops/layout.py) pads the declared
    stack to the 128-lane width before lowering, so the DEFAULT config
    lints clean; reverting the pass (HOROVOD_LAYOUT_PAD=0, or a
    regression in plan()/pad()) resurfaces the unaligned dims and
    trips HVD204 — pinned both ways by tests/test_hvdhlo.py.
    """
    jax = _force_cpu_mesh()
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.ops import layout as L
    from horovod_tpu.ops.layout import Site

    C, W = 64, 64  # stage-0 trunk/width: the 50%-waste fixture shape
    rng = np.random.default_rng(0)

    def conv_init(kh, kw, cin, cout):
        return jnp.asarray(
            rng.standard_normal((kh, kw, cin, cout))
            * (2.0 / (kh * kw * cin)) ** 0.5, jnp.float32)

    def bn_init(c):
        return {"scale": jnp.ones((c,), jnp.float32),
                "bias": jnp.zeros((c,), jnp.float32)}

    params = {"conv1": conv_init(1, 1, C, W), "bn1": bn_init(W),
              "conv2": conv_init(3, 3, W, W), "bn2": bn_init(W),
              "conv3": conv_init(1, 1, W, 4 * W), "bn3": bn_init(4 * W),
              "proj": conv_init(1, 1, C, 4 * W), "bnp": bn_init(4 * W),
              "fc": jnp.asarray(rng.standard_normal((4 * W, 1000))
                                * (4 * W) ** -0.5, jnp.float32)}
    stack = [Site("conv1", {2: "in", 3: "c1"}),
             Site("bn1/scale", {0: "c1"}), Site("bn1/bias", {0: "c1"}),
             Site("conv2", {2: "c1", 3: "c2"}),
             Site("bn2/scale", {0: "c2"}), Site("bn2/bias", {0: "c2"}),
             Site("conv3", {2: "c2", 3: "out"}),
             Site("bn3/scale", {0: "out"}), Site("bn3/bias", {0: "out"}),
             Site("proj", {2: "in", 3: "out"}),
             Site("bnp/scale", {0: "out"}), Site("bnp/bias", {0: "out"}),
             Site("fc", {0: "out"})]
    plan = L.plan(params, stack)
    params = plan.pad(params)
    cin = plan.edges["in"].padded  # activations enter on the padded trunk

    def bn(x, p):
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.mean(jnp.square(x), axis=(0, 1, 2)) - jnp.square(mean)
        inv = jax.lax.rsqrt(var + 1e-5)
        return (x - mean) * inv * p["scale"] + p["bias"]

    def conv(x, w, stride=1):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=(stride, stride), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def loss(p, x, yl):
        h = jax.nn.relu(bn(conv(x, p["conv1"]), p["bn1"]))
        h = jax.nn.relu(bn(conv(h, p["conv2"]), p["bn2"]))
        h = bn(conv(h, p["conv3"]), p["bn3"])
        sc = bn(conv(x, p["proj"]), p["bnp"])
        h = jnp.mean(jax.nn.relu(h + sc), axis=(1, 2))
        logp = jax.nn.log_softmax(h @ p["fc"])
        return -jnp.mean(jnp.take_along_axis(logp, yl[:, None], axis=1))

    def step(p, x, yl):
        g = jax.grad(loss)(p, x, yl)
        return jax.tree_util.tree_map(lambda a, b: a - 0.01 * b, p, g)

    # Bench-canonical batch and class count: the BACKWARD contracts over
    # the batch (conv dW) and the classes (softmax dlogits), so an
    # unaligned batch would self-inflict the very HVD204 findings this
    # program exists to prove the LAYOUT pass removes. B=128 is the
    # measured conv sweet spot (docs/benchmarks.md); 1000 classes sits
    # under the padding-waste floor, exactly like the real model.
    x = jnp.asarray(rng.standard_normal((128, 8, 8, cin)), jnp.float32)
    yl = jnp.asarray(rng.integers(0, 1000, (128,)))
    return jax.jit(step, donate_argnums=0).lower(params, x, yl).as_text()


#: Stable pseudo-path for --hlo-step findings, so baseline entries
#: survive across hosts and invocations.
def step_path(kind: str) -> str:
    return f"<hlo-step:{kind}>"
