from horovod_tpu.analysis.driver import main

main()
