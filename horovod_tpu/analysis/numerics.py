"""hvdnum: static numerics & reduction-semantics verification (HVD5xx;
docs/static_analysis.md).

The HVD1xx-4xx wall catches deadlocks, resharding waste, OOM and comms
overruns — failures that crash or stall. The bugs that corrupt training
*silently* are numeric: a bf16 dot that also accumulates in bf16, a
gradient downcast applied before (not after) its all-reduce, a
sum-vs-mean scale whose divisor was baked in as a constant and goes
stale the first time the elastic world size changes, and reduction
orders that differ across replicas — which voids the bit-identical
resume guarantee the chaos e2e depends on. All of these are properties
of the lowered program, checkable at compile time from the same text
hvdhlo/hvdsched already parse.

This module builds the analysis state the HVD5xx rules
(``analysis/num_rules.py``) consume:

* a **dtype-flow lattice** propagated forward over the parsed def-use
  graph (``analysis/hlo.py``): per value, the current element type, the
  widest type seen on any upstream path, and the most recent
  precision-dropping ``convert`` — so a reduce can tell "natively
  narrow" from "narrowed on the way here" (HVD502);
* a **gradient-scale table**: one entry per fp reduce collective, with
  its replica-group size (``analysis/schedule.py`` machinery — explicit
  lists, V2 iota, one parser), the explicit post-reduce scale constant
  resolved through ``hlo.constant_value`` (the satellite literal fix:
  scientific notation + typed bf16/f8 literals), and the resulting
  effective multiplier ``k / divisor`` — the invariant HVD503 checks
  in-program and HVD505 diffs across a mesh-shape pair.

Like hvdshard/hvdsched, findings are baselined
(``scripts/hvdnum_baseline.json``), not suppressed inline, and feed
``hvdnum_findings_total{rule}``. CI gate: ``make num-lint``.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis.hlo import (
    DTYPE_BYTES,
    HloOp,
    HloProgram,
    constant_value,
    parse,
)
from horovod_tpu.analysis.schedule import CollectiveEvent, ProgramSchedule
from horovod_tpu.analysis.shard import (
    _axis_partitions,
    _bytes_env,
    group_axis_label,
)

#: Floating-point element types, by width class. f8 variants share the
#: low-precision bucket with bf16/f16: none can hold a long gradient
#: accumulation without catastrophic rounding.
FP_DTYPES = frozenset({
    "f64", "f32", "bf16", "f16",
    "f8e4m3fn", "f8e5m2", "f8e4m3b11fnuz", "f8e4m3fnuz", "f8e5m2fnuz",
})
LOW_PRECISION = frozenset(d for d in FP_DTYPES
                          if DTYPE_BYTES.get(d, 4) < 4)

#: Collectives that *combine* values (order- and scale-sensitive);
#: gather/permute ops only move bytes and carry no reduction semantics.
REDUCE_COLLECTIVES = frozenset({"all_reduce", "reduce_scatter"})

#: Ops a reduced value flows through unchanged on the way to its
#: explicit scale op (the divide/multiply HVD503 audits). Arithmetic
#: ops are deliberately absent: the scan must stop at the first op
#: that changes the value's magnitude.
_SCALE_TRANSPARENT = frozenset({
    "convert", "copy", "bitcast", "reshape", "transpose", "slice",
    "get_tuple_element", "tuple", "optimization_barrier",
})

#: Ops resolved through when chasing a scale operand back to its
#: defining scalar constant (a divisor is usually broadcast first).
_CONST_TRANSPARENT = frozenset({
    "broadcast", "broadcast_in_dim", "reshape", "convert", "copy",
    "bitcast", "constant",
})

#: Keyless RNG opcodes: per-device implicit seed state, so a restored
#: replica replays a different stream (HVD504). ``rng_bit_generator``
#: threads its state explicitly and is exempt.
KEYLESS_RNG_OPS = frozenset({"rng", "rng_uniform", "rng_normal"})


# ------------------------------------------------------ loud env knobs

_MIN_REDUCE_ENV = "HOROVOD_NUM_MIN_REDUCE_BYTES"
_SCALE_TOL_ENV = "HOROVOD_NUM_SCALE_TOL"
_ALLOW_ACCUM_ENV = "HOROVOD_NUM_ALLOW_ACCUM"

#: Default relative tolerance when matching an explicit scale constant
#: against a group size: XLA folds divides into reciprocal multiplies,
#: so 1/3 round-trips through a printed decimal.
DEFAULT_SCALE_TOL = 0.01


def min_reduce_bytes() -> int:
    """HVD502/HVD503 payload floor (``HOROVOD_NUM_MIN_REDUCE_BYTES``,
    default 0: every fp gradient reduction is judged). Malformed input
    raises ValueError (loud-knob policy)."""
    return _bytes_env(_MIN_REDUCE_ENV, 0)


def scale_tol() -> float:
    """Relative tolerance for divisor-vs-group-size comparison
    (``HOROVOD_NUM_SCALE_TOL``, default 0.01). Loud on garbage."""
    from horovod_tpu.analysis.schedule import _float_env
    tol = _float_env(_SCALE_TOL_ENV)
    return DEFAULT_SCALE_TOL if tol is None else tol


class _AccumAllowCache:
    """Process-wide cache of parsed HOROVOD_NUM_ALLOW_ACCUM sets, keyed
    by the raw env string (bench workers and concurrent lint threads
    share one parse per distinct value). Instrumented by hvdrace
    (race.DEFAULT_MODULES)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sets: Dict[str, frozenset] = {}  # guarded-by: _lock

    def get(self, raw: str) -> Optional[frozenset]:
        with self._lock:
            return self._sets.get(raw)

    def put(self, raw: str, allowed: frozenset) -> None:
        with self._lock:
            self._sets[raw] = allowed


_accum_cache = _AccumAllowCache()


def allowed_accum() -> frozenset:
    """Low-precision dtypes HVD501 accepts as accumulation types
    (``HOROVOD_NUM_ALLOW_ACCUM="bf16"`` for a model that has qualified
    bf16 accumulation). Comma-separated dtype tokens; an unknown token
    raises ValueError — a typo'd knob must fail the lint loudly, never
    silently widen or narrow the rule."""
    raw = os.environ.get(_ALLOW_ACCUM_ENV, "").strip()
    hit = _accum_cache.get(raw)
    if hit is not None:
        return hit
    tokens = frozenset(t.strip().lower() for t in raw.split(",")
                       if t.strip())
    for t in tokens:
        if t not in DTYPE_BYTES:
            raise ValueError(
                f"{_ALLOW_ACCUM_ENV}={raw!r}: unknown dtype token {t!r} "
                f"(expected comma-separated XLA dtype names, e.g. "
                f"'bf16' or 'bf16,f16')")
    _accum_cache.put(raw, tokens)
    return tokens


# ------------------------------------------------- the dtype-flow lattice

@dataclasses.dataclass
class ValueFlow:
    """Lattice state of one SSA value: current element type, the widest
    fp type on any upstream path, and the most recent precision-dropping
    convert that produced the narrowing (None = natively this wide)."""

    dtype: Optional[str]
    width: Optional[int]
    max_width: int
    narrowed_at: Optional[HloOp]


@dataclasses.dataclass(frozen=True)
class GradReduction:
    """One fp reduce collective + its resolved scale semantics."""

    op: HloOp
    event: CollectiveEvent
    dtype: str
    group_size: int
    nbytes: int
    #: Explicit post-reduce scale expressed as a divisor (a downstream
    #: ``divide`` by c, or ``multiply`` by 1/c); None = bare sum, or a
    #: dynamic scale when ``dynamic`` is set.
    divisor: Optional[float]
    divisor_line: Optional[int]
    #: The nearest scale op divides by a runtime value (e.g. an
    #: allreduced live group size — the elastic-correct pattern): the
    #: static multiplier is unknowable and the scale rules skip it.
    dynamic: bool = False

    @property
    def multiplier(self) -> Optional[float]:
        """Effective per-replica gradient multiplier: k for a bare sum,
        k/divisor with an explicit scale (1.0 = true mean), None when
        the scale is dynamic."""
        if self.dynamic:
            return None
        if self.divisor:
            return self.group_size / self.divisor
        return float(self.group_size)


def _fp_dtype(t) -> Optional[str]:
    if t is None:
        return None
    d = t.dtype.lower()
    return d if d in FP_DTYPES else None


class NumericsProgram:
    """The hvdnum analysis state of one lowered program: the parsed
    module, its collective schedule, the dtype-flow lattice, and the
    gradient-scale table."""

    def __init__(self, prog: HloProgram):
        self.prog = prog
        self.path = prog.path
        self.schedule = ProgramSchedule(prog)
        #: (scope, ssa name) -> ValueFlow
        self.flow: Dict[Tuple[str, str], ValueFlow] = {}
        self.reductions: List[GradReduction] = []
        self._propagate()
        self._collect_reductions()

    # -- forward dtype-flow pass (printed order is SSA order in both
    # textual forms, so one linear sweep converges)
    def _propagate(self) -> None:
        for op in self.prog.ops:
            if not op.result:
                continue
            out_t = op.result_types[0] if op.result_types else None
            dtype = _fp_dtype(out_t)
            width = DTYPE_BYTES.get(dtype) if dtype else None
            max_width = width or 0
            narrowed: Optional[HloOp] = None
            for o in op.operands:
                f = self.flow.get((op.scope, o))
                if f is None:
                    continue
                max_width = max(max_width, f.max_width)
                if narrowed is None and f.narrowed_at is not None:
                    narrowed = f.narrowed_at
            if op.opcode == "convert":
                src = (op.operand_types[0] if op.operand_types else None)
                src_d = _fp_dtype(src)
                src_w = DTYPE_BYTES.get(src_d) if src_d else None
                if src_w is None and op.operands:
                    f = self.flow.get((op.scope, op.operands[0]))
                    src_w = f.width if f else None
                if (src_w is not None and width is not None
                        and dtype and width < src_w):
                    narrowed = op
                    max_width = max(max_width, src_w)
            self.flow[(op.scope, op.result)] = ValueFlow(
                dtype, width, max_width, narrowed)

    # -- gradient-scale table
    def _collect_reductions(self) -> None:
        opmap = {op.line: op for op in self.prog.ops}
        ndev = self.schedule.num_devices
        for ev in self.schedule.events:
            if ev.opcode not in REDUCE_COLLECTIVES:
                continue
            op = opmap.get(ev.line)
            if op is None:
                continue
            dtype = None
            for t in list(op.operand_types) + list(op.result_types):
                dtype = _fp_dtype(t)
                if dtype:
                    break
            if dtype is None:
                continue  # integer/predicate reductions are exact
            k = max((len(g) for g in ev.groups), default=ndev)
            divisor, dline, dyn = self._post_scale(op)
            self.reductions.append(GradReduction(
                op=op, event=ev, dtype=dtype, group_size=max(k, 1),
                nbytes=ev.nbytes, divisor=divisor, divisor_line=dline,
                dynamic=dyn))

    def _resolve_const(self, scope: str, name: str,
                       depth: int = 8) -> Optional[float]:
        """Chase an operand back through broadcasts/reshapes to its
        defining scalar constant (hlo.constant_value)."""
        while depth > 0:
            depth -= 1
            d = self.prog.defining(scope, name)
            if d is None:
                return None
            if d.opcode == "constant":
                return constant_value(d)
            if d.opcode not in _CONST_TRANSPARENT or not d.operands:
                return None
            name = d.operands[0]
        return None

    def _post_scale(self, op: HloOp, max_visits: int = 128
                    ) -> Tuple[Optional[float], Optional[int], bool]:
        """The first explicit scale applied to a reduce's result
        (through _SCALE_TRANSPARENT ops), as
        ``(divisor, line, dynamic)``. BFS so the *nearest* scale op
        wins: a mean's 1/k multiply is adjacent to the reduce, while
        the learning-rate multiply rides behind the optimizer's update
        math. A divide by a runtime value (allreduced live group size)
        reports dynamic=True — the elastic-correct pattern the static
        rules must not second-guess."""
        if not op.result:
            return None, None, False
        seen = {op.result}
        frontier = [op]
        visits = 0
        while frontier and visits < max_visits:
            cur = frontier.pop(0)
            visits += 1
            for use in self.prog.uses(cur.scope, cur.result):
                if use.opcode == "divide" and len(use.operands) >= 2:
                    if use.operands[0] != cur.result:
                        continue  # our value is the denominator of
                        # someone else's math, not a scale of ours
                    c = self._resolve_const(use.scope, use.operands[1])
                    if c:
                        return c, use.line, False
                    return None, use.line, True
                if use.opcode == "multiply" and len(use.operands) >= 2:
                    c = None
                    for other in use.operands:
                        if other == cur.result:
                            continue
                        c = self._resolve_const(use.scope, other)
                        if c:
                            break
                    if c:
                        return 1.0 / c, use.line, False
                    return None, use.line, True
                if use.opcode in _SCALE_TRANSPARENT and use.result \
                        and use.result not in seen:
                    seen.add(use.result)
                    frontier.append(use)
        return None, None, False


@dataclasses.dataclass
class NumericsSet:
    """All programs linted together — the unit HVD505 sees. The
    cross-mesh diff only exists across programs (the different-mesh
    restore pair lowered from one step), so lint_files parses every
    path into ONE set, mirroring hvdsched."""

    programs: List[NumericsProgram]


def analyze_text(text: str, path: str = "<hlo>") -> NumericsProgram:
    return NumericsProgram(parse(text, path))


# ------------------------------------------------------------- linting

def registry() -> Dict[str, Tuple[str, object]]:
    """rule_id -> (description, check(nset) -> iterable[Finding])."""
    from horovod_tpu.analysis import num_rules
    return dict(num_rules.RULES)


def lint_programs(nprogs: Sequence[NumericsProgram],
                  select: Optional[Sequence[str]] = None,
                  ignore: Sequence[str] = ()) -> List[Finding]:
    """Run the HVD5xx rules over one NumericsSet."""
    wanted = {r.upper() for r in select} if select is not None else None
    ignored = {r.upper() for r in ignore}
    nset = NumericsSet(list(nprogs))
    out: List[Finding] = []
    for rule_id, (_desc, check) in sorted(registry().items()):
        if wanted is not None and rule_id not in wanted:
            continue
        if rule_id in ignored:
            continue
        out.extend(check(nset))
    out.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return out


def lint_text(text: str, path: str = "<hlo>",
              select: Optional[Sequence[str]] = None,
              ignore: Sequence[str] = ()) -> List[Finding]:
    return lint_programs([analyze_text(text, path)],
                         select=select, ignore=ignore)


def lint_files(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Sequence[str] = ()) -> List[Finding]:
    """Parse ALL paths into one NumericsSet before linting: the
    HVD505 mesh-pair diff only exists across files."""
    findings: List[Finding] = []
    nprogs: List[NumericsProgram] = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            findings.append(Finding(str(p), 1, "HVD999",
                                    f"unreadable: {e}"))
            continue
        nprogs.append(analyze_text(text, path=str(p)))
    findings.extend(lint_programs(nprogs, select=select, ignore=ignore))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def record_metrics(findings: Sequence[Finding]) -> None:
    """hvdnum_findings_total{rule}; pre-registers the counter even on
    a clean run so dashboards see the series, and swallows failures —
    analysis must work without the runtime deps."""
    try:
        from horovod_tpu.observability import metrics as m
        counter = m.registry().counter(
            "hvdnum_findings_total", "hvdnum findings by rule",
            labelnames=("rule",))
        for f in findings:
            counter.labels(rule=f.rule_id).inc()
    except Exception:
        pass


# ---------------------------------------------------- the bench stamp

#: Op families whose result dtype IS an accumulation type: what the
#: stamp's ``accum_dtypes`` reports (the compile-time answer to "what
#: precision do my matmuls and gradient reductions accumulate in?").
_ACCUM_OPS = frozenset({"dot", "dot_general", "convolution", "reduce"})


def stamp(text: str,
          axis_sizes: Optional[Sequence[Tuple[str, int]]] = None,
          path: str = "<compiled>") -> Dict[str, object]:
    """The bench ``numerics`` stamp: accumulation dtypes seen plus the
    gradient-scale table, off the SAME compiled text the comms stamps
    read, replica groups classified by the SAME shard.group_axis_label
    helper — so scale attribution and comms attribution can never
    disagree on what a group means. perf_gate requires this stamp
    structurally on every gspmd section; perfboard carries its finding
    count across rounds."""
    np_ = analyze_text(text, path)
    accum = set()
    for op in np_.prog.ops:
        if op.opcode in _ACCUM_OPS:
            d = _fp_dtype(op.result_types[0] if op.result_types else None)
            if d:
                accum.add(d)
    for r in np_.reductions:
        accum.add(r.dtype)
    partitions = (_axis_partitions(axis_sizes)
                  if axis_sizes is not None else None)
    table: List[Dict[str, object]] = []
    for r in np_.reductions:
        mult = r.multiplier
        ent: Dict[str, object] = {
            "opcode": r.event.opcode,
            "dtype": r.dtype,
            "group_size": r.group_size,
            "bytes": r.nbytes,
            "divisor": r.divisor,
            "multiplier": None if mult is None else round(mult, 6),
        }
        if partitions is not None:
            groups = [list(g) for g in r.event.groups] or None
            ent["axis"] = group_axis_label(groups, partitions)
        table.append(ent)
    findings = lint_programs([np_])
    record_metrics(findings)
    rules: Dict[str, int] = {}
    for f in findings:
        rules[f.rule_id] = rules.get(f.rule_id, 0) + 1
    out: Dict[str, object] = {
        "accum_dtypes": sorted(accum),
        "grad_scale": table,
        "findings": len(findings),
        "clean": not findings,
    }
    if rules:
        out["rules"] = rules
    return out


def close(a: float, b: float, tol: Optional[float] = None) -> bool:
    """Scale comparison helper shared by HVD503/HVD505 (one tolerance,
    one knob)."""
    if tol is None:
        tol = scale_tol()
    return math.isclose(a, b, rel_tol=tol, abs_tol=1e-12)
