"""HVD-ENV: every HOROVOD_* env var referenced in code is documented.

Folds ``scripts/check_env_docs.py`` (PR 2) into the hvdlint driver so
``make lint`` has one entrypoint, one exit code and one output format.
The old script remains as a thin shim over this module.

The knob surface drifts: code grows ``HOROVOD_FOO`` reads faster than
docs grow tables. This rule extracts every quoted ``"HOROVOD_..."``
string literal from ``horovod_tpu/**/*.py`` and requires the exact name
to appear somewhere under ``docs/`` or README.md — docs/env_vars.md is
the canonical catalog.

Composed names (a policy prefix like HOROVOD_KV_RETRY plus a
``_MAX_ATTEMPTS`` suffix) are covered by documenting the prefix: a
literal that is a documented literal plus a documented suffix pattern
passes.
"""

from __future__ import annotations

import pathlib
import re
from typing import Dict, List, Optional, Set, Tuple

from horovod_tpu.analysis.driver import (Finding, MSG_NO_RATIONALE,
                                         parse_suppression,
                                         suppression_covers)

RULE_ID = "HVD-ENV"
DESCRIPTION = "HOROVOD_* env var referenced in code but undocumented"

LITERAL_RE = re.compile(r"""["'](HOROVOD_[A-Z0-9_]+)["']""")

# Suffixes appended to documented prefixes at runtime (RetryPolicy.from_env
# env scheme, docs/resilience.md): HOROVOD_KV_RETRY + _MAX_ATTEMPTS etc.
COMPOSED_SUFFIXES = ("_MAX_ATTEMPTS", "_BASE_DELAY", "_MAX_DELAY",
                     "_MULTIPLIER", "_JITTER", "_DEADLINE")


def referenced_vars(code_dir: pathlib.Path
                    ) -> Dict[str, List[Tuple[str, int, str]]]:
    """name -> [(relative path, line, line text), ...] references."""
    found: Dict[str, List[Tuple[str, int, str]]] = {}
    root = code_dir.parent
    for path in sorted(code_dir.glob("**/*.py")):
        rel = str(path.relative_to(root))
        for lineno, line in enumerate(
                path.read_text(encoding="utf-8").splitlines(), 1):
            for name in LITERAL_RE.findall(line):
                found.setdefault(name, []).append((rel, lineno, line))
    return found


def documented_vars(root: pathlib.Path) -> Set[str]:
    doc_paths = sorted((root / "docs").glob("**/*.md")) + [root / "README.md"]
    text = "\n".join(p.read_text(encoding="utf-8")
                     for p in doc_paths if p.exists())
    return set(re.findall(r"HOROVOD_[A-Z0-9_]+", text))


def check_project(root: Optional[str] = None) -> List[Finding]:
    """Repo-level check; returns one finding per undocumented var."""
    root_path = (pathlib.Path(root) if root is not None
                 else pathlib.Path(__file__).resolve().parent.parent.parent)
    code_dir = root_path / "horovod_tpu"
    if not code_dir.is_dir() or not (root_path / "docs").is_dir():
        return []  # not running inside the repo: nothing to check
    refs = referenced_vars(code_dir)
    docs = documented_vars(root_path)
    findings: List[Finding] = []
    for name, sites in sorted(refs.items()):
        if name in docs:
            continue
        if any(name.endswith(sfx) and name[: -len(sfx)] in docs
               for sfx in COMPOSED_SUFFIXES):
            continue
        # The driver's suppression grammar applies here too: a covering
        # suppression on ANY referencing line silences the var (rule-
        # internal knobs that deliberately stay undocumented); without
        # a rationale it degrades to HVD000, same as the AST rules.
        entries = [(path, lineno, parse_suppression(text))
                   for path, lineno, text in sites]
        covering = [(p, ln, e) for p, ln, e in entries
                    if suppression_covers(e, RULE_ID)]
        if covering:
            for p, ln, e in covering:
                if not e[1]:
                    findings.append(Finding(p, ln, "HVD000",
                                            MSG_NO_RATIONALE))
            continue
        path, lineno, _ = sites[0]
        findings.append(Finding(
            path, lineno, RULE_ID,
            f"undocumented env var {name}: add it to docs/env_vars.md "
            f"(or the relevant doc page)"))
    return findings


def main() -> int:
    """Shim surface for scripts/check_env_docs.py."""
    findings = check_project()
    for f in findings:
        print(f.render())
    return 1 if findings else 0
