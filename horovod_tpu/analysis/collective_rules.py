"""Collective-consistency lint rules (HVD001-HVD004).

The SPMD contract behind every backend this framework has (and the
reference's coordinator protocol, controller.cc:74-447) is: **every rank
issues the same collectives, in the same order, with the same
signature**. Violations don't crash — they stall, 1000 chips deep. These
rules flag the source patterns that most often break the contract, on
user/training code and the repo's own examples:

HVD001  collective invoked under rank-dependent control flow
        (``if hvd.rank() == 0: hvd.broadcast(...)``) — only some ranks
        submit it, the rest hang at the next collective.
HVD002  collective name derived from iteration over an unordered
        container (a set) — iteration order differs per process, so
        ranks pair up different tensors under the same call index.
HVD003  unnamed collective inside a loop — auto-assigned names collide
        across iterations once calls overlap (async handles, reference
        DUPLICATE_NAME_ERROR) and make timeline/stall diagnostics
        ambiguous.
HVD004  ``process_set=`` differs between branches of one ``if`` — if the
        condition isn't globally uniform, member sets disagree about who
        participates.

Heuristics are deliberately lexical (no cross-function dataflow): a
false positive is one ``disable=... -- rationale`` suppression comment
away, while a missed stall costs a debugging session on a live cluster.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from horovod_tpu.analysis.driver import Finding, SourceFile

#: The eager collective API surface (ops/collectives.py) plus the
#: high-level wrappers that submit collectives on the caller's behalf
#: (optim/functions.py).
COLLECTIVE_NAMES: Set[str] = {
    "allreduce", "grouped_allreduce", "allgather", "grouped_allgather",
    "broadcast", "reducescatter", "grouped_reducescatter", "alltoall",
    "barrier",
    "allreduce_async", "grouped_allreduce_async", "allgather_async",
    "broadcast_async", "alltoall_async", "reducescatter_async",
    "broadcast_object", "broadcast_parameters", "broadcast_variables",
    "broadcast_optimizer_state", "allgather_object",
}

#: Ops whose reference auto-naming collides across loop iterations
#: (HVD003), mapped to the 0-based POSITIONAL index of their `name`
#: parameter (ops/collectives.py signatures; the frontends mirror
#: them). The broadcast_* / *_object wrappers name their tensors
#: internally and barrier takes no name.
NAME_ARG_POS: Dict[str, Tuple[int, ...]] = {
    "allreduce": (2,), "grouped_allreduce": (2,),
    "allgather": (1,), "grouped_allgather": (1,),
    "broadcast": (2,), "reducescatter": (2,),
    "grouped_reducescatter": (2,), "alltoall": (2,),
    "allreduce_async": (2,),
    # torch's async wrapper takes name at position 1
    # (frontends/torch.py), the core alias at 2 — accept either.
    "grouped_allreduce_async": (1, 2),
    "allgather_async": (1,), "broadcast_async": (2,),
    "alltoall_async": (2,), "reducescatter_async": (2,),
}
NAMED_OP_NAMES: Set[str] = set(NAME_ARG_POS)

#: Receivers whose methods share names with our API but are NOT Horovod
#: collectives (np.broadcast, tf.broadcast_to's relatives, etc.).
_FOREIGN_ROOTS: Set[str] = {
    "np", "numpy", "jnp", "jax", "lax", "torch", "tf", "tensorflow",
    "mx", "mxnet", "keras", "K",
}

#: Calls that return this process's identity — the seed of
#: rank-dependent control flow.
_RANK_CALL_NAMES: Set[str] = {
    "rank", "local_rank", "cross_rank", "process_index",
}


def _terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _root_name(func: ast.AST) -> Optional[str]:
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def is_collective_call(node: ast.AST) -> Optional[str]:
    """The collective's op name if `node` is a Horovod collective call."""
    if not isinstance(node, ast.Call):
        return None
    name = _terminal_name(node.func)
    if name not in COLLECTIVE_NAMES:
        return None
    if isinstance(node.func, ast.Attribute) \
            and _root_name(node.func) in _FOREIGN_ROOTS:
        return None
    return name


def _contains_rank_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and _terminal_name(sub.func) in _RANK_CALL_NAMES:
            return True
    return False


def _walk_pruned(root: ast.stmt) -> Iterator[Tuple[ast.Call, str]]:
    """Collective calls under `root`, pruning nested def/class bodies:
    a ``def`` inside a rank-guard only runs if something calls it, and
    that callsite is what the rule should (and does) anchor to."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node is not root:
            continue
        op = is_collective_call(node)
        if op is not None:
            yield node, op  # still recurse: grouped calls can nest args
        stack.extend(ast.iter_child_nodes(node))


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _name_argument(call: ast.Call, op: str) -> Optional[ast.expr]:
    """The expression passed as `name` — keyword or positional."""
    expr = _kwarg(call, "name")
    if expr is not None:
        return expr
    for pos in NAME_ARG_POS.get(op, ()):
        if len(call.args) > pos \
                and not isinstance(call.args[pos], ast.Starred):
            return call.args[pos]
    return None


# --------------------------------------------------------------- HVD001

def check_rank_dependent(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        branches: List[List[ast.stmt]] = []
        desc = ""
        if isinstance(node, ast.If) and _contains_rank_call(node.test):
            branches = [node.body, node.orelse]
            desc = "if"
        elif isinstance(node, ast.While) \
                and _contains_rank_call(node.test):
            branches = [node.body]
            desc = "while"
        elif isinstance(node, ast.IfExp) \
                and _contains_rank_call(node.test):
            branches = []
            for side in (node.body, node.orelse):
                op = is_collective_call(side)
                if op is not None:
                    yield sf.finding(
                        side, "HVD001",
                        f"collective '{op}' in a rank-dependent "
                        f"conditional expression: every rank must issue "
                        f"the same collectives in the same order")
            continue
        for branch in branches:
            for call, op in _collectives_under_stmts(branch):
                yield sf.finding(
                    call, "HVD001",
                    f"collective '{op}' under rank-dependent control "
                    f"flow ({desc} at line {node.lineno}): every rank "
                    f"must issue the same collectives in the same order")


def _collectives_under_stmts(stmts: Iterable[ast.stmt]
                             ) -> Iterator[Tuple[ast.Call, str]]:
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # see _walk_pruned: flag callsites, not def bodies
        yield from _walk_pruned(stmt)


# --------------------------------------------------------------- HVD002

def _unordered_iter_reason(it: ast.expr) -> Optional[str]:
    """Why iterating `it` has process-dependent order, or None."""
    if isinstance(it, ast.Set):
        return "a set literal"
    if isinstance(it, ast.SetComp):
        return "a set comprehension"
    if isinstance(it, ast.Call):
        name = _terminal_name(it.func)
        if name in ("set", "frozenset"):
            return f"{name}()"
        if name in ("vars", "globals", "locals"):
            return f"{name}()"
    return None


def _loop_targets(target: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


def check_unordered_naming(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        reason = _unordered_iter_reason(node.iter)
        if reason is None:
            continue
        targets = _loop_targets(node.target)
        for call, op in _collectives_under_stmts(node.body):
            name_expr = _name_argument(call, op)
            if name_expr is None:
                continue
            used = {n.id for n in ast.walk(name_expr)
                    if isinstance(n, ast.Name)}
            if used & targets:
                yield sf.finding(
                    call, "HVD002",
                    f"collective '{op}' name derives from iteration "
                    f"over an unordered container ({reason}): iteration "
                    f"order differs across processes, so ranks submit "
                    f"mismatched names at the same call index — iterate "
                    f"a sorted/ordered sequence instead")


# --------------------------------------------------------------- HVD003

def check_unnamed_in_loop(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for call, op in _collectives_under_stmts(node.body):
            if op not in NAMED_OP_NAMES:
                continue
            name_expr = _name_argument(call, op)
            if name_expr is None or (isinstance(name_expr, ast.Constant)
                                     and name_expr.value is None):
                yield sf.finding(
                    call, "HVD003",
                    f"unnamed collective '{op}' inside a loop: "
                    f"auto-assigned names collide across iterations "
                    f"(reference DUPLICATE_NAME_ERROR) and make "
                    f"timeline/stall diagnostics ambiguous — pass "
                    f"name=")


# --------------------------------------------------------------- HVD004

def _ps_repr(call: ast.Call) -> Optional[str]:
    ps = _kwarg(call, "process_set")
    if ps is None:
        return None
    return ast.dump(ps)


def check_process_set_branches(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.If) or not node.orelse:
            continue
        body_ps: Dict[str, Tuple[ast.Call, Optional[str]]] = {}
        for call, op in _collectives_under_stmts(node.body):
            body_ps.setdefault(op, (call, _ps_repr(call)))
        for call, op in _collectives_under_stmts(node.orelse):
            if op not in body_ps:
                continue
            other_call, other_ps = body_ps[op]
            this_ps = _ps_repr(call)
            if this_ps != other_ps:
                yield sf.finding(
                    call, "HVD004",
                    f"'{op}' uses a different process_set than the "
                    f"matching call in the other branch (line "
                    f"{other_call.lineno}): unless the condition is "
                    f"globally uniform, ranks disagree on who "
                    f"participates")


RULES = {
    "HVD001": ("collective under rank-dependent control flow",
               check_rank_dependent),
    "HVD002": ("collective named from iteration over an unordered "
               "container", check_unordered_naming),
    "HVD003": ("unnamed collective inside a loop (auto-name collision)",
               check_unnamed_in_loop),
    "HVD004": ("process_set differs across branches",
               check_process_set_branches),
}
