"""Collective-consistency lint rules (HVD001-HVD005).

The SPMD contract behind every backend this framework has (and the
reference's coordinator protocol, controller.cc:74-447) is: **every rank
issues the same collectives, in the same order, with the same
signature**. Violations don't crash — they stall, 1000 chips deep. These
rules flag the source patterns that most often break the contract, on
user/training code and the repo's own examples:

HVD001  collective invoked under rank-dependent control flow
        (``if hvd.rank() == 0: hvd.broadcast(...)``) — only some ranks
        submit it, the rest hang at the next collective. Since the
        interprocedural upgrade this also catches a *helper* that
        (transitively) issues a collective being called under the
        guard — the exact refactor that used to blind the lexical rule.
HVD002  collective name derived from iteration over an unordered
        container (a set) — iteration order differs per process, so
        ranks pair up different tensors under the same call index.
HVD003  unnamed collective inside a loop — auto-assigned names collide
        across iterations once calls overlap (async handles, reference
        DUPLICATE_NAME_ERROR) and make timeline/stall diagnostics
        ambiguous.
HVD004  ``process_set=`` differs between branches of one ``if`` — if the
        condition isn't globally uniform, member sets disagree about who
        participates. Checked across call sites too: a helper whose
        ``process_set`` parameter gets different arguments per branch is
        the same bug one frame deeper.
HVD005  collective ``name=`` derived from a rank-tainted value
        (``name=f"g{hvd.rank()}"`` — directly, through locals, or
        through a helper parameter): every rank submits a *different*
        name at the same call index, the naming twin of HVD001.

The dataflow lives in ``analysis/callgraph.py``; the graph is built once
per lint run by the driver and attached as ``sf.graph``. A false
positive is one ``disable=... -- rationale`` suppression comment away,
while a missed stall costs a debugging session on a live cluster.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from horovod_tpu.analysis.callgraph import (
    COLLECTIVE_NAMES, FOREIGN_ROOTS, NAME_ARG_POS, NAMED_OP_NAMES, RANK,
    RANK_CALL_NAMES, CallGraph, _scope_walk, contains_rank_call,
    is_collective_call, kwarg as _kwarg, name_argument as _name_argument,
    terminal_name as _terminal_name,
)
from horovod_tpu.analysis.driver import Finding, SourceFile

# Legacy aliases: the collective-call model moved to callgraph.py when
# it grew interprocedural consumers; these names stay importable here.
_RANK_CALL_NAMES = RANK_CALL_NAMES
_FOREIGN_ROOTS = FOREIGN_ROOTS


def _graph(sf: SourceFile) -> CallGraph:
    """The lint run's call graph (driver attaches it; single-blob unit
    runs build their own one-file graph on demand)."""
    graph = getattr(sf, "graph", None)
    if graph is None:
        graph = CallGraph([sf])
        sf.graph = graph
    return graph


def _walk_pruned(root: ast.stmt) -> Iterator[Tuple[ast.Call, str]]:
    """Collective calls under `root`, pruning nested def/class bodies
    (callgraph._scope_walk): a ``def`` inside a rank-guard only runs if
    something calls it, and that callsite is what the rule should (and
    does) anchor to."""
    for node in _scope_walk(root):
        op = is_collective_call(node)
        if op is not None:
            yield node, op  # grouped calls can nest args: walk recurses


def _calls_pruned(root: ast.stmt) -> Iterator[ast.Call]:
    """Every call under `root` with the same def/class pruning."""
    for node in _scope_walk(root):
        if isinstance(node, ast.Call):
            yield node


def _collectives_under_stmts(stmts: Iterable[ast.stmt]
                             ) -> Iterator[Tuple[ast.Call, str]]:
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # see _walk_pruned: flag callsites, not def bodies
        yield from _walk_pruned(stmt)


def _calls_under_stmts(stmts: Iterable[ast.stmt]) -> Iterator[ast.Call]:
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield from _calls_pruned(stmt)


# --------------------------------------------------------------- HVD001

def _contains_rank_call(node: ast.AST) -> bool:
    return contains_rank_call(node)


def check_rank_dependent(sf: SourceFile) -> Iterator[Finding]:
    graph = _graph(sf)
    for node in ast.walk(sf.tree):
        branches: List[List[ast.stmt]] = []
        desc = ""
        if isinstance(node, ast.If) and _contains_rank_call(node.test):
            branches = [node.body, node.orelse]
            desc = "if"
        elif isinstance(node, ast.While) \
                and _contains_rank_call(node.test):
            branches = [node.body]
            desc = "while"
        elif isinstance(node, ast.IfExp) \
                and _contains_rank_call(node.test):
            for side in (node.body, node.orelse):
                op = is_collective_call(side)
                if op is not None:
                    yield sf.finding(
                        side, "HVD001",
                        f"collective '{op}' in a rank-dependent "
                        f"conditional expression: every rank must issue "
                        f"the same collectives in the same order")
            continue
        for branch in branches:
            for call, op in _collectives_under_stmts(branch):
                yield sf.finding(
                    call, "HVD001",
                    f"collective '{op}' under rank-dependent control "
                    f"flow ({desc} at line {node.lineno}): every rank "
                    f"must issue the same collectives in the same order")
            # Interprocedural: a call that lands in a linted function
            # whose summary (transitively) issues collectives is the
            # same bug one frame deeper — flag the callsite.
            for call in _calls_under_stmts(branch):
                effects = graph.call_effects(sf, call)
                if not effects:
                    continue
                op, _ps, origin = effects[0]
                callee = _terminal_name(call.func) or "<call>"
                yield sf.finding(
                    call, "HVD001",
                    f"call to '{callee}' under rank-dependent control "
                    f"flow ({desc} at line {node.lineno}) issues "
                    f"collective '{op}' ({origin}): every rank must "
                    f"issue the same collectives in the same order")


# --------------------------------------------------------------- HVD002

def _unordered_iter_reason(it: ast.expr) -> Optional[str]:
    """Why iterating `it` has process-dependent order, or None."""
    if isinstance(it, ast.Set):
        return "a set literal"
    if isinstance(it, ast.SetComp):
        return "a set comprehension"
    if isinstance(it, ast.Call):
        name = _terminal_name(it.func)
        if name in ("set", "frozenset"):
            return f"{name}()"
        if name in ("vars", "globals", "locals"):
            return f"{name}()"
    return None


def _loop_targets(target: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


def check_unordered_naming(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        reason = _unordered_iter_reason(node.iter)
        if reason is None:
            continue
        targets = _loop_targets(node.target)
        for call, op in _collectives_under_stmts(node.body):
            name_expr = _name_argument(call, op)
            if name_expr is None:
                continue
            used = {n.id for n in ast.walk(name_expr)
                    if isinstance(n, ast.Name)}
            if used & targets:
                yield sf.finding(
                    call, "HVD002",
                    f"collective '{op}' name derives from iteration "
                    f"over an unordered container ({reason}): iteration "
                    f"order differs across processes, so ranks submit "
                    f"mismatched names at the same call index — iterate "
                    f"a sorted/ordered sequence instead")


# --------------------------------------------------------------- HVD003

def check_unnamed_in_loop(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for call, op in _collectives_under_stmts(node.body):
            if op not in NAMED_OP_NAMES:
                continue
            name_expr = _name_argument(call, op)
            if name_expr is None or (isinstance(name_expr, ast.Constant)
                                     and name_expr.value is None):
                yield sf.finding(
                    call, "HVD003",
                    f"unnamed collective '{op}' inside a loop: "
                    f"auto-assigned names collide across iterations "
                    f"(reference DUPLICATE_NAME_ERROR) and make "
                    f"timeline/stall diagnostics ambiguous — pass "
                    f"name=")


# --------------------------------------------------------------- HVD004

def _ps_entries(stmts: Iterable[ast.stmt], sf: SourceFile,
                graph: CallGraph
                ) -> Iterator[Tuple[str, ast.Call, Optional[str]]]:
    """(op, anchor call, process_set repr) for every collective a
    branch issues — directly, or transitively through a resolvable
    helper (with the helper's symbolic process_set substituted from
    this call site's arguments)."""
    for call, op in _collectives_under_stmts(stmts):
        ps = _kwarg(call, "process_set")
        yield op, call, (ast.dump(ps) if ps is not None else None)
    for call in _calls_under_stmts(stmts):
        for op, ps, _origin in graph.call_effects(sf, call):
            yield op, call, ps


def check_process_set_branches(sf: SourceFile) -> Iterator[Finding]:
    graph = _graph(sf)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.If) or not node.orelse:
            continue
        body_ps: Dict[str, Tuple[ast.Call, Set[Optional[str]]]] = {}
        for op, call, ps in _ps_entries(node.body, sf, graph):
            anchor, seen = body_ps.setdefault(op, (call, set()))
            seen.add(ps)
        for op, call, ps in _ps_entries(node.orelse, sf, graph):
            if op not in body_ps:
                continue
            other_call, seen = body_ps[op]
            if ps not in seen:
                yield sf.finding(
                    call, "HVD004",
                    f"'{op}' uses a different process_set than the "
                    f"matching call in the other branch (line "
                    f"{other_call.lineno}): unless the condition is "
                    f"globally uniform, ranks disagree on who "
                    f"participates")


# --------------------------------------------------------------- HVD005

def _scopes(sf: SourceFile) -> Iterator[Optional[ast.AST]]:
    """Module top level plus every (async) function/method — the taint
    scopes. Async defs carry the same divergence bug class."""
    yield None
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scope_stmts(sf: SourceFile,
                 scope: Optional[ast.AST]) -> List[ast.stmt]:
    return (sf.tree.body if scope is None else scope.body)


def check_rank_tainted_name(sf: SourceFile) -> Iterator[Finding]:
    graph = _graph(sf)
    for scope in _scopes(sf):
        env = graph.taint_env(sf, scope)
        for stmt in _scope_stmts(sf, scope):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # covered by its own taint scope
            for call in _calls_pruned(stmt):
                op = is_collective_call(call)
                if op is not None:
                    name_expr = _name_argument(call, op)
                    if name_expr is not None \
                            and env.rank_tainted(name_expr):
                        yield sf.finding(
                            call, "HVD005",
                            f"collective '{op}' name derives from a "
                            f"rank-dependent value: ranks submit "
                            f"DIFFERENT names at the same call index "
                            f"and pair up mismatched tensors — "
                            f"collective names must be identical on "
                            f"every rank")
                    continue
                for callee in graph.resolve(sf, call):
                    for idx in sorted(callee.name_taint_params):
                        arg = CallGraph._arg_for_param(callee, call, idx)
                        if arg is not None and env.rank_tainted(arg):
                            pname = (callee.params[idx]
                                     if idx < len(callee.params)
                                     else f"#{idx}")
                            yield sf.finding(
                                call, "HVD005",
                                f"argument '{pname}' of "
                                f"{callee.label()} flows into a "
                                f"collective name and is "
                                f"rank-dependent here: collective "
                                f"names must be identical on every "
                                f"rank")


RULES = {
    "HVD001": ("collective under rank-dependent control flow "
               "(direct or through a helper call)",
               check_rank_dependent),
    "HVD002": ("collective named from iteration over an unordered "
               "container", check_unordered_naming),
    "HVD003": ("unnamed collective inside a loop (auto-name collision)",
               check_unnamed_in_loop),
    "HVD004": ("process_set differs across branches (direct or across "
               "call sites)", check_process_set_branches),
    "HVD005": ("collective name derived from a rank-dependent value",
               check_rank_tainted_name),
}
