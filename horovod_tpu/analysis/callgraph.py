"""Module-level call graph + rank-taint dataflow for hvdlint.

The HVD0xx rules were deliberately lexical in PR 3 — and went blind the
moment a collective moved into a helper::

    def sync(x):
        return hvd.allreduce(x, name="s")

    if hvd.rank() == 0:
        sync(x)          # lexical HVD001 sees no collective here

This module closes that hole. ``CallGraph`` is built once per lint run
over every parsed file, then shared by the rules through ``sf.graph``:

* **Function summaries.** For each module-level function and each method
  it records which collectives the function issues *transitively* (with
  the ``process_set=`` expression, parameter references kept symbolic so
  call sites can substitute their own argument), whether its return
  value is rank-tainted, which parameters flow through to the return
  value, and which parameters flow into a collective ``name=``.
  Summaries are computed to a fixpoint, so chains and recursion are
  handled (sets only grow, so iteration terminates).

* **Taint.** A value is *rank-tainted* when it derives from
  ``hvd.rank()`` / ``local_rank()`` / ``cross_rank()`` /
  ``process_index()`` — the seed of every SPMD-divergence bug this
  package hunts. Taint is tracked flow-insensitively per scope
  (module top level seeds the functions below it) and across calls via
  the summaries: resolvable callees contribute exactly what their
  summary says; unresolvable calls conservatively union their argument
  taints (``str(rank())`` stays tainted, ``helper()`` with clean args
  stays clean).

* **Resolution is deliberately narrow** to keep false positives out of
  ``make lint``: a bare name resolves to a same-module function, or —
  when the name was brought in by ``from m import f`` — to any linted
  module-level function of that name; ``self.m()`` resolves within the
  enclosing class; ``alias.f()`` resolves only when ``alias`` is an
  imported *module* name in that file. Arbitrary attribute calls
  (``obj.save()``) stay unresolved: guessing there is how a linter
  starts crying wolf.

This module is the lower layer: it owns the collective-call model
(``COLLECTIVE_NAMES`` et al.) so both rule families and the graph can
share it without an import cycle.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

#: Taint-source sentinel for process-identity values.
RANK = "rank()"

#: Calls that return this process's identity — the seed of
#: rank-dependent control flow and rank-dependent names.
RANK_CALL_NAMES: Set[str] = {
    "rank", "local_rank", "cross_rank", "process_index",
}

#: The eager collective API surface (ops/collectives.py) plus the
#: high-level wrappers that submit collectives on the caller's behalf
#: (optim/functions.py).
COLLECTIVE_NAMES: Set[str] = {
    "allreduce", "grouped_allreduce", "bucketed_allreduce", "allgather",
    "grouped_allgather",
    "broadcast", "reducescatter", "grouped_reducescatter", "alltoall",
    "barrier",
    "allreduce_async", "grouped_allreduce_async", "bucketed_allreduce_async",
    "allgather_async",
    "broadcast_async", "alltoall_async", "reducescatter_async",
    "broadcast_object", "broadcast_parameters", "broadcast_variables",
    "broadcast_optimizer_state", "allgather_object",
}

#: Ops whose reference auto-naming collides across loop iterations
#: (HVD003), mapped to the 0-based POSITIONAL index of their `name`
#: parameter (ops/collectives.py signatures; the frontends mirror
#: them). The broadcast_* / *_object wrappers name their tensors
#: internally and barrier takes no name.
NAME_ARG_POS: Dict[str, Tuple[int, ...]] = {
    "allreduce": (2,), "grouped_allreduce": (2,),
    "bucketed_allreduce": (2,), "bucketed_allreduce_async": (2,),
    "allgather": (1,), "grouped_allgather": (1,),
    "broadcast": (2,), "reducescatter": (2,),
    "grouped_reducescatter": (2,), "alltoall": (2,),
    "allreduce_async": (2,),
    # torch's async wrapper takes name at position 1
    # (frontends/torch.py), the core alias at 2 — accept either.
    "grouped_allreduce_async": (1, 2),
    "allgather_async": (1,), "broadcast_async": (2,),
    "alltoall_async": (2,), "reducescatter_async": (2,),
}
NAMED_OP_NAMES: Set[str] = set(NAME_ARG_POS)

#: Receivers whose methods share names with our API but are NOT Horovod
#: collectives (np.broadcast, tf.broadcast_to's relatives, etc.).
FOREIGN_ROOTS: Set[str] = {
    "np", "numpy", "jnp", "jax", "lax", "torch", "tf", "tensorflow",
    "mx", "mxnet", "keras", "K",
}


def terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def root_name(func: ast.AST) -> Optional[str]:
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def is_collective_call(node: ast.AST) -> Optional[str]:
    """The collective's op name if `node` is a Horovod collective call."""
    if not isinstance(node, ast.Call):
        return None
    name = terminal_name(node.func)
    if name not in COLLECTIVE_NAMES:
        return None
    if isinstance(node.func, ast.Attribute) \
            and root_name(node.func) in FOREIGN_ROOTS:
        return None
    return name


def kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def name_argument(call: ast.Call, op: str) -> Optional[ast.expr]:
    """The expression passed as `name` — keyword or positional."""
    expr = kwarg(call, "name")
    if expr is not None:
        return expr
    for pos in NAME_ARG_POS.get(op, ()):
        if len(call.args) > pos \
                and not isinstance(call.args[pos], ast.Starred):
            return call.args[pos]
    return None


def contains_rank_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and terminal_name(sub.func) in RANK_CALL_NAMES:
            return True
    return False


# ------------------------------------------------------------- summaries

#: A symbolic ``process_set=`` value: ("none",) when absent,
#: ("param", i) when the callee passes its own i-th parameter through,
#: ("expr", <ast.dump>) otherwise.
PsToken = Tuple[str, ...]
PS_NONE: PsToken = ("none",)


class FunctionInfo:
    """One linted function/method and its transitive-effect summary."""

    __slots__ = ("name", "cls", "path", "lineno", "node", "sf", "params",
                 "collectives", "origins", "tainted_return",
                 "return_taint_params", "name_taint_params")

    def __init__(self, name: str, cls: Optional[str], sf, node) -> None:
        self.name = name
        self.cls = cls
        self.sf = sf
        self.path = sf.path
        self.lineno = node.lineno
        self.node = node
        self.params = [a.arg for a in node.args.args]
        # op -> set of PsToken this function (transitively) issues it with
        self.collectives: Dict[str, Set[PsToken]] = {}
        # op -> human-readable origin ("m.py:12" or "via 'g' (m.py:3)")
        self.origins: Dict[str, str] = {}
        self.tainted_return = False
        self.return_taint_params: Set[int] = set()
        self.name_taint_params: Set[int] = set()

    def label(self) -> str:
        qual = f"{self.cls}.{self.name}" if self.cls else self.name
        return f"'{qual}' ({self.path}:{self.lineno})"


def _path_is_module(path: str, module: str) -> bool:
    """Does the file at `path` implement dotted `module`? Suffix-matched
    so relative imports ("checkpoint") and absolute ones
    ("horovod_tpu.checkpoint") both pair with
    "horovod_tpu/checkpoint.py" (or the package's __init__.py)."""
    p = path.replace("\\", "/")
    if p.endswith("/__init__.py"):
        p = p[: -len("/__init__.py")]
    elif p.endswith(".py"):
        p = p[:-3]
    dotted = p.strip("/").replace("/", ".")
    return dotted == module or dotted.endswith("." + module)


class CallGraph:
    """Call graph + summaries over a set of parsed SourceFiles."""

    #: Fixpoint bounds — generous for any real repo, tiny for safety.
    _MAX_ROUNDS = 20
    _MAX_LOCAL_ROUNDS = 8

    def __init__(self, sfs: Sequence) -> None:
        self._sfs = list(sfs)
        self._by_name: Dict[str, List[FunctionInfo]] = {}
        self._methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        # per file: from-imported name -> source module ("" = unknowable,
        # e.g. `from . import x`; resolution then stays empty rather than
        # guessing across same-named functions).
        self._from_imports: Dict[str, Dict[str, str]] = {}
        # per file: bound import name -> the module it denotes
        # (`import a.b as z` -> {"z": "a.b"}; `import a.b` -> {"a": "a"}).
        self._module_aliases: Dict[str, Dict[str, str]] = {}
        # (path, id(call node)) -> enclosing class name (for self.x())
        self._call_cls: Dict[Tuple[str, int], Optional[str]] = {}
        self._taint_cache: Dict[Tuple[str, int], "_TaintEnv"] = {}
        for sf in self._sfs:
            self._index_file(sf)
        self._summarize_all()
        # Taint envs built DURING the summary fixpoint saw half-built
        # summaries (e.g. a module global assigned from a helper whose
        # tainted_return had not been discovered yet). Drop them so the
        # rules recompute against the final summaries.
        self._taint_cache.clear()

    # ------------------------------------------------------------ indexing
    def _index_file(self, sf) -> None:
        froms: Dict[str, str] = {}
        aliases: Dict[str, str] = {}
        self._from_imports[sf.path] = froms
        self._module_aliases[sf.path] = aliases

        def visit(node: ast.AST, cls: Optional[str], depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Import):
                    for a in child.names:
                        if a.asname:
                            aliases[a.asname] = a.name
                        else:
                            root = a.name.split(".")[0]
                            aliases[root] = root
                elif isinstance(child, ast.ImportFrom):
                    for a in child.names:
                        froms[a.asname or a.name] = child.module or ""
                if isinstance(child, ast.Call):
                    self._call_cls[(sf.path, id(child))] = cls
                child_cls, child_depth = cls, depth
                if isinstance(child, ast.ClassDef):
                    child_cls = child.name
                    child_depth = depth + 1
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fi = FunctionInfo(child.name, cls, sf, child)
                    if depth == 0 and cls is None:
                        self._by_name.setdefault(child.name,
                                                 []).append(fi)
                    elif cls is not None:
                        self._methods[(sf.path, cls, child.name)] = fi
                    else:
                        # nested def: indexed nowhere, but its calls
                        # still carry class context for self.x().
                        pass
                    child_depth = depth + 1
                visit(child, child_cls, child_depth)

        visit(sf.tree, None, 0)

    def _all_functions(self) -> Iterator[FunctionInfo]:
        for fis in self._by_name.values():
            yield from fis
        yield from self._methods.values()

    # ---------------------------------------------------------- resolution
    def resolve(self, sf, call: ast.Call) -> List[FunctionInfo]:
        """Linted functions a call may land in ([] = unknown/foreign)."""
        func = call.func
        if isinstance(func, ast.Name):
            cands = self._by_name.get(func.id, [])
            local = [f for f in cands if f.path == sf.path]
            if local:
                return local
            mod = self._from_imports.get(sf.path, {}).get(func.id)
            if mod:
                # Only functions defined in THAT module: a name imported
                # from an unlinted module must not resolve to an
                # unrelated same-named linted function.
                return [f for f in cands if _path_is_module(f.path, mod)]
            return []
        if isinstance(func, ast.Attribute):
            # Full receiver chain: `a.b.f(...)` -> segs ["a","b"],
            # terminal "f".
            segs: List[str] = []
            node = func.value
            while isinstance(node, ast.Attribute):
                segs.append(node.attr)
                node = node.value
            if not isinstance(node, ast.Name):
                return []
            segs.append(node.id)
            segs.reverse()
            root = segs[0]
            if root in ("self", "cls") and len(segs) == 1:
                encl = self._call_cls.get((sf.path, id(call)))
                if encl is not None:
                    fi = self._methods.get((sf.path, encl, func.attr))
                    if fi is not None:
                        return [fi]
                return []
            if root in FOREIGN_ROOTS:
                return []
            aliasmod = self._module_aliases.get(sf.path, {}).get(root)
            if aliasmod:
                # The callee must live in the module the alias denotes —
                # never "any linted function with that name".
                module = ".".join([aliasmod] + segs[1:])
                return [f for f in self._by_name.get(func.attr, [])
                        if _path_is_module(f.path, module)]
        return []

    # ------------------------------------------------------------- effects
    def call_effects(self, sf, call: ast.Call
                     ) -> List[Tuple[str, Optional[str], str]]:
        """(op, concrete ps repr, origin label) for every collective a
        resolvable non-collective call transitively issues, with
        parameter-symbolic process sets substituted from this call's
        arguments. Empty for direct collectives and unresolved calls."""
        if is_collective_call(call) is not None:
            return []
        out: List[Tuple[str, Optional[str], str]] = []
        for fi in self.resolve(sf, call):
            for op, tokens in fi.collectives.items():
                origin = (f"via {fi.label()}"
                          if fi.origins.get(op, "").startswith("via")
                          else f"in {fi.label()}")
                for tok in tokens:
                    out.append((op, self._subst_ps(fi, call, tok), origin))
        return out

    def _subst_ps(self, fi: FunctionInfo, call: ast.Call,
                  tok: PsToken) -> Optional[str]:
        if tok == PS_NONE:
            return None
        if tok[0] == "expr":
            return tok[1]
        idx = int(tok[1])
        arg = self._arg_for_param(fi, call, idx)
        return ast.dump(arg) if arg is not None else None

    @staticmethod
    def _arg_for_param(fi: FunctionInfo, call: ast.Call,
                       idx: int) -> Optional[ast.expr]:
        """The call-site expression bound to `fi`'s idx-th parameter."""
        pos = idx
        if fi.params and fi.params[0] in ("self", "cls") \
                and isinstance(call.func, ast.Attribute):
            pos = idx - 1  # bound method: self is implicit at the call
        if 0 <= pos < len(call.args) \
                and not any(isinstance(a, ast.Starred)
                            for a in call.args[:pos + 1]):
            return call.args[pos]
        if 0 <= idx < len(fi.params):
            return kwarg(call, fi.params[idx])
        return None

    # --------------------------------------------------------------- taint
    def taint_env(self, sf, scope: Optional[ast.AST]) -> "_TaintEnv":
        """Flow-insensitive taint for one scope (None = module top
        level); function scopes are seeded with the module scope's
        tainted globals."""
        node = scope if scope is not None else sf.tree
        key = (sf.path, id(node))
        env = self._taint_cache.get(key)
        if env is None:
            seed: Dict[str, Set[str]] = {}
            if scope is not None:
                seed = dict(self.taint_env(sf, None).vars)
            env = _TaintEnv(self, sf, node, seed)
            self._taint_cache[key] = env
        return env

    # ------------------------------------------------------------ summaries
    def _summarize_all(self) -> None:
        funcs = list(self._all_functions())
        for _ in range(self._MAX_ROUNDS):
            changed = False
            for fi in funcs:
                if self._summarize(fi):
                    changed = True
            if not changed:
                return

    def _summarize(self, fi: FunctionInfo) -> bool:
        """One summary pass; True if anything grew."""
        env = _TaintEnv(self, fi.sf, fi.node,
                        dict(self.taint_env(fi.sf, None).vars))
        changed = False
        for node in _scope_walk(fi.node):
            if isinstance(node, ast.Return) and node.value is not None:
                t = env.expr(node.value)
                if RANK in t and not fi.tainted_return:
                    fi.tainted_return = changed = True
                for src in t:
                    if isinstance(src, tuple) \
                            and src[1] not in fi.return_taint_params:
                        fi.return_taint_params.add(src[1])
                        changed = True
            if not isinstance(node, ast.Call):
                continue
            op = is_collective_call(node)
            if op is not None:
                tok = self._ps_token(fi, node)
                if tok not in fi.collectives.setdefault(op, set()):
                    fi.collectives[op].add(tok)
                    changed = True
                fi.origins.setdefault(op, f"{fi.path}:{node.lineno}")
                name_expr = name_argument(node, op)
                if name_expr is not None:
                    for src in env.expr(name_expr):
                        if isinstance(src, tuple) \
                                and src[1] not in fi.name_taint_params:
                            fi.name_taint_params.add(src[1])
                            changed = True
                continue
            for callee in self.resolve(fi.sf, node):
                for op, tokens in callee.collectives.items():
                    mine = fi.collectives.setdefault(op, set())
                    for tok in tokens:
                        tok = self._retoken(fi, callee, node, tok)
                        if tok not in mine:
                            mine.add(tok)
                            changed = True
                    fi.origins.setdefault(op, f"via {callee.label()}")
                for idx in callee.name_taint_params:
                    arg = self._arg_for_param(callee, node, idx)
                    if arg is None:
                        continue
                    for src in env.expr(arg):
                        if isinstance(src, tuple) \
                                and src[1] not in fi.name_taint_params:
                            fi.name_taint_params.add(src[1])
                            changed = True
        return changed

    def _ps_token(self, fi: FunctionInfo, call: ast.Call) -> PsToken:
        ps = kwarg(call, "process_set")
        if ps is None:
            return PS_NONE
        if isinstance(ps, ast.Name) and ps.id in fi.params:
            return ("param", fi.params.index(ps.id))
        return ("expr", ast.dump(ps))

    def _retoken(self, fi: FunctionInfo, callee: FunctionInfo,
                 call: ast.Call, tok: PsToken) -> PsToken:
        """Rewrite a callee's symbolic ps token into this function's
        frame: callee-parameter references become either our own
        parameter references or the concrete call-site expression."""
        if tok == PS_NONE or tok[0] == "expr":
            return tok
        arg = self._arg_for_param(callee, call, int(tok[1]))
        if arg is None:
            return PS_NONE
        if isinstance(arg, ast.Name) and arg.id in fi.params:
            return ("param", fi.params.index(arg.id))
        return ("expr", ast.dump(arg))


def _scope_walk(root: ast.AST) -> Iterator[ast.AST]:
    """ast.walk pruned at nested function/class boundaries."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node is not root:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _TaintEnv:
    """Per-scope taint table: name -> set of sources (RANK and/or
    ("param", i))."""

    def __init__(self, graph: CallGraph, sf, scope: ast.AST,
                 seed: Dict[str, Set[str]]) -> None:
        self.graph = graph
        self.sf = sf
        self.vars: Dict[str, Set] = dict(seed)
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for i, a in enumerate(scope.args.args):
                self.vars.setdefault(a.arg, set()).add(("param", i))
        self._solve(scope)

    def _solve(self, scope: ast.AST) -> None:
        binds: List[Tuple[ast.expr, ast.expr]] = []
        for node in _scope_walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    binds.append((t, node.value))
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if node.value is not None:
                    binds.append((node.target, node.value))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                binds.append((node.target, node.iter))
            elif isinstance(node, ast.NamedExpr):
                binds.append((node.target, node.value))
        for _ in range(CallGraph._MAX_LOCAL_ROUNDS):
            changed = False
            for target, value in binds:
                t = self.expr(value)
                if not t:
                    continue
                for n in ast.walk(target):
                    if isinstance(n, ast.Name):
                        cur = self.vars.setdefault(n.id, set())
                        if not t <= cur:
                            cur.update(t)
                            changed = True
            if not changed:
                return

    def expr(self, e: ast.AST) -> Set:
        if isinstance(e, ast.Name):
            return set(self.vars.get(e.id, ()))
        if isinstance(e, ast.Call):
            if terminal_name(e.func) in RANK_CALL_NAMES:
                return {RANK}
            callees = self.graph.resolve(self.sf, e)
            if callees:
                out: Set = set()
                for fi in callees:
                    if fi.tainted_return:
                        out.add(RANK)
                    for idx in fi.return_taint_params:
                        arg = CallGraph._arg_for_param(fi, e, idx)
                        if arg is not None:
                            out |= self.expr(arg)
                return out
            # Unresolved call: conservatively, taint flows through
            # arguments (str(rank()), format(...), sorted(...)).
            out = set()
            for a in e.args:
                out |= self.expr(a)
            for kw in e.keywords:
                out |= self.expr(kw.value)
            return out
        if isinstance(e, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef)):
            return set()
        out: Set = set()
        for child in ast.iter_child_nodes(e):
            out |= self.expr(child)
        return out

    def rank_tainted(self, e: ast.AST) -> bool:
        return RANK in self.expr(e)
