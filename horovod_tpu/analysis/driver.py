"""hvdlint driver: rule registry, suppression handling, CLI.

One entrypoint (``python -m horovod_tpu.analysis``), one exit code, one
output format::

    file:line RULE-ID message

Rules have stable IDs (HVD0xx collective consistency, HVD1xx concurrency
discipline, HVD-ENV documentation drift). A finding on a line is
suppressed by a trailing ``hvdlint: disable=HVD001 -- root-only by
design`` comment on that line. The rationale after ``--`` is mandatory:
a bare suppression is itself a finding (HVD000), so every silenced rule
carries an explanation a reviewer can audit. ``disable`` with no ID list
suppresses every rule on the line (rationale still required).

Findings also feed the process metrics registry
(``hvdlint_findings_total{rule}``, observability/metrics.py) so lint runs
wired into jobs surface in the same telemetry plane as the runtime.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import pathlib
import re
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: Suppression comment grammar (docs/static_analysis.md). A rule ID
#: token may contain single dashes (HVD-ENV) but the token pattern
#: cannot cross the ``--`` rationale separator.
_ID_TOKEN = r"[A-Za-z0-9_]+(?:-[A-Za-z0-9_]+)*"
_SUPPRESS_RE = re.compile(
    r"#\s*hvdlint:\s*disable"
    rf"(?:=(?P<ids>{_ID_TOKEN}(?:\s*,\s*{_ID_TOKEN})*))?"
    r"(?:\s*--\s*(?P<why>\S.*))?")

#: Suppress-all sentinel in a parsed suppression entry.
_ALL = "*"

HVD000 = "HVD000"

#: Shared by the AST pass and the HVD-ENV pass — lint_paths dedupes
#: cross-pass findings by exact message, so there must be ONE copy.
MSG_NO_RATIONALE = ("suppression comment lacks a rationale: append "
                    "' -- <why this is safe>' to the disable comment")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a source location."""

    path: str
    line: int
    rule_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule_id} {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line,
                "rule": self.rule_id, "message": self.message}


def parse_suppression(line: str) -> Optional[Tuple[Set[str], bool]]:
    """(suppressed rule ids or {"*"}, has_rationale) for one source
    line, or None if it carries no suppression comment. Shared by the
    AST rules (via SourceFile) and the repo-level HVD-ENV rule."""
    m = _SUPPRESS_RE.search(line)
    if not m:
        return None
    ids = m.group("ids")
    ruleset = ({_ALL} if ids is None else
               {i.strip().upper() for i in ids.split(",") if i.strip()})
    return ruleset, m.group("why") is not None


def suppression_covers(entry: Optional[Tuple[Set[str], bool]],
                       rule_id: str) -> bool:
    if entry is None:
        return False
    ruleset, _ = entry
    return _ALL in ruleset or rule_id.upper() in ruleset


class SourceFile:
    """Parsed source + per-line suppression table shared by every rule."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line -> (set of suppressed rule ids or {_ALL}, has_rationale)
        self.suppressions: Dict[int, Tuple[Set[str], bool]] = {}
        for lineno, line in enumerate(self.lines, 1):
            entry = parse_suppression(line)
            if entry is not None:
                self.suppressions[lineno] = entry

    def suppressed(self, line: int, rule_id: str) -> bool:
        return suppression_covers(self.suppressions.get(line), rule_id)

    def finding(self, node: ast.AST, rule_id: str, message: str) -> Finding:
        return Finding(self.path, getattr(node, "lineno", 1), rule_id,
                       message)


def _rationale_findings(sf: SourceFile) -> Iterable[Finding]:
    """HVD000: a suppression without a ``-- rationale`` is a finding."""
    for lineno, (_ids, has_why) in sorted(sf.suppressions.items()):
        if not has_why:
            yield Finding(sf.path, lineno, HVD000, MSG_NO_RATIONALE)


def registry() -> Dict[str, Tuple[str, object]]:
    """rule_id -> (one-line description, check(sf) -> iterable[Finding]).

    Imported lazily so the CLI only pays for (and only can fail on) the
    rule modules it actually runs.
    """
    from horovod_tpu.analysis import collective_rules, concurrency_rules
    reg: Dict[str, Tuple[str, object]] = {}
    reg.update(collective_rules.RULES)
    reg.update(concurrency_rules.RULES)
    return reg


#: Lowered-program rule families: (CLI flag, analysis module exposing a
#: ``registry()`` hook). ``--list-rules`` derives its listing from this
#: table, so a new family appears by registering here ONCE — the
#: hand-maintained per-family import list this replaces silently
#: dropped new families (tests assert every HVD rule documented in
#: docs/static_analysis.md is reachable through it).
HLO_RULE_FAMILIES: Tuple[Tuple[str, str], ...] = (
    ("--hlo", "horovod_tpu.analysis.hlo"),
    ("--shard", "horovod_tpu.analysis.shard"),
    ("--sched", "horovod_tpu.analysis.schedule"),
    ("--num", "horovod_tpu.analysis.numerics"),
)


def family_registries() -> Dict[str, Dict[str, Tuple[str, object]]]:
    """CLI flag -> that family's rule registry, one entry per
    HLO_RULE_FAMILIES row (imported lazily, like registry())."""
    import importlib
    out: Dict[str, Dict[str, Tuple[str, object]]] = {}
    for flag, modname in HLO_RULE_FAMILIES:
        out[flag] = importlib.import_module(modname).registry()
    return out


def lint_source(text: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None,
                ignore: Sequence[str] = (),
                graph: Optional[object] = None) -> List[Finding]:
    """Run the AST rule families over one source blob (unit-test surface).

    Returns surviving findings (suppressions applied), sorted by line.
    `graph` is the lint run's shared CallGraph; a single-blob run builds
    its own one-file graph.
    """
    sf = SourceFile(path, text)
    return _lint_sf(sf, select=select, ignore=ignore, graph=graph)


def _lint_sf(sf: SourceFile,
             select: Optional[Sequence[str]] = None,
             ignore: Sequence[str] = (),
             graph: Optional[object] = None) -> List[Finding]:
    if graph is None:
        from horovod_tpu.analysis.callgraph import CallGraph
        graph = CallGraph([sf])
    sf.graph = graph
    reg = registry()
    wanted = {r.upper() for r in select} if select is not None else None
    ignored = {r.upper() for r in ignore}
    out: List[Finding] = []
    if (wanted is None or HVD000 in wanted) and HVD000 not in ignored:
        out.extend(_rationale_findings(sf))
    for rule_id, (_desc, check) in sorted(reg.items()):
        if wanted is not None and rule_id not in wanted:
            continue
        if rule_id in ignored:
            continue
        for f in check(sf):
            if not sf.suppressed(f.line, f.rule_id):
                out.append(f)
    out.sort(key=lambda f: (f.line, f.rule_id))
    return out


def _iter_py_files(paths: Sequence[str]) -> Iterable[pathlib.Path]:
    seen: Set[pathlib.Path] = set()
    for p in paths:
        path = pathlib.Path(p)
        candidates = (sorted(path.rglob("*.py")) if path.is_dir()
                      else [path])
        for c in candidates:
            c = c.resolve()
            if c in seen or c.suffix != ".py" or not c.exists():
                continue
            # Generated/vendored trees have no lint contract.
            if "__pycache__" in c.parts:
                continue
            seen.add(c)
            yield c


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Sequence[str] = (),
               root: Optional[str] = None,
               env_rule: bool = True) -> List[Finding]:
    """Lint every ``*.py`` under `paths` + the repo-level HVD-ENV rule."""
    findings: List[Finding] = []
    for p in paths:
        # A typo'd path must FAIL the gate, not silently lint nothing —
        # this command fronts CI.
        if not pathlib.Path(p).exists():
            findings.append(Finding(str(p), 1, "HVD999",
                                    "path does not exist"))
    # Parse everything FIRST: the interprocedural rules need one call
    # graph spanning every linted file before any rule runs.
    sfs: List[SourceFile] = []
    for path in _iter_py_files(paths):
        rel = path
        if root is not None:
            try:
                rel = path.relative_to(pathlib.Path(root).resolve())
            except ValueError:
                pass
        try:
            text = path.read_text(encoding="utf-8")
            sfs.append(SourceFile(str(rel), text))
        except SyntaxError as e:
            findings.append(Finding(str(rel), e.lineno or 1, "HVD999",
                                    f"syntax error: {e.msg}"))
        except OSError as e:
            findings.append(Finding(str(rel), 1, "HVD999",
                                    f"unreadable: {e}"))
    from horovod_tpu.analysis.callgraph import CallGraph
    graph = CallGraph(sfs)
    for sf in sfs:
        findings.extend(_lint_sf(sf, select=select, ignore=ignore,
                                 graph=graph))
    if env_rule and (select is None or "HVD-ENV" in
                     {s.upper() for s in select}) \
            and "HVD-ENV" not in {i.upper() for i in ignore}:
        from horovod_tpu.analysis import env_rule as env_mod
        findings.extend(env_mod.check_project(root))
    # The AST pass and the project-level HVD-ENV pass can both report
    # the same location (e.g. HVD000 for one bare suppression): dedupe.
    unique: Dict[Tuple[str, int, str, str], Finding] = {}
    for f in findings:
        unique.setdefault((f.path, f.line, f.rule_id, f.message), f)
    findings = list(unique.values())
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def _baseline_key(f: Finding) -> Tuple[str, str, str]:
    """Baseline identity for a finding. Line numbers churn with every
    unrelated edit, so they are excluded — both the anchor line and any
    line references embedded in the message (normalized to 'N')."""
    return (f.path, f.rule_id, re.sub(r"\d+", "N", f.message))


def load_baseline(path: str) -> Counter:
    """Multiset of accepted findings from a ``--format json`` dump (or a
    bare JSON list of finding objects)."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data.get("findings", []) if isinstance(data, dict) else data
    # Shape errors must surface as ValueError so the CLI's 'unreadable
    # baseline' exit-2 path catches them (not an AttributeError crash).
    if not isinstance(entries, list) \
            or not all(isinstance(e, dict) for e in entries):
        raise ValueError(
            "baseline must be a --format json dump (or a JSON list of "
            "finding objects)")
    keys = []
    for e in entries:
        keys.append((str(e.get("path", "")), str(e.get("rule", "")),
                     re.sub(r"\d+", "N", str(e.get("message", "")))))
    return Counter(keys)


def apply_baseline(findings: Sequence[Finding],
                   baseline: Counter) -> Tuple[List[Finding], int]:
    """(new findings, count matched by the baseline). Multiplicity-aware:
    a baseline entry absorbs at most as many findings as it was recorded
    with, so a *new* duplicate of a baselined finding still gates."""
    budget = Counter(baseline)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        key = _baseline_key(f)
        if budget[key] > 0:
            budget[key] -= 1
            matched += 1
        else:
            new.append(f)
    return new, matched


def render_json(findings: Sequence[Finding]) -> str:
    """The ``--format json`` payload — also the baseline file format."""
    return json.dumps(
        {"findings": [f.as_dict() for f in findings],
         "count": len(findings)}, indent=2, sort_keys=True) + "\n"


def _record_metrics(findings: Sequence[Finding]) -> None:
    """Feed findings into the metrics plane (PR 2 registry); lint must
    still work in environments without the runtime deps, so any import
    failure is swallowed."""
    try:
        from horovod_tpu.observability import metrics as m
        counter = m.registry().counter(
            "hvdlint_findings_total", "hvdlint findings by rule",
            labelnames=("rule",))
        for f in findings:
            counter.labels(rule=f.rule_id).inc()
    except Exception:
        pass


def run_cli(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdlint: collective-consistency and concurrency "
                    "static analysis, plus hvdhlo compile-time lint of "
                    "lowered XLA programs via --hlo "
                    "(docs/static_analysis.md)")
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to lint")
    parser.add_argument("--hlo", action="store_true",
                        help="hvdhlo mode: treat paths as lowered "
                             "StableHLO/HLO text dumps and run the "
                             "HVD2xx rules over the program structure")
    parser.add_argument("--shard", action="store_true",
                        help="hvdshard mode: treat paths as lowered "
                             "StableHLO/post-SPMD HLO dumps and run "
                             "the HVD3xx sharding/memory rules; "
                             "combine with --hlo to run both families "
                             "over the same dumps")
    parser.add_argument("--sched", action="store_true",
                        help="hvdsched mode: treat paths as lowered "
                             "StableHLO/post-SPMD HLO dumps, "
                             "reconstruct the per-device collective "
                             "schedule and run the HVD4xx cross-device "
                             "matching + comms cost-model rules; ALL "
                             "paths are linted as one set so the "
                             "cross-program rules (HVD401/HVD403) see "
                             "every pairing; composes with --hlo and "
                             "--shard over the same dumps")
    parser.add_argument("--num", action="store_true",
                        help="hvdnum mode: treat paths as lowered "
                             "StableHLO/post-SPMD HLO dumps and run "
                             "the HVD5xx numerics rules — dtype-flow "
                             "(low-precision accumulation, downcast-"
                             "before-reduce), gradient-scale audit, "
                             "and the determinism hazards that void "
                             "bit-identical resume; ALL paths are "
                             "linted as one set so the cross-mesh "
                             "HVD505 diff sees every pairing; "
                             "composes with --hlo/--shard/--sched "
                             "over the same dumps")
    parser.add_argument("--hlo-step", default=None, metavar="PROGRAM",
                        choices=("lm", "resnet_block", "lm_sharded",
                                 "lm_runtime"),
                        help="hvdhlo mode: lower the named canonical "
                             "step program under the current fusion/"
                             "layout config on the virtual CPU mesh "
                             "and lint it (the `make hlo-lint` / "
                             "`make conv-smoke` / `make shard-lint` "
                             "CI gates); lm_sharded lints the 2-D "
                             "(batch x model) mesh GSPMD program and "
                             "lm_runtime the DistributedOptimizer-"
                             "driven hybrid runtime step, both under "
                             "BOTH rule families, pre- and post-SPMD")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule IDs to run (default all)")
    parser.add_argument("--ignore", default="",
                        help="comma-separated rule IDs to skip")
    parser.add_argument("--no-env", action="store_true",
                        help="skip the repo-level HVD-ENV docs-drift rule")
    parser.add_argument("--root", default=None,
                        help="repo root for HVD-ENV and relative paths "
                             "(default: auto-detected from this package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="fmt",
                        help="output format; json doubles as the "
                             "--baseline file format")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="diff-aware mode: findings recorded in FILE "
                             "(a --format json dump) are accepted; only "
                             "NEW findings are printed and gate the exit "
                             "code")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        from horovod_tpu.analysis import env_rule as env_mod
        reg = dict(registry())
        reg[env_mod.RULE_ID] = (env_mod.DESCRIPTION, None)
        reg[HVD000] = ("suppression comment lacks a rationale", None)
        for flag, family in family_registries().items():
            for rule_id, (desc, _check) in family.items():
                reg[rule_id] = (f"[{flag}] {desc}", None)
        for rule_id in sorted(reg):
            print(f"{rule_id}  {reg[rule_id][0]}")
        return 0

    hlo_mode = (args.hlo or args.shard or args.sched or args.num
                or args.hlo_step is not None)
    if not args.paths and not args.hlo_step:
        parser.error("no paths given (try: horovod_tpu/ examples/)")

    root = args.root
    if root is None:
        # horovod_tpu/analysis/driver.py -> repo root two levels up from
        # the package directory.
        root = str(pathlib.Path(__file__).resolve().parent.parent.parent)
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    ignore = [s.strip() for s in args.ignore.split(",") if s.strip()]
    if hlo_mode:
        from horovod_tpu.analysis import hlo as hlo_mod
        from horovod_tpu.analysis import numerics as num_mod
        from horovod_tpu.analysis import schedule as sched_mod
        from horovod_tpu.analysis import shard as shard_mod
        findings = []
        try:
            # File mode: --hlo runs HVD2xx, --shard runs HVD3xx,
            # --sched runs HVD4xx, --num runs HVD5xx; the flags
            # compose over the same dumps. A bare --hlo-step adds no
            # file findings (paths empty).
            if args.hlo or (args.paths and not args.shard
                            and not args.sched and not args.num):
                findings.extend(hlo_mod.lint_files(
                    args.paths, select=select, ignore=ignore))
            if args.shard:
                findings.extend(shard_mod.lint_files(
                    args.paths, select=select, ignore=ignore))
            if args.sched:
                findings.extend(sched_mod.lint_files(
                    args.paths, select=select, ignore=ignore))
            if args.num:
                findings.extend(num_mod.lint_files(
                    args.paths, select=select, ignore=ignore))
            if (args.hlo + args.shard + args.sched + args.num) > 1:
                findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
            if args.hlo_step in ("lm_sharded", "lm_runtime"):
                # The 2-D-mesh gates lint BOTH textual forms: the
                # HVD2xx program rules on the pre-partition MLIR
                # (global shapes) and the HVD3xx sharding/memory rules
                # on both it and the post-SPMD module (per-device
                # shapes + schedule). lm_sharded is the GSPMD
                # (annotation-driven) twin; lm_runtime lowers the
                # DistributedOptimizer-driven hybrid step the backend
                # actually executes.
                lower_fn = (shard_mod.lower_sharded_step_texts
                            if args.hlo_step == "lm_sharded"
                            else shard_mod.lower_runtime_step_texts)
                try:
                    texts = lower_fn()
                except Exception as e:
                    print(f"hvdshard: cannot lower step program "
                          f"{args.hlo_step!r}: {e}", file=sys.stderr)
                    return 2
                base = hlo_mod.step_path(args.hlo_step)
                findings.extend(hlo_mod.lint_text(
                    texts["stablehlo"], path=base,
                    select=select, ignore=ignore))
                for fmt, suffix in (("stablehlo", ""), ("hlo", ":spmd")):
                    findings.extend(shard_mod.lint_text(
                        texts[fmt], path=base[:-1] + suffix + ">",
                        select=select, ignore=ignore))
                # The HVD4xx schedule rules read the post-SPMD form
                # (scheduled order, per-device groups). Safe on the
                # default programs: single-program SPMD is internally
                # consistent (HVD401/403 vacuous) and HVD404/405 are
                # unarmed without their env knobs.
                findings.extend(sched_mod.lint_text(
                    texts["hlo"], path=base[:-1] + ":spmd>",
                    select=select, ignore=ignore))
                # The HVD5xx numerics rules also read the post-SPMD
                # form (real replica groups + the scale constants XLA
                # actually folded). The default programs accumulate in
                # f32 with group-sized scaling, so `make num-lint`
                # gates them against the empty baseline.
                findings.extend(num_mod.lint_text(
                    texts["hlo"], path=base[:-1] + ":spmd>",
                    select=select, ignore=ignore))
                findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
            elif args.hlo_step is not None:
                # Lowering failures must fail the gate loudly — a CI
                # host that cannot build the step program is not a
                # clean lint.
                try:
                    text = hlo_mod.lower_step_text(args.hlo_step)
                except Exception as e:
                    print(f"hvdhlo: cannot lower step program "
                          f"{args.hlo_step!r}: {e}", file=sys.stderr)
                    return 2
                findings.extend(hlo_mod.lint_text(
                    text, path=hlo_mod.step_path(args.hlo_step),
                    select=select, ignore=ignore))
                if args.num:
                    findings.extend(num_mod.lint_text(
                        text, path=hlo_mod.step_path(args.hlo_step),
                        select=select, ignore=ignore))
                findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
        except ValueError as e:
            # A malformed knob (HOROVOD_HLO_LINT_HBM_BUDGET=16GiB)
            # raises by design — but it is a TOOL error, not findings:
            # the driver's error convention is one line + exit 2
            # (lowering failures, unreadable baselines), never a
            # traceback that exits 1 as if findings were found.
            name = ("hvdnum" if args.num
                    and not (args.sched or args.shard)
                    else "hvdsched" if args.sched and not args.shard
                    else "hvdshard" if args.shard or args.hlo_step
                    in ("lm_sharded", "lm_runtime") else "hvdhlo")
            print(f"{name}: {e}", file=sys.stderr)
            return 2
    else:
        findings = lint_paths(args.paths, select=select, ignore=ignore,
                              root=root, env_rule=not args.no_env)
    matched = 0
    # A step-mode run narrowed to one family (make sched-lint /
    # make num-lint) reports as that family too, so the gate's clean
    # line names the tool that actually judged the program.
    sel_all_sched = bool(select) and all(
        re.fullmatch(r"HVD4\d\d", r.strip().upper()) for r in select)
    sel_all_num = bool(select) and all(
        re.fullmatch(r"HVD5\d\d", r.strip().upper()) for r in select)
    sched_only = ((args.sched or sel_all_sched)
                  and not (args.hlo or args.shard or args.num))
    num_only = ((args.num or sel_all_num)
                and not (args.hlo or args.shard or args.sched))
    shard_mode = args.shard or args.hlo_step in ("lm_sharded",
                                                 "lm_runtime")
    name = ("hvdnum" if num_only
            else "hvdsched" if sched_only
            else "hvdshard" if shard_mode
            else "hvdhlo" if hlo_mode else "hvdlint")
    if args.baseline is not None:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            # A broken baseline must fail the gate, not pass everything.
            print(f"{name}: unreadable baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        findings, matched = apply_baseline(findings, baseline)
    if hlo_mode:
        from horovod_tpu.analysis import hlo as hlo_mod
        from horovod_tpu.analysis import numerics as num_mod
        from horovod_tpu.analysis import schedule as sched_mod
        from horovod_tpu.analysis import shard as shard_mod
        # Each family owns its metric: HVD3xx ->
        # hvdshard_findings_total, HVD4xx -> hvdsched_findings_total,
        # HVD5xx -> hvdnum_findings_total, the rest ->
        # hvdhlo_findings_total.
        shard_f = [f for f in findings
                   if re.fullmatch(r"HVD3\d\d", f.rule_id)]
        sched_f = [f for f in findings
                   if re.fullmatch(r"HVD4\d\d", f.rule_id)]
        num_f = [f for f in findings
                 if re.fullmatch(r"HVD5\d\d", f.rule_id)]
        hlo_mod.record_metrics([f for f in findings
                                if f not in shard_f
                                and f not in sched_f
                                and f not in num_f])
        shard_mod.record_metrics(shard_f)
        sched_mod.record_metrics(sched_f)
        num_mod.record_metrics(num_f)
    else:
        _record_metrics(findings)
    if args.fmt == "json":
        sys.stdout.write(render_json(findings))
    else:
        for f in findings:
            print(f.render())
    if findings:
        tag = " new" if args.baseline is not None else ""
        print(f"{name}: {len(findings)}{tag} finding(s)"
              + (f" ({matched} baselined)" if matched else ""),
              file=sys.stderr)
        return 1
    if args.fmt != "json":
        print(f"{name}: clean"
              + (f" ({matched} baselined)" if matched else ""))
    return 0


def main() -> None:
    sys.exit(run_cli())
