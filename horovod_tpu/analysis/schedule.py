"""hvdsched: static cross-device collective-schedule verification and
the analytic ICI/DCN comms cost model (HVD4xx; docs/static_analysis.md).

The runtime fingerprint verifier (analysis/verifier.py) catches a
collective-order divergence only *live*, after every rank is already
hung inside the mismatched collective. hvdsched proves the same
property at compile time: it reconstructs the per-device collective
schedule from the lowered program text — every all-reduce /
all-gather / reduce-scatter / all-to-all / collective-permute /
send / recv with its replica groups (explicit list, V2 iota, permute
source-target pairs), channel id, and payload bytes, in scheduled
order — and checks that every member of every replica group reaches
the same collectives in the same order (analysis/sched_rules.py).

On top of the same event stream sits the analytic comms cost model
(the Megatron-LM-style hand analysis, mechanized): ring time =
wire_bytes / link_GB/s with the standard wire factors — 2(k-1)/k for
all-reduce, (k-1)/k for all-gather / reduce-scatter / all-to-all, one
hop for permute/send/recv — over a two-tier link table (fast
intra-slice ICI vs slow inter-slice DCN, the slice boundary declared
by ``HOROVOD_MESH_SLICES``; parallel/mesh.slice_groups). Constants
follow the flops.py policy: documented fallbacks, env-overridable
(``HOROVOD_SCHED_LINK_GBPS``), loud ValueError on garbage. bench.py
stamps :func:`comms_model` beside the measured ``comms_by_axis`` so
perfboard can track predicted-vs-measured across rounds, and both
attributions share ONE group classifier (shard.group_axis_label) so
they can never disagree on what a replica group means.

Like hvdshard, findings are baselined
(``scripts/hvdsched_baseline.json``), not suppressed inline.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis.hlo import HloOp, HloProgram, parse
from horovod_tpu.analysis.shard import (
    _SOURCE_TARGET_RE,
    _axis_partitions,
    _bytes_env,
    _parse_replica_groups,
    group_axis_label,
)

_MB = 1024 * 1024

#: Opcodes that participate in the cross-device schedule. Async pairs
#: fold onto their ``*_start`` half (the issue point in the schedule);
#: the ``*_done`` halves are dropped.
SCHED_OPCODES = frozenset({
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "collective_permute", "send", "recv",
})

_ASYNC_START = re.compile(r"^(.*)_start$")
_ASYNC_DONE = re.compile(r"^(.*)_done$")

# StableHLO attribute forms (post-SPMD HLO text forms are delegated to
# shard._parse_replica_groups / _SOURCE_TARGET_RE — one parser, not two).
_DENSE_GROUPS_RE = re.compile(r"replica_groups\s*=\s*dense<([^>]*)>")
_DENSE_PAIRS_RE = re.compile(r"source_target_pairs\s*=\s*dense<([^>]*)>")
_CHANNEL_MLIR_RE = re.compile(
    r"channel_handle\s*=\s*#stablehlo\.channel_handle<\s*handle\s*=\s*(\d+)")
_CHANNEL_HLO_RE = re.compile(r"channel_id=(\d+)")


def _parse_dense_rows(body: str) -> Optional[List[List[int]]]:
    """Rows of a 2-D ``dense<[[0, 1], [2, 3]]>`` literal (or a splat
    ``dense<0>``), as lists of ints; None when unparseable."""
    body = body.strip()
    if body.startswith("[["):
        rows = re.findall(r"\[([\d,\s-]*)\]", body[1:-1])
        out = []
        for row in rows:
            cells = [c for c in row.replace(" ", "").split(",") if c]
            out.append([int(c) for c in cells])
        return out
    if re.fullmatch(r"-?\d+", body):
        return [[int(body)]]
    return None


# -------------------------------------------------- the event stream

@dataclasses.dataclass(frozen=True)
class CollectiveEvent:
    """One scheduled collective, as every participating device sees it."""

    line: int
    opcode: str                              # canonical (start/done folded)
    groups: Tuple[Tuple[int, ...], ...]      # sorted device-id groups
    pairs: Optional[Tuple[Tuple[int, int], ...]]  # permute (src, tgt)
    channel_id: Optional[int]
    nbytes: int                              # payload (pre-wire-factor)
    path: str

    @property
    def signature(self) -> Tuple:
        """What must match across devices for the schedule to agree:
        (opcode, replica groups, payload bytes). Channel ids are
        assigned per-lowering and line numbers per-program, so neither
        participates."""
        return (self.opcode, self.groups, self.nbytes)

    def involves(self, device: int) -> bool:
        return any(device in g for g in self.groups)

    def describe(self) -> str:
        gtxt = ",".join("[" + ",".join(str(d) for d in g) + "]"
                        for g in self.groups[:4])
        if len(self.groups) > 4:
            gtxt += ",..."
        ch = f", ch={self.channel_id}" if self.channel_id is not None else ""
        return (f"{self.opcode}({self.nbytes / _MB:.2f} MB, "
                f"groups={gtxt}{ch})")


def _event_pairs(attrs: str) -> Optional[Tuple[Tuple[int, int], ...]]:
    m = _DENSE_PAIRS_RE.search(attrs)
    if m:
        rows = _parse_dense_rows(m.group(1))
        if rows:
            return tuple((r[0], r[1]) for r in rows if len(r) >= 2)
    m = _SOURCE_TARGET_RE.search(attrs)
    if m:
        pairs = []
        for grp in re.findall(r"\{[^{}]*\}", m.group(1)):
            cells = [int(x) for x in grp.strip("{}").split(",") if x.strip()]
            if len(cells) >= 2:
                pairs.append((cells[0], cells[1]))
        return tuple(pairs) or None
    return None


def _event_groups(attrs: str,
                  pairs: Optional[Tuple[Tuple[int, int], ...]],
                  num_devices: int) -> Optional[List[List[int]]]:
    m = _DENSE_GROUPS_RE.search(attrs)
    if m:
        return _parse_dense_rows(m.group(1))
    if pairs:
        # Connected components of the permute graph, via the shared
        # HLO-text parser (it already union-finds source_target_pairs).
        fake = ("source_target_pairs={" +
                ",".join("{%d,%d}" % p for p in pairs) + "}")
        return _parse_replica_groups(fake, num_devices)
    return _parse_replica_groups(attrs, num_devices)


def _explicit_ids(attrs: str) -> Iterable[int]:
    """Every device id named literally in a collective's group/pair
    attributes — the first pass that sizes the device space before
    full-mesh ``replica_groups={}`` groups can be expanded."""
    for rx in (_DENSE_GROUPS_RE, _DENSE_PAIRS_RE):
        m = rx.search(attrs)
        if m:
            for row in _parse_dense_rows(m.group(1)) or []:
                for d in row:
                    yield d
    for rx in (_SOURCE_TARGET_RE,):
        m = rx.search(attrs)
        if m:
            for cell in re.findall(r"\d+", m.group(1)):
                yield int(cell)
    m = re.search(r"replica_groups=\{((?:\{[^{}]*\},?)+)\}", attrs)
    if m:
        for cell in re.findall(r"\d+", m.group(1)):
            yield int(cell)


def _canonical_opcode(opcode: str) -> Optional[str]:
    """Fold async halves onto the issue point; None for opcodes
    outside the schedule (incl. every ``*_done`` completion)."""
    if _ASYNC_DONE.match(opcode):
        return None
    m = _ASYNC_START.match(opcode)
    if m and m.group(1) in SCHED_OPCODES:
        return m.group(1)
    return opcode if opcode in SCHED_OPCODES else None


class ProgramSchedule:
    """The per-device collective schedule of one lowered program:
    events in printed (scheduled) order; a device's schedule is its
    involvement-filtered projection."""

    def __init__(self, prog: HloProgram):
        self.prog = prog
        self.path = prog.path
        ops = [(op, _canonical_opcode(op.opcode)) for op in prog.ops]
        ops = [(op, canon) for op, canon in ops if canon is not None]
        ndev = max(prog.num_partitions or 0, 1)
        for op, _ in ops:
            for d in _explicit_ids(op.attrs):
                ndev = max(ndev, d + 1)
        self.num_devices = ndev
        from horovod_tpu.analysis import hlo_rules
        events: List[CollectiveEvent] = []
        for op, canon in ops:
            pairs = (_event_pairs(op.attrs)
                     if canon in ("collective_permute", "send", "recv")
                     else None)
            groups = _event_groups(op.attrs, pairs, ndev)
            nb = hlo_rules._collective_payload(op) or 0
            gt = (tuple(tuple(sorted(g)) for g in groups)
                  if groups is not None else ())
            ch = None
            m = (_CHANNEL_MLIR_RE.search(op.attrs)
                 or _CHANNEL_HLO_RE.search(op.attrs))
            if m:
                ch = int(m.group(1))
            events.append(CollectiveEvent(
                line=op.line, opcode=canon, groups=gt, pairs=pairs,
                channel_id=ch, nbytes=int(nb), path=self.path))
        self.events = events

    @property
    def devices(self) -> List[int]:
        return sorted({d for e in self.events for g in e.groups for d in g})

    def device_events(self, device: int) -> List[CollectiveEvent]:
        return [e for e in self.events if e.involves(device)]


@dataclasses.dataclass
class ScheduleSet:
    """All programs linted together — the unit the cross-program rules
    (HVD401/HVD403) see. One SPMD program is internally consistent by
    construction; divergence needs two independently-authored programs
    (e.g. a hand-split MPMD pipeline, one module per stage group)."""

    schedules: List[ProgramSchedule]


def parse_schedule(text: str, path: str = "<hlo>") -> ProgramSchedule:
    return ProgramSchedule(parse(text, path))


# ------------------------------------------- analytic ICI/DCN cost model

#: Documented fallback link bandwidths, GB/s per direction per device.
#: ICI ~= one TPU v4/v5 inter-chip link pair's usable ring bandwidth;
#: DCN ~= a 100 Gb/s-class data-center NIC's usable share. Both are
#: deliberately round planning numbers (flops.py policy: a documented
#: fallback beats a silent zero), overridable per deployment via
#: HOROVOD_SCHED_LINK_GBPS="ici=90,dcn=12.5".
ICI_LINK_GBPS = 90.0
DCN_LINK_GBPS = 12.5

_LINK_ENV = "HOROVOD_SCHED_LINK_GBPS"
_LINK_ENTRY_RE = re.compile(r"(ici|dcn)\s*=\s*(\d+(?:\.\d+)?)")


class _LinkTableCache:
    """Process-wide cache of parsed HOROVOD_SCHED_LINK_GBPS tables,
    keyed by the raw env string (bench workers and concurrent lint
    threads share one parse per distinct value). Instrumented by
    hvdrace (race.DEFAULT_MODULES)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock

    def get(self, raw: str) -> Optional[Dict[str, float]]:
        with self._lock:
            hit = self._tables.get(raw)
            return dict(hit) if hit is not None else None

    def put(self, raw: str, table: Dict[str, float]) -> None:
        with self._lock:
            self._tables[raw] = dict(table)


_link_cache = _LinkTableCache()


def link_gbps() -> Dict[str, float]:
    """The two-tier link table ``{"ici": GB/s, "dcn": GB/s}``.

    Env grammar: comma-separated ``tier=GB/s`` entries, either tier
    optional (``HOROVOD_SCHED_LINK_GBPS="dcn=25"`` overrides only the
    DCN tier). Malformed input raises ValueError — the `_bytes_env`
    lesson: a mistyped knob must fail the lint loudly, never silently
    revert to defaults.
    """
    raw = os.environ.get(_LINK_ENV, "").strip()
    hit = _link_cache.get(raw)
    if hit is not None:
        return hit
    table = {"ici": ICI_LINK_GBPS, "dcn": DCN_LINK_GBPS}
    if raw:
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            m = _LINK_ENTRY_RE.fullmatch(part)
            if not m or float(m.group(2)) <= 0:
                raise ValueError(
                    f"{_LINK_ENV}={raw!r}: expected comma-separated "
                    f"tier=GB/s entries with tier in (ici, dcn) and a "
                    f"positive value, e.g. 'ici=90,dcn=12.5'; bad "
                    f"entry {part!r}")
            table[m.group(1)] = float(m.group(2))
    _link_cache.put(raw, table)
    return table


_SLICES_ENV = "HOROVOD_MESH_SLICES"


def declared_slices() -> Optional[int]:
    """The declared hierarchical-mesh slice count (None = flat mesh,
    HVD404 unarmed and everything rides the ICI tier). Malformed input
    raises ValueError (loud-knob policy)."""
    raw = os.environ.get(_SLICES_ENV, "").strip()
    if not raw:
        return None
    if not re.fullmatch(r"\d+", raw) or int(raw) < 1:
        raise ValueError(
            f"{_SLICES_ENV}={raw!r}: expected a positive integer "
            f"slice count (contiguous equal slices of the flat rank "
            f"space; parallel/mesh.slice_groups)")
    return int(raw)


def wire_factor(opcode: str, k: int) -> float:
    """Bytes-on-the-wire multiple of the payload for one collective
    over a k-member ring: all-reduce moves 2(k-1)/k (reduce-scatter +
    all-gather halves), gather/scatter/all-to-all move (k-1)/k, a
    permute / send / recv is one hop."""
    if k <= 1:
        return 0.0
    if opcode == "all_reduce":
        return 2.0 * (k - 1) / k
    if opcode in ("all_gather", "reduce_scatter", "all_to_all"):
        return (k - 1) / k
    return 1.0


def group_tier(group: Sequence[int], slices: Optional[int],
               num_devices: int) -> str:
    """"dcn" when the group crosses a declared slice boundary (the
    whole collective then moves at the slowest member link), else
    "ici". Slice of rank d = d // (num_devices // slices), matching
    parallel/mesh.slice_groups."""
    if not slices or slices <= 1 or num_devices % slices:
        return "ici"
    per = num_devices // slices
    return "dcn" if len({d // per for d in group}) > 1 else "ici"


@dataclasses.dataclass(frozen=True)
class EventCost:
    tier: str            # "ici" | "dcn"
    wire_bytes: int      # payload x wire_factor
    seconds: float


def event_cost(ev: CollectiveEvent, num_devices: int,
               slices: Optional[int] = None,
               table: Optional[Dict[str, float]] = None) -> EventCost:
    """Analytic time of one collective: ring time = wire bytes over
    the slowest tier any of its groups touches."""
    if table is None:
        table = link_gbps()
    k = max((len(g) for g in ev.groups), default=1)
    wire = int(ev.nbytes * wire_factor(ev.opcode, k))
    tier = "ici"
    for g in ev.groups:
        if len(g) > 1 and group_tier(g, slices, num_devices) == "dcn":
            tier = "dcn"
            break
    sec = wire / (table[tier] * 1e9) if wire else 0.0
    return EventCost(tier=tier, wire_bytes=wire, seconds=sec)


def comms_model(text: str, axis_sizes: Sequence[Tuple[str, int]],
                path: str = "<compiled>",
                slices: Optional[int] = None) -> Dict[str, object]:
    """The bench ``comms_model`` stamp: predicted per-axis wire bytes
    and time from the analytic model, off the SAME compiled text the
    measured ``comms_by_axis`` reads, classified by the SAME
    shard.group_axis_label helper — so predicted_vs_measured compares
    the wire-factor model against the payload accounting and nothing
    else (docs/perf.md).
    """
    sched = parse_schedule(text, path)
    if slices is None:
        slices = declared_slices()
    table = link_gbps()
    partitions = _axis_partitions(axis_sizes)
    ndev = 1
    for _, s in axis_sizes:
        ndev *= s
    per_axis: Dict[str, Dict[str, object]] = {}
    payload_total = 0
    wire_total = 0
    time_total = 0.0
    for ev in sched.events:
        groups = [list(g) for g in ev.groups] if ev.groups else None
        label = group_axis_label(groups, partitions)
        if label is None:
            continue  # degenerate single-device groups: no wire
        cost = event_cost(ev, ndev, slices, table)
        ent = per_axis.setdefault(label, {
            "bytes_per_step": 0, "wire_bytes_per_step": 0,
            "predicted_s": 0.0, "ops": 0, "tier": "ici"})
        ent["bytes_per_step"] += ev.nbytes
        ent["wire_bytes_per_step"] += cost.wire_bytes
        ent["predicted_s"] += cost.seconds
        ent["ops"] += 1
        if cost.tier == "dcn":
            ent["tier"] = "dcn"
        payload_total += ev.nbytes
        wire_total += cost.wire_bytes
        time_total += cost.seconds
    return {
        "link_gbps": table,
        "slices": slices,
        "per_axis": per_axis,
        "payload_bytes_per_step": payload_total,
        "predicted_bytes_per_step": wire_total,
        "predicted_total_s": time_total,
    }


# -------------------------------- the overlappable backward window

_WINDOW_ENV = "HOROVOD_SCHED_OVERLAP_WINDOW_MS"
_PEAK_ENV = "HOROVOD_SCHED_PEAK_TFLOPS"
_FRACTION_ENV = "HOROVOD_SCHED_OVERLAP_FRACTION"

#: Backward share of step compute — the window gradient collectives
#: can hide inside (fwd recompute excluded). The classic 2/3 of the
#: 3x-forward-FLOPs training step; documented fallback, env override.
DEFAULT_OVERLAP_FRACTION = 0.67


def _float_env(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected a number") from None
    if val <= 0:
        raise ValueError(f"{name}={raw!r}: expected a positive number")
    return val


_MLIR_CONTRACT_RE = re.compile(
    r"contracting_dims\s*=\s*\[([\d, ]*)\]\s*x\s*\[([\d, ]*)\]")
_HLO_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")


def dot_flops(prog: HloProgram) -> int:
    """Total dot/dot_general FLOPs of one program: 2 x output elems x
    contracted extent per dot (contracting dims parsed the same way
    hvdhlo's lane-padding rule does). Convolutions are not counted —
    the estimate is deliberately a floor."""
    total = 0
    for op in prog.ops:
        if op.opcode not in ("dot", "dot_general"):
            continue
        out = op.result_types[0] if op.result_types else None
        lhs = op.operand_types[0] if op.operand_types else None
        if out is None or lhs is None or not out.elems:
            continue
        m = (_MLIR_CONTRACT_RE.search(op.attrs)
             or _HLO_LHS_CONTRACT_RE.search(op.attrs))
        if not m:
            continue
        idxs = [int(x) for x in m.group(1).replace(" ", "").split(",") if x]
        extent = 1
        for i in idxs:
            if i < len(lhs.dims):
                extent *= lhs.dims[i]
        total += 2 * out.elems * max(extent, 1)
    return total


def overlap_window_s(prog: Optional[HloProgram] = None,
                     phases_s: Optional[Dict[str, float]] = None
                     ) -> Optional[float]:
    """The overlappable backward window predicted comms must hide in.

    Priority: an explicit ``HOROVOD_SCHED_OVERLAP_WINDOW_MS``; a
    perfscope-style phase split (``phases_s`` with a measured
    ``device_compute`` phase, times in seconds); else the analytic
    dot-FLOPs / ``HOROVOD_SCHED_PEAK_TFLOPS`` estimate — each scaled
    by ``HOROVOD_SCHED_OVERLAP_FRACTION``. None when nothing is
    configured: HVD405 stays silent, so the default CPU CI programs
    (no declared peak) lint clean.
    """
    ms = _float_env(_WINDOW_ENV)
    if ms is not None:
        return ms / 1e3
    frac = _float_env(_FRACTION_ENV)
    if frac is None:
        frac = DEFAULT_OVERLAP_FRACTION
    if phases_s:
        compute = phases_s.get("device_compute")
        if compute is None:
            compute = sum(v for v in phases_s.values()
                          if isinstance(v, (int, float)))
        return float(compute) * frac
    if prog is not None:
        tflops = _float_env(_PEAK_ENV)
        if tflops is not None:
            return dot_flops(prog) / (tflops * 1e12) * frac
    return None


def min_staged_bytes() -> int:
    """HVD404's payload floor (HOROVOD_SCHED_MIN_STAGED_BYTES,
    default 1 MiB): below it, flat cross-slice collectives are latency-
    dominated and staging buys nothing."""
    return _bytes_env("HOROVOD_SCHED_MIN_STAGED_BYTES", _MB)


# ----------------------------------------------------- lint entrypoints

def registry() -> Dict[str, Tuple[str, object]]:
    from horovod_tpu.analysis import sched_rules
    return dict(sched_rules.RULES)


def lint_schedules(scheds: Sequence[ProgramSchedule],
                   select: Optional[Sequence[str]] = None,
                   ignore: Sequence[str] = ()) -> List[Finding]:
    """Run the HVD4xx rules over one ScheduleSet — programs linted
    together so the cross-program rules see every pairing."""
    wanted = {r.upper() for r in select} if select is not None else None
    ignored = {r.upper() for r in ignore}
    sset = ScheduleSet(list(scheds))
    out: List[Finding] = []
    for rule_id, (_desc, check) in sorted(registry().items()):
        if wanted is not None and rule_id not in wanted:
            continue
        if rule_id in ignored:
            continue
        out.extend(check(sset))
    out.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return out


def lint_text(text: str, path: str = "<hlo>",
              select: Optional[Sequence[str]] = None,
              ignore: Sequence[str] = ()) -> List[Finding]:
    return lint_schedules([parse_schedule(text, path)],
                          select=select, ignore=ignore)


def lint_files(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Sequence[str] = ()) -> List[Finding]:
    """Parse ALL paths into one ScheduleSet before linting: the
    misordered-pair HVD401 acceptance only exists across files."""
    findings: List[Finding] = []
    scheds: List[ProgramSchedule] = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            findings.append(Finding(str(p), 1, "HVD999",
                                    f"unreadable: {e}"))
            continue
        scheds.append(parse_schedule(text, path=str(p)))
    findings.extend(lint_schedules(scheds, select=select, ignore=ignore))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def record_metrics(findings: Sequence[Finding]) -> None:
    """hvdsched_findings_total{rule}; pre-registers the counter even on
    a clean run so dashboards see the series, and swallows failures —
    analysis must work without the runtime deps."""
    try:
        from horovod_tpu.observability import metrics as m
        counter = m.registry().counter(
            "hvdsched_findings_total", "hvdsched findings by rule",
            labelnames=("rule",))
        for f in findings:
            counter.labels(rule=f.rule_id).inc()
    except Exception:
        pass
