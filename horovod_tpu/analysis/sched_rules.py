"""hvdsched rules HVD401-HVD405: cross-device collective-schedule
contracts + the exposed-comms roofline (docs/static_analysis.md).

The property every rule defends is the one the runtime fingerprint
verifier (analysis/verifier.py) can only check live: every member of
a replica group must reach the same collectives, with the same shape,
in the same order — or the group deadlocks with zero error anywhere.
hvdsched proves it from the lowered text before anything runs, the
PR-12 pattern of landing the static gate in front of the runtime
feature (here: pp/sp/ep and hierarchical ICI/DCN staging, ROADMAP
item 3).

HVD401  two devices sharing a replica group reach the same collective
        at different sequence positions, or reach different
        (op, groups, bytes) at the same position — the static
        deadlock. Cross-program only: one SPMD program is internally
        consistent by construction, so this fires on hand-split MPMD
        module pairs (one module per pipeline stage group).
HVD402  a collective-permute whose source_target_pairs are not a
        permutation (duplicate sender/receiver) or form an open chain
        instead of a union of disjoint cycles (orphan sender /
        receiver), and send/recv channels with no matching partner —
        the classic 1F1B mispairing that wedges the pipeline.
HVD403  overlapping subset collectives whose relative order differs
        between member devices: a happens-before cycle of length >= 3
        across device schedules (the 2-party case is HVD401's
        position mismatch).
HVD404  a >= HOROVOD_SCHED_MIN_STAGED_BYTES (1 MiB) all-reduce whose
        replica group crosses the declared slice boundary
        (HOROVOD_MESH_SLICES) as ONE flat collective while some slice
        holds >= 2 members — the whole payload rides the slow DCN
        tier when intra-slice reduce-scatter + inter-slice all-reduce
        staging would move 1/per_slice of it.
HVD405  predicted exposed comms: the analytic per-step comms time
        (analysis/schedule.event_cost) exceeds the overlappable
        backward window (HOROVOD_SCHED_OVERLAP_WINDOW_MS, or
        dot-FLOPs / HOROVOD_SCHED_PEAK_TFLOPS x overlap fraction).
        Silent when no window is configured, so default CPU CI
        programs lint clean.

Findings are baselined (``scripts/hvdsched_baseline.json``), not
suppressed inline — lowered text has no comments.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from horovod_tpu.analysis.driver import Finding
from horovod_tpu.analysis import schedule as S

HVD401 = "HVD401"
HVD402 = "HVD402"
HVD403 = "HVD403"
HVD404 = "HVD404"
HVD405 = "HVD405"

_MB = 1024 * 1024


# ------------------------------------------------------------- HVD401

def _shared_projection(ps: "S.ProgramSchedule", d1: int,
                       d2: int) -> List["S.CollectiveEvent"]:
    """d1's schedule restricted to collectives whose groups put d1 and
    d2 in the same group — the subsequence both must agree on."""
    out = []
    for e in ps.events:
        if any(d1 in g and d2 in g for g in e.groups):
            out.append(e)
    return out


def check_hvd401(sset: "S.ScheduleSet") -> Iterable[Finding]:
    scheds = sset.schedules
    seen: Set[Tuple] = set()
    for ia in range(len(scheds)):
        for ib in range(ia + 1, len(scheds)):
            A, B = scheds[ia], scheds[ib]
            for d1 in A.devices:
                for d2 in B.devices:
                    a = _shared_projection(A, d1, d2)
                    b = _shared_projection(B, d2, d1)
                    if not a and not b:
                        continue
                    n = min(len(a), len(b))
                    diverged = False
                    for pos in range(n):
                        ea, eb = a[pos], b[pos]
                        if ea.signature == eb.signature:
                            continue
                        diverged = True
                        key = (A.path, B.path, pos,
                               ea.signature, eb.signature)
                        if key in seen:
                            break
                        seen.add(key)
                        later = next(
                            (j for j in range(pos + 1, len(b))
                             if b[j].signature == ea.signature), None)
                        detail = (
                            f"; device {d2} reaches that same "
                            f"collective later, at position {later} — "
                            f"misordered schedules"
                            if later is not None else "")
                        yield Finding(
                            A.path, ea.line, HVD401,
                            f"device {d1} ({A.path}) and device {d2} "
                            f"({B.path}) share a replica group but "
                            f"diverge at shared-collective position "
                            f"{pos}: device {d1} issues "
                            f"{ea.describe()} while device {d2} "
                            f"issues {eb.describe()}{detail} — every "
                            f"group member must reach the same "
                            f"collective in the same order or the "
                            f"group deadlocks at step time")
                        break
                    if not diverged and len(a) != len(b):
                        key = (A.path, B.path, "len", len(a), len(b))
                        if key in seen:
                            continue
                        seen.add(key)
                        longer, dev, other = (
                            (a, d1, d2) if len(a) > len(b)
                            else (b, d2, d1))
                        ev = longer[n]
                        yield Finding(
                            ev.path, ev.line, HVD401,
                            f"device {dev} issues {len(longer)} "
                            f"collectives shared with device {other} "
                            f"but device {other} only issues {n}: "
                            f"{ev.describe()} at position {n} has no "
                            f"counterpart — the orphan collective "
                            f"blocks forever waiting for device "
                            f"{other}")


# ------------------------------------------------------------- HVD402

def check_hvd402(sset: "S.ScheduleSet") -> Iterable[Finding]:
    for ps in sset.schedules:
        sends: Dict[Optional[int], "S.CollectiveEvent"] = {}
        recvs: Dict[Optional[int], "S.CollectiveEvent"] = {}
        for ev in ps.events:
            if ev.opcode == "send":
                sends.setdefault(ev.channel_id, ev)
            elif ev.opcode == "recv":
                recvs.setdefault(ev.channel_id, ev)
            if ev.pairs is None:
                continue
            srcs = [s for s, _ in ev.pairs]
            tgts = [t for _, t in ev.pairs]
            dup_s = sorted({x for x in srcs if srcs.count(x) > 1})
            dup_t = sorted({x for x in tgts if tgts.count(x) > 1})
            if dup_s or dup_t:
                yield Finding(
                    ps.path, ev.line, HVD402,
                    f"{ev.opcode} source_target_pairs "
                    f"{[list(p) for p in ev.pairs]} are not a "
                    f"permutation: duplicate source(s) {dup_s} / "
                    f"target(s) {dup_t} — two transfers contend for "
                    f"one rank's slot and the permute deadlocks or "
                    f"clobbers")
                continue
            orphan_send = sorted(set(srcs) - set(tgts))
            orphan_recv = sorted(set(tgts) - set(srcs))
            if orphan_send or orphan_recv:
                yield Finding(
                    ps.path, ev.line, HVD402,
                    f"{ev.opcode} source_target_pairs "
                    f"{[list(p) for p in ev.pairs]} form an open "
                    f"chain, not a union of disjoint cycles: rank(s) "
                    f"{orphan_send} send but never receive and "
                    f"rank(s) {orphan_recv} receive but never send — "
                    f"the 1F1B mispairing that wedges the pipeline; "
                    f"close the ring ((i+1) % stages) or pair the "
                    f"forward shift with its reverse")
        for ch in sorted(set(sends) - set(recvs), key=str):
            ev = sends[ch]
            yield Finding(
                ps.path, ev.line, HVD402,
                f"send on channel {ch} has no matching recv in the "
                f"program — the orphan sender blocks forever")
        for ch in sorted(set(recvs) - set(sends), key=str):
            ev = recvs[ch]
            yield Finding(
                ps.path, ev.line, HVD402,
                f"recv on channel {ch} has no matching send in the "
                f"program — the orphan receiver blocks forever")


# ------------------------------------------------------------- HVD403

def check_hvd403(sset: "S.ScheduleSet") -> Iterable[Finding]:
    # Happens-before edges between collective signatures: for every
    # device's schedule, each event precedes every later one. A cycle
    # of length >= 3 means no global order satisfies every device —
    # the interleaving hazard on shared ranks of overlapping subset
    # collectives. (A 2-cycle is HVD401's pairwise position mismatch.)
    # A device only asserts "u before v" when the order is unambiguous
    # in its schedule (EVERY occurrence of u precedes every occurrence
    # of v) — repeated signatures interleaved within one device are a
    # normal pipeline shape, not an ordering claim.
    edges: Dict[Tuple, Set[Tuple]] = {}
    witness: Dict[Tuple[Tuple, Tuple],
                  Tuple[str, int, "S.CollectiveEvent"]] = {}
    for ps in sset.schedules:
        for d in ps.devices:
            seq = ps.device_events(d)
            first: Dict[Tuple, int] = {}
            last: Dict[Tuple, int] = {}
            for i, ev in enumerate(seq):
                first.setdefault(ev.signature, i)
                last[ev.signature] = i
            for u in first:
                for v in first:
                    if u == v or last[u] >= first[v]:
                        continue
                    edges.setdefault(u, set()).add(v)
                    witness.setdefault(
                        (u, v), (ps.path, d, seq[first[u]]))

    color: Dict[Tuple, int] = {}  # 0 absent / 1 on stack / 2 done
    stack: List[Tuple] = []
    cycles: List[List[Tuple]] = []

    def visit(u: Tuple) -> None:
        color[u] = 1
        stack.append(u)
        for v in sorted(edges.get(u, ()), key=repr):
            c = color.get(v, 0)
            if c == 0:
                visit(v)
            elif c == 1:
                cyc = stack[stack.index(v):] + [v]
                if len(cyc) - 1 >= 3:
                    cycles.append(cyc)
        stack.pop()
        color[u] = 2

    for u in sorted(edges, key=repr):
        if color.get(u, 0) == 0:
            visit(u)

    reported: Set[frozenset] = set()
    for cyc in cycles:
        nodes = frozenset(cyc[:-1])
        if nodes in reported:
            continue
        reported.add(nodes)
        legs = []
        first = None
        for u, v in zip(cyc, cyc[1:]):
            path, d, ev = witness[(u, v)]
            if first is None:
                first = (path, ev.line)
            legs.append(f"device {d} ({path}) orders "
                        f"{u[0]} before {v[0]}")
        yield Finding(
            first[0], first[1], HVD403,
            f"overlapping subset collectives with no consistent "
            f"global order — {len(cyc) - 1}-cycle in the cross-device "
            f"happens-before graph: " + "; ".join(legs) +
            f" — shared ranks can interleave the groups and deadlock; "
            f"give the overlapping collectives one device-independent "
            f"issue order")


# ------------------------------------------------------------- HVD404

def check_hvd404(sset: "S.ScheduleSet") -> Iterable[Finding]:
    slices = S.declared_slices()
    if not slices or slices <= 1:
        return
    floor = S.min_staged_bytes()
    for ps in sset.schedules:
        ndev = ps.num_devices
        if ndev % slices:
            continue
        per = ndev // slices
        for ev in ps.events:
            if ev.opcode != "all_reduce" or ev.nbytes < floor:
                continue
            for g in ev.groups:
                spanned = {d // per for d in g}
                if len(spanned) > 1 and len(g) > len(spanned):
                    yield Finding(
                        ps.path, ev.line, HVD404,
                        f"{ev.nbytes / _MB:.1f} MB all-reduce over "
                        f"replica group {list(g)} crosses the "
                        f"declared slice boundary "
                        f"(HOROVOD_MESH_SLICES={slices}, {per} "
                        f"devices/slice) as one flat collective: the "
                        f"whole payload rides the slow inter-slice "
                        f"DCN tier; stage it as intra-slice "
                        f"reduce-scatter + inter-slice all-reduce "
                        f"(+ intra-slice all-gather) so only "
                        f"payload/{per} crosses the boundary")
                    break


# ------------------------------------------------------------- HVD405

def check_hvd405(sset: "S.ScheduleSet") -> Iterable[Finding]:
    slices = S.declared_slices()
    table = S.link_gbps()
    for ps in sset.schedules:
        if not ps.events:
            continue
        window = S.overlap_window_s(ps.prog)
        if window is None:
            continue  # no window configured: rule unarmed
        costs = [(ev, S.event_cost(ev, ps.num_devices, slices, table))
                 for ev in ps.events]
        total = sum(c.seconds for _, c in costs)
        if total <= window:
            continue
        top_ev, top_c = max(costs, key=lambda p: p[1].seconds)
        yield Finding(
            ps.path, top_ev.line, HVD405,
            f"predicted per-step comms {total * 1e3:.2f} ms exceeds "
            f"the overlappable backward window {window * 1e3:.2f} ms "
            f"({(total - window) * 1e3:.2f} ms exposed): the step is "
            f"predicted comms-bound; largest contributor "
            f"{top_ev.describe()} at {top_c.seconds * 1e3:.2f} ms on "
            f"the {top_c.tier} tier "
            f"({table[top_c.tier]:g} GB/s) — shard the payload, "
            f"stage it across the slice boundary, or raise the "
            f"declared window if measured overlap disagrees")


RULES = {
    HVD401: ("replica-group members reach different collectives or "
             "positions (static deadlock)", check_hvd401),
    HVD402: ("permute pairs not a union of disjoint cycles / orphan "
             "send-recv (1F1B hazard)", check_hvd402),
    HVD403: ("overlapping subset collectives ordered differently "
             "across member devices", check_hvd403),
    HVD404: ("flat >=1MiB all-reduce across the declared slice "
             "boundary where staging is available", check_hvd404),
    HVD405: ("predicted comms exceed the overlappable backward "
             "window (exposed comms)", check_hvd405),
}
