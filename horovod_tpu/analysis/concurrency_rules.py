"""Concurrency-discipline lint rules (HVD101-HVD103).

This runtime spawns ~20 background threads (exporter, watchdog, elastic
driver, rendezvous server, data service, timeline writer) and PR 2
already fixed one cross-thread race (timeline ``_pending_spans``) by
hand. These rules make the locking discipline *checkable*:

HVD101  ``# guarded-by: <lock>`` convention. Annotate the assignment
        that creates shared state::

            self._pending_spans = {}  # guarded-by: _lock

        and every later access of ``._pending_spans`` in the module must
        sit lexically inside ``with <something>.<lock>:``. Accesses in
        the creating scope (``__init__`` / the class body / module top
        level) are exempt — the object is not shared yet.
HVD102  ``threading.Thread(...)`` without an explicit ``daemon=``: an
        undecided thread lifetime is how launchers hang at exit. Decide
        (``daemon=True``, or ``daemon=False`` plus a join path) and say
        so at the spawn site.
HVD103  blocking call (``time.sleep``, socket/HTTP ops, ``Event.wait``,
        ``serve_forever``, ``block_until_ready``, ``subprocess.run``,
        ``Popen.wait``, timeout-less ``queue.Queue.get``/``put``) while
        lexically holding a lock: every other thread needing that lock
        now waits on the network/timer too — the shape of the PR 1
        stall bugs.

Lexical scope is the contract: lock handoffs through helper calls are
invisible to these rules and should either be refactored or suppressed
with a rationale.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from horovod_tpu.analysis.driver import Finding, SourceFile

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: Terminal callee names considered blocking for HVD103. `join` and
#: `get` are deliberately absent (str.join / dict.get false positives;
#: `wait` covers `Popen.wait` along with Event/Condition waits).
BLOCKING_NAMES: Set[str] = {
    "sleep", "urlopen", "wait", "accept", "recv", "recvfrom", "recv_into",
    "sendall", "connect", "create_connection", "getaddrinfo", "select",
    "serve_forever", "block_until_ready", "check_output", "check_call",
    "communicate",
}

#: (receiver root, terminal) pairs blocking only under that exact
#: qualification — names too common to flag on any receiver.
BLOCKING_QUALIFIED: Set[Tuple[str, str]] = {
    ("subprocess", "run"), ("subprocess", "call"),
}

#: Queue-ish receiver names whose `.get(...)`/`.put(...)` block
#: indefinitely unless a timeout is given. Matching is by receiver name
#: (``self._queue.get()``, ``q.put(item)``) — dict/KV ``.get`` stays
#: exempt because plain data receivers aren't named like queues.
_QUEUEISH = ("queue", "_q", "q")


def _queueish(name: str) -> bool:
    return "queue" in name.lower() or name.lower() in _QUEUEISH


def _terminal(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _with_lock_names(node: ast.With) -> Set[str]:
    """Terminal names of every context-manager expression in `node`
    (``with self._lock:`` -> {"_lock"}; ``with a.b.lock:`` -> {"lock"})."""
    names: Set[str] = set()
    for item in node.items:
        expr = item.context_expr
        # `with lock.acquire_timeout(..)`-style helpers: use the
        # receiver's name too.
        if isinstance(expr, ast.Call):
            t = _terminal(expr.func)
            if t is not None:
                names.add(t)
            if isinstance(expr.func, ast.Attribute):
                r = _terminal(expr.func.value)
                if r is not None:
                    names.add(r)
        else:
            t = _terminal(expr)
            if t is not None:
                names.add(t)
    return names


def _lockish(name: str) -> bool:
    return "lock" in name.lower()


# --------------------------------------------------------------- HVD101

class _Annotation:
    __slots__ = ("attr", "lock", "line", "owner", "cls")

    def __init__(self, attr: str, lock: str, line: int,
                 owner: Optional[ast.AST],
                 cls: Optional[str] = None) -> None:
        self.attr = attr
        self.lock = lock
        self.line = line
        self.owner = owner  # the function/class scope that may touch it
        #                     unguarded (creation scope)
        self.cls = cls  # enclosing class name — binds the annotation to
        #                 a runtime class for hvdrace (analysis/race.py)

    @property
    def class_level(self) -> bool:
        """True when the annotated state lives on the class itself
        (assignment in the class body), not per-instance."""
        return isinstance(self.owner, ast.ClassDef)


def _assigned_names(stmt: ast.stmt) -> List[Tuple[str, bool]]:
    """(name, is_attribute) for each target assigned by `stmt`."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    out: List[Tuple[str, bool]] = []
    for t in targets:
        if isinstance(t, ast.Attribute):
            out.append((t.attr, True))
        elif isinstance(t, ast.Name):
            out.append((t.id, False))
    return out


def _collect_annotations(sf: SourceFile) -> List[_Annotation]:
    """Find ``# guarded-by:`` comments and bind each to the state it
    annotates (the assignment on that physical line)."""
    lock_by_line: Dict[int, str] = {}
    for lineno, line in enumerate(sf.lines, 1):
        m = GUARDED_BY_RE.search(line)
        if m:
            lock_by_line[lineno] = m.group(1)
    if not lock_by_line:
        return []
    anns: List[_Annotation] = []
    bound: Set[int] = set()

    def visit(node: ast.AST, scope: Optional[ast.AST],
              cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            child_scope, child_cls = scope, cls
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child
            elif isinstance(child, ast.ClassDef):
                child_scope = child
                child_cls = child.name
            if isinstance(child, (ast.Assign, ast.AnnAssign,
                                  ast.AugAssign)):
                # The annotation comment may sit on any line the
                # statement spans (long dict literals).
                for ln in range(child.lineno,
                               (child.end_lineno or child.lineno) + 1):
                    if ln in lock_by_line and ln not in bound:
                        for name, _is_attr in _assigned_names(child):
                            anns.append(_Annotation(
                                name, lock_by_line[ln], ln, scope, cls))
                            bound.add(ln)
            visit(child, child_scope, child_cls)

    visit(sf.tree, None, None)
    return anns


def check_guarded_by(sf: SourceFile) -> Iterator[Finding]:
    anns = _collect_annotations(sf)
    if not anns:
        return
    by_attr: Dict[str, _Annotation] = {a.attr: a for a in anns}

    # Creation scopes where unguarded access is allowed: the annotated
    # assignment's own function (typically __init__) or class body.
    def walk(node: ast.AST, scope: Optional[ast.AST],
             held: Set[str]) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            child_held = held
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child
                child_held = set()  # locks don't span call boundaries
            elif isinstance(child, ast.ClassDef):
                child_scope = child
            if isinstance(child, ast.With):
                inner = held | _with_lock_names(child)
                # The with-items themselves evaluate pre-acquisition of
                # the later items, but flagging `with self._lock:` for
                # touching `_lock` would be absurd; item exprs are
                # exempt via `held|names` covering them too.
                for stmt in child.body:
                    yield from walk_stmt(stmt, child_scope, inner)
                continue
            yield from check_node(child, child_scope, child_held)
            yield from walk(child, child_scope, child_held)

    def walk_stmt(stmt: ast.AST, scope, held) -> Iterator[Finding]:
        yield from check_node(stmt, scope, held)
        yield from walk(stmt, scope, held)

    def check_node(node: ast.AST, scope, held: Set[str]
                   ) -> Iterator[Finding]:
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name is None:
            return
        ann = by_attr.get(name)
        if ann is None or ann.lock in held:
            return
        if scope is ann.owner:  # creation scope (None = module top level)
            return
        yield sf.finding(
            node, "HVD101",
            f"'{name}' is guarded-by '{ann.lock}' (annotation at line "
            f"{ann.line}) but accessed outside 'with ...{ann.lock}:'")

    yield from walk(sf.tree, None, set())


# --------------------------------------------------------------- HVD102

def check_thread_daemon(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        t = _terminal(node.func)
        if t != "Thread":
            continue
        if isinstance(node.func, ast.Attribute):
            root = node.func.value
            if not (isinstance(root, ast.Name)
                    and root.id == "threading"):
                continue
        if not any(kw.arg == "daemon" for kw in node.keywords):
            yield sf.finding(
                node, "HVD102",
                "threading.Thread without an explicit daemon=: decide "
                "the thread's lifetime at the spawn site (daemon=True, "
                "or daemon=False with a join path)")


# --------------------------------------------------------------- HVD103

def _has_timeout(call: ast.Call) -> bool:
    """True when a queue get/put is bounded: a ``timeout=`` keyword, the
    positional timeout slot (``get(block, timeout)`` /
    ``put(item, block, timeout)``), or a non-blocking ``block=False``
    (raises Empty/Full immediately — it cannot wait at all)."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if any(kw.arg == "block"
           and isinstance(kw.value, ast.Constant)
           and kw.value.value is False for kw in call.keywords):
        return True
    block_pos = 0 if _terminal(call.func) == "get" else 1
    if len(call.args) > block_pos \
            and isinstance(call.args[block_pos], ast.Constant) \
            and call.args[block_pos].value is False:
        return True
    pos = 1 if _terminal(call.func) == "get" else 2
    return len(call.args) > pos


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why `call` can block indefinitely, or None."""
    t = _terminal(call.func)
    if t in BLOCKING_NAMES:
        return f"'{t}(...)'"
    if isinstance(call.func, ast.Attribute):
        root = _terminal(call.func.value)
        if root is not None and (root, t) in BLOCKING_QUALIFIED:
            return f"'{root}.{t}(...)'"
        if t in ("get", "put") and root is not None \
                and _queueish(root) and not _has_timeout(call):
            return f"queue '{root}.{t}(...)' without a timeout"
    return None


def check_blocking_under_lock(sf: SourceFile) -> Iterator[Finding]:
    def walk(node: ast.AST, held: Set[str]) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_held = held
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_held = set()
            elif isinstance(child, ast.With):
                lock_names = {n for n in _with_lock_names(child)
                              if _lockish(n)}
                if lock_names:
                    child_held = held | lock_names
            if isinstance(child, ast.Call) and held:
                reason = _blocking_reason(child)
                if reason is not None:
                    yield sf.finding(
                        child, "HVD103",
                        f"blocking call {reason} while holding lock "
                        f"{sorted(held)}: every thread needing the lock "
                        f"now waits on the timer/network too — move the "
                        f"blocking work outside the critical section")
            yield from walk(child, child_held)

    yield from walk(sf.tree, set())


RULES = {
    "HVD101": ("guarded-by state accessed outside its lock",
               check_guarded_by),
    "HVD102": ("threading.Thread without explicit daemon=",
               check_thread_daemon),
    "HVD103": ("blocking call while holding a lock",
               check_blocking_under_lock),
}
