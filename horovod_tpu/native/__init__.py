"""ctypes bindings for the native control-plane library.

Reference analog: horovod/common/basics.py — a ctypes wrapper over the C++
runtime. Here the native pieces are the control plane only (KV/coordination
server, timeline writer, stall inspector); the data plane is XLA. The
library is built lazily with `make` on first use and every entry point has
a pure-Python fallback, so the framework works even without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhorovod_tpu_native.so")
_lib: Optional[ctypes.CDLL] = None
_lock = threading.Lock()
_build_failed = False

# KV protocol ops (must match kv_store.cc).
OP_PUT, OP_GET, OP_ADD, OP_AND, OP_OR, OP_GETC, OP_DEL, OP_PING = range(1, 9)


def _build() -> bool:
    try:
        subprocess.run(["make", "-s"], cwd=_DIR, check=True,
                       capture_output=True, timeout=300)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        # Always invoke make (it no-ops when the .so is newer than the
        # sources) so edits to src/*.cc are never silently ignored by a
        # stale binary; fall back to a pre-existing .so if the toolchain is
        # missing.
        if not _build() and not os.path.exists(_LIB_PATH):
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.hvdn_kv_server_start.restype = ctypes.c_void_p
        lib.hvdn_kv_server_start.argtypes = [ctypes.c_int]
        lib.hvdn_kv_server_port.restype = ctypes.c_int
        lib.hvdn_kv_server_port.argtypes = [ctypes.c_void_p]
        lib.hvdn_kv_server_stop.argtypes = [ctypes.c_void_p]
        lib.hvdn_kv_client_new.restype = ctypes.c_void_p
        lib.hvdn_kv_client_new.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.hvdn_kv_client_free.argtypes = [ctypes.c_void_p]
        lib.hvdn_kv_request.restype = ctypes.c_longlong
        lib.hvdn_kv_request.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_ulonglong,
            ctypes.c_char_p, ctypes.c_ulonglong]
        lib.hvdn_timeline_open.restype = ctypes.c_void_p
        lib.hvdn_timeline_open.argtypes = [ctypes.c_char_p]
        lib.hvdn_timeline_emit.restype = ctypes.c_int
        lib.hvdn_timeline_emit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_int]
        lib.hvdn_timeline_close.argtypes = [ctypes.c_void_p]
        try:  # stale prebuilt .so without counter-track support
            lib.hvdn_timeline_emit_counter.restype = ctypes.c_int
            lib.hvdn_timeline_emit_counter.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_double, ctypes.c_longlong]
        except AttributeError:
            pass
        lib.hvdn_stall_new.restype = ctypes.c_void_p
        lib.hvdn_stall_new.argtypes = [ctypes.c_double, ctypes.c_double]
        lib.hvdn_stall_free.argtypes = [ctypes.c_void_p]
        lib.hvdn_stall_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hvdn_stall_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hvdn_stall_check.restype = ctypes.c_longlong
        lib.hvdn_stall_check.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int)]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


class NativeKVServer:
    """TCP KV/coordination server (reference analog: the launcher's HTTP KV
    store served natively — gloo/http_store.cc counterpart)."""

    def __init__(self, port: int = 0):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.hvdn_kv_server_start(port)
        if not self._h:
            raise RuntimeError(f"failed to start native KV server on {port}")
        self.port = lib.hvdn_kv_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.hvdn_kv_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NativeKVClient:
    def __init__(self, host: str, port: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.hvdn_kv_client_new(host.encode(), port)
        if not self._h:
            raise RuntimeError(f"failed to connect to {host}:{port}")

    def _req(self, op: int, key: str, val: bytes = b"",
             outcap: int = 0) -> tuple:
        out = ctypes.create_string_buffer(outcap) if outcap else None
        st = self._lib.hvdn_kv_request(
            self._h, op, key.encode(), val, len(val), out, outcap)
        return st, (out.raw[:st] if (out is not None and st > 0) else b"")

    def put(self, key: str, val: bytes) -> None:
        self._req(OP_PUT, key, val)

    def get(self, key: str, maxlen: int = 1 << 20) -> Optional[bytes]:
        st, data = self._req(OP_GET, key, b"", maxlen)
        if st > maxlen:  # value larger than our buffer: re-fetch full size
            st, data = self._req(OP_GET, key, b"", int(st))
        return data if st >= 0 else None

    def delete(self, key: str) -> None:
        self._req(OP_DEL, key)

    def add(self, key: str, delta: int) -> int:
        st, _ = self._req(OP_ADD, key,
                          int(delta).to_bytes(8, "little", signed=True))
        return int(st)

    def bitwise(self, key: str, bits: bytes, op: str = "and") -> int:
        """Contribute to a cross-rank AND/OR (reference:
        controller.cc CrossRankBitwiseAnd/Or). Returns contributor count."""
        st, _ = self._req(OP_AND if op == "and" else OP_OR, key, bits)
        return int(st)

    def get_when(self, key: str, expected: int, timeout: float = 60.0,
                 maxlen: int = 1 << 20) -> Optional[bytes]:
        """Fetch a combined value once `expected` ranks contributed."""
        import time
        deadline = time.monotonic() + timeout
        payload = int(expected).to_bytes(8, "little", signed=True)
        # Escalating backoff: the common case (consistency agreement on
        # every eager collective) completes within a few hundred µs of
        # the last rank's contribution — a flat 5 ms sleep would tax
        # EVERY collective by one interval. Spin fine first, then yield.
        delay = 0.0002
        while time.monotonic() < deadline:
            out = ctypes.create_string_buffer(maxlen)
            st = self._lib.hvdn_kv_request(
                self._h, OP_GETC, key.encode(), payload, 8, out, maxlen)
            if st > maxlen:  # buffer too small: re-fetch at full size
                out = ctypes.create_string_buffer(int(st))
                st = self._lib.hvdn_kv_request(
                    self._h, OP_GETC, key.encode(), payload, 8, out, int(st))
            if st >= 0:
                return out.raw[:st]
            time.sleep(delay)
            delay = min(delay * 2, 0.005)
        return None

    def barrier(self, name: str, size: int, timeout: float = 60.0) -> bool:
        """KV-counter barrier (reference: EnqueueBarrier's negotiation role
        for host-side phases)."""
        self.add(f"__barrier__/{name}", 1)
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st, data = self._req(OP_GET, f"__barrier__/{name}", b"", 8)
            if st == 8 and int.from_bytes(data, "little",
                                          signed=True) >= size:
                return True
            time.sleep(0.002)
        return False

    def ping(self) -> bool:
        st, _ = self._req(OP_PING, "")
        return st == 42

    def close(self) -> None:
        if self._h:
            self._lib.hvdn_kv_client_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeTimeline:
    """Writer-thread Chrome-trace sink (reference: TimelineWriter,
    common/timeline.cc)."""

    def __init__(self, path: str):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.hvdn_timeline_open(path.encode())
        if not self._h:
            raise RuntimeError(f"cannot open timeline at {path}")

    def emit(self, name: str, cat: str, phase: str, ts_us: int,
             dur_us: int = 0, pid: int = 0, tid: int = 0) -> None:
        self._lib.hvdn_timeline_emit(
            self._h, name.encode(), cat.encode(), phase.encode(),
            ts_us, dur_us, pid, tid)

    def emit_counter(self, name: str, series: str, value: float,
                     ts_us: int) -> None:
        """Chrome `"ph":"C"` counter sample (timeline counter tracks)."""
        fn = getattr(self._lib, "hvdn_timeline_emit_counter", None)
        if fn is not None:
            fn(self._h, name.encode(), series.encode(), float(value),
               ts_us)

    def close(self) -> None:
        if self._h:
            self._lib.hvdn_timeline_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeStallInspector:
    """Reference: StallInspector (common/stall_inspector.cc)."""

    def __init__(self, warn_sec: float = 60.0, shutdown_sec: float = 0.0):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.hvdn_stall_new(warn_sec, shutdown_sec)

    def submit(self, name: str) -> None:
        self._lib.hvdn_stall_submit(self._h, name.encode())

    def done(self, name: str) -> None:
        self._lib.hvdn_stall_done(self._h, name.encode())

    def check(self) -> tuple:
        """Returns (stalled_names: list[str], shutdown: bool)."""
        buf = ctypes.create_string_buffer(1 << 16)
        flag = ctypes.c_int(0)
        n = self._lib.hvdn_stall_check(self._h, buf, len(buf),
                                       ctypes.byref(flag))
        names = buf.value.decode().split("\n") if n > 0 else []
        return [x for x in names if x], bool(flag.value)

    def free(self) -> None:
        if self._h:
            self._lib.hvdn_stall_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass
