// Stall inspector: detects collectives some ranks entered and others
// didn't.
//
// Native redesign of the reference StallInspector
// (horovod/common/stall_inspector.cc — coordinator warns at 60 s,
// stall_inspector.h:78, optional shutdown window). Here the bookkeeping is
// host-side: report_submit() when a named collective is entered,
// report_done() when it completes; check() returns the names outstanding
// longer than the warning threshold.

#include <chrono>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace hvdn {

class StallInspector {
 public:
  StallInspector(double warn_sec, double shutdown_sec)
      : warn_sec_(warn_sec), shutdown_sec_(shutdown_sec) {}

  void Submit(const std::string& name) {
    std::lock_guard<std::mutex> g(mu_);
    pending_.emplace(name, Now());
  }

  void Done(const std::string& name) {
    std::lock_guard<std::mutex> g(mu_);
    pending_.erase(name);
  }

  // Returns stalled names joined by '\n'; sets *shutdown if any exceeded
  // the shutdown window.
  std::string Check(int* shutdown) {
    std::lock_guard<std::mutex> g(mu_);
    double now = Now();
    std::string out;
    *shutdown = 0;
    for (const auto& [name, t0] : pending_) {
      double age = now - t0;
      if (age >= warn_sec_) {
        if (!out.empty()) out += '\n';
        out += name;
      }
      if (shutdown_sec_ > 0 && age >= shutdown_sec_) *shutdown = 1;
    }
    return out;
  }

 private:
  static double Now() {
    return std::chrono::duration<double>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  double warn_sec_, shutdown_sec_;
  std::mutex mu_;
  std::map<std::string, double> pending_;
};

}  // namespace hvdn

extern "C" {

void* hvdn_stall_new(double warn_sec, double shutdown_sec) {
  return new hvdn::StallInspector(warn_sec, shutdown_sec);
}

void hvdn_stall_free(void* h) { delete static_cast<hvdn::StallInspector*>(h); }

void hvdn_stall_submit(void* h, const char* name) {
  static_cast<hvdn::StallInspector*>(h)->Submit(name);
}

void hvdn_stall_done(void* h, const char* name) {
  static_cast<hvdn::StallInspector*>(h)->Done(name);
}

// Writes '\n'-joined stalled names into buf; returns byte count (may be 0).
long long hvdn_stall_check(void* h, char* buf, long long cap, int* shutdown) {
  std::string s = static_cast<hvdn::StallInspector*>(h)->Check(shutdown);
  long long n = static_cast<long long>(s.size());
  if (buf != nullptr && cap > 0) {
    long long c = n < cap - 1 ? n : cap - 1;
    std::memcpy(buf, s.data(), static_cast<size_t>(c));
    buf[c] = '\0';
  }
  return n;
}

}  // extern "C"
