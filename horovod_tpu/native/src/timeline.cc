// Chrome-trace timeline writer with a dedicated writer thread.
//
// Native redesign of the reference Timeline (horovod/common/timeline.cc:
// TimelineWriter + boost lockfree SPSC queue + writer thread; activity
// span model documented at common.h:83-116). Events are enqueued from the
// hot path into a bounded MPSC ring; a writer thread drains to
// about:tracing JSON. Dropped-on-overflow, never blocking the caller.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace hvdn {

struct Event {
  char name[64];
  char cat[24];  // for phase 'C': the counter series name (args key)
  char phase;  // 'B' begin, 'E' end, 'X' complete, 'i' instant, 'M' meta,
               // 'C' counter
  int64_t ts_us;
  int64_t dur_us;
  int32_t pid;
  int32_t tid;
  double value;  // phase 'C' only
};

class Timeline {
 public:
  Timeline(const char* path, size_t capacity = 1 << 16)
      : capacity_(capacity), ring_(capacity) {
    f_ = std::fopen(path, "w");
    if (f_ == nullptr) return;
    std::fputs("[\n", f_);
    writer_ = std::thread([this] { WriterLoop(); });
  }

  ~Timeline() { Close(); }

  bool ok() const { return f_ != nullptr; }

  bool Emit(const Event& e) {
    std::unique_lock<std::mutex> g(mu_);
    size_t next = (head_ + 1) % capacity_;
    if (next == tail_) return false;  // full: drop (never block hot path)
    ring_[head_] = e;
    head_ = next;
    g.unlock();
    cv_.notify_one();
    return true;
  }

  void Close() {
    bool expected = false;
    if (!closing_.compare_exchange_strong(expected, true)) return;
    cv_.notify_all();
    if (writer_.joinable()) writer_.join();
    if (f_ != nullptr) {
      std::fputs("]\n", f_);
      std::fclose(f_);
      f_ = nullptr;
    }
  }

 private:
  void WriterLoop() {
    bool first = true;
    while (true) {
      std::unique_lock<std::mutex> g(mu_);
      cv_.wait_for(g, std::chrono::milliseconds(100),
                   [this] { return head_ != tail_ || closing_.load(); });
      bool drained = false;
      while (tail_ != head_) {
        Event e = ring_[tail_];
        tail_ = (tail_ + 1) % capacity_;
        g.unlock();
        WriteEvent(e, first);
        first = false;
        drained = true;
        g.lock();
      }
      if (drained) {
        // Durability: push the batch into the OS page cache so a
        // SIGKILL'd run still leaves a loadable (truncated-array) trace.
        std::fflush(f_);
      }
      if (closing_.load() && head_ == tail_) break;
    }
  }

  static void JsonEscape(const char* in, char* out, size_t outcap) {
    size_t j = 0;
    for (size_t i = 0; in[i] != '\0' && j + 2 < outcap; ++i) {
      char c = in[i];
      if (c == '"' || c == '\\') out[j++] = '\\';
      if (static_cast<unsigned char>(c) < 0x20) c = ' ';
      out[j++] = c;
    }
    out[j] = '\0';
  }

  void WriteEvent(const Event& e, bool first) {
    char name[140], cat[56];
    JsonEscape(e.name, name, sizeof(name));
    JsonEscape(e.cat, cat, sizeof(cat));
    if (!first) std::fputs(",\n", f_);
    if (e.phase == 'C') {
      std::fprintf(f_,
                   "{\"name\":\"%s\",\"ph\":\"C\",\"ts\":%lld,"
                   "\"pid\":%d,\"args\":{\"%s\":%.17g}}",
                   name, static_cast<long long>(e.ts_us), e.pid, cat,
                   e.value);
    } else if (e.phase == 'X') {
      std::fprintf(f_,
                   "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"X\","
                   "\"ts\":%lld,\"dur\":%lld,\"pid\":%d,\"tid\":%d}",
                   name, cat, static_cast<long long>(e.ts_us),
                   static_cast<long long>(e.dur_us), e.pid, e.tid);
    } else {
      std::fprintf(f_,
                   "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"%c\","
                   "\"ts\":%lld,\"pid\":%d,\"tid\":%d}",
                   name, cat, e.phase, static_cast<long long>(e.ts_us),
                   e.pid, e.tid);
    }
  }

  size_t capacity_;
  std::vector<Event> ring_;
  size_t head_ = 0, tail_ = 0;
  std::mutex mu_;
  std::condition_variable cv_;
  std::atomic<bool> closing_{false};
  std::FILE* f_ = nullptr;
  std::thread writer_;
};

}  // namespace hvdn

extern "C" {

void* hvdn_timeline_open(const char* path) {
  auto* t = new hvdn::Timeline(path);
  if (!t->ok()) {
    delete t;
    return nullptr;
  }
  return t;
}

int hvdn_timeline_emit(void* h, const char* name, const char* cat, char phase,
                       long long ts_us, long long dur_us, int pid, int tid) {
  hvdn::Event e{};
  std::snprintf(e.name, sizeof(e.name), "%s", name);
  std::snprintf(e.cat, sizeof(e.cat), "%s", cat);
  e.phase = phase;
  e.ts_us = ts_us;
  e.dur_us = dur_us;
  e.pid = pid;
  e.tid = tid;
  return static_cast<hvdn::Timeline*>(h)->Emit(e) ? 0 : -1;
}

int hvdn_timeline_emit_counter(void* h, const char* name, const char* series,
                               double value, long long ts_us) {
  hvdn::Event e{};
  std::snprintf(e.name, sizeof(e.name), "%s", name);
  std::snprintf(e.cat, sizeof(e.cat), "%s", series);
  e.phase = 'C';
  e.ts_us = ts_us;
  e.value = value;
  return static_cast<hvdn::Timeline*>(h)->Emit(e) ? 0 : -1;
}

void hvdn_timeline_close(void* h) {
  auto* t = static_cast<hvdn::Timeline*>(h);
  t->Close();
  delete t;
}

}  // extern "C"
