// TCP key-value / coordination store.
//
// Native control plane for rendezvous, barriers, and bitvector cache
// coordination. Plays the role the reference's C++ control plane plays:
// the Gloo HTTP KV store (reference: horovod/common/gloo/http_store.cc,
// gloo_context rendezvous) and the controller's cross-rank bitwise
// AND/OR cache sync (reference: horovod/common/controller.cc:159-190
// CoordinateCacheAndState + CrossRankBitwiseAnd/Or).
//
// Wire protocol (binary, length-prefixed):
//   request : u8 op | u32 klen | key | u64 vlen | value
//   response: i64 status_or_len | payload
// Ops: 1=PUT 2=GET 3=ADD(i64 delta -> new value) 4=AND 5=OR
//      6=GETC (value returned only once `count >= expected`)
//      7=DEL  8=PING
// AND/OR combine byte arrays elementwise and track contributor count; GETC
// takes an 8-byte little-endian expected-count as its value and returns the
// combined bytes once enough ranks contributed (the 2-allreduce bitvector
// negotiation collapses to: every rank AND/ORs, then GETCs).

#include <arpa/inet.h>
#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace hvdn {

struct Entry {
  std::vector<uint8_t> value;
  int64_t count = 0;  // contributors (AND/OR) or monotonically bumped on PUT
};

class KVServer {
 public:
  explicit KVServer(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~KVServer() { Stop(); }

  int port() const { return port_; }
  bool ok() const { return listen_fd_ >= 0; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    std::lock_guard<std::mutex> g(conn_mu_);
    // Serve threads may be blocked in recv() on idle client connections;
    // shutdown their fds so the joins below cannot hang (Serve still owns
    // the close()).
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : conn_threads_)
      if (t.joinable()) t.join();
  }

 private:
  static bool ReadAll(int fd, void* buf, size_t n) {
    auto* p = static_cast<uint8_t*>(buf);
    while (n > 0) {
      ssize_t r = ::recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  static bool WriteAll(int fd, const void* buf, size_t n) {
    auto* p = static_cast<const uint8_t*>(buf);
    while (n > 0) {
      ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stopping_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(conn_mu_);
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stopping_.load()) {
      uint8_t op;
      uint32_t klen;
      uint64_t vlen;
      if (!ReadAll(fd, &op, 1) || !ReadAll(fd, &klen, 4) ||
          klen > (1u << 20))
        break;
      std::string key(klen, '\0');
      if (!ReadAll(fd, key.data(), klen) || !ReadAll(fd, &vlen, 8) ||
          vlen > (1ull << 32))
        break;
      std::vector<uint8_t> val(vlen);
      if (vlen && !ReadAll(fd, val.data(), vlen)) break;

      int64_t status = 0;
      std::vector<uint8_t> payload;
      {
        std::lock_guard<std::mutex> g(mu_);
        switch (op) {
          case 1: {  // PUT
            auto& e = store_[key];
            e.value = std::move(val);
            e.count += 1;
            break;
          }
          case 2: {  // GET
            auto it = store_.find(key);
            if (it == store_.end()) {
              status = -1;
            } else {
              payload = it->second.value;
              status = static_cast<int64_t>(payload.size());
            }
            break;
          }
          case 3: {  // ADD
            int64_t delta = 0;
            if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
            auto& e = store_[key];
            if (e.value.size() != 8) e.value.assign(8, 0);
            int64_t cur;
            std::memcpy(&cur, e.value.data(), 8);
            cur += delta;
            std::memcpy(e.value.data(), &cur, 8);
            e.count += 1;
            status = cur;
            break;
          }
          case 4:    // AND
          case 5: {  // OR
            auto& e = store_[key];
            if (e.value.empty()) {
              e.value = val;
            } else if (e.value.size() == val.size()) {
              for (size_t i = 0; i < val.size(); ++i)
                e.value[i] = (op == 4) ? (e.value[i] & val[i])
                                       : (e.value[i] | val[i]);
            } else {
              status = -2;  // size mismatch
              break;
            }
            e.count += 1;
            status = e.count;
            break;
          }
          case 6: {  // GETC
            int64_t expected = 0;
            if (val.size() == 8) std::memcpy(&expected, val.data(), 8);
            auto it = store_.find(key);
            if (it == store_.end() || it->second.count < expected) {
              status = -1;  // not ready
            } else {
              payload = it->second.value;
              status = static_cast<int64_t>(payload.size());
            }
            break;
          }
          case 7:  // DEL
            store_.erase(key);
            break;
          case 8:  // PING
            status = 42;
            break;
          default:
            status = -3;
        }
      }
      if (!WriteAll(fd, &status, 8)) break;
      if (status > 0 && !payload.empty() &&
          !WriteAll(fd, payload.data(), payload.size()))
        break;
    }
    // Deregister BEFORE close: once closed the fd number can be reused by
    // an unrelated descriptor, and a stale entry would make Stop()'s
    // shutdown() tear down that stranger's socket. (Also keeps conn_fds_
    // from growing for the lifetime of a long launcher.)
    {
      std::lock_guard<std::mutex> g(conn_mu_);
      conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                      conn_fds_.end());
    }
    ::close(fd);
  }

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> conn_threads_;
  std::mutex mu_;
  std::map<std::string, Entry> store_;
};

class KVClient {
 public:
  KVClient(const char* host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    ::inet_pton(AF_INET, host, &addr.sin_addr);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
        0) {
      ::close(fd_);
      fd_ = -1;
      return;
    }
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }

  ~KVClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return fd_ >= 0; }

  // Returns status; fills out (resized) on GET-like ops.
  int64_t Request(uint8_t op, const std::string& key, const uint8_t* val,
                  uint64_t vlen, std::vector<uint8_t>* out) {
    std::lock_guard<std::mutex> g(mu_);
    if (fd_ < 0) return -100;
    uint32_t klen = static_cast<uint32_t>(key.size());
    if (!WriteAll(fd_, &op, 1) || !WriteAll(fd_, &klen, 4) ||
        !WriteAll(fd_, key.data(), klen) || !WriteAll(fd_, &vlen, 8) ||
        (vlen && !WriteAll(fd_, val, vlen)))
      return -100;
    int64_t status;
    if (!ReadAll(fd_, &status, 8)) return -100;
    if (status > 0 && out != nullptr && (op == 2 || op == 6)) {
      out->resize(static_cast<size_t>(status));
      if (!ReadAll(fd_, out->data(), out->size())) return -100;
    }
    return status;
  }

 private:
  static bool ReadAll(int fd, void* buf, size_t n) {
    auto* p = static_cast<uint8_t*>(buf);
    while (n > 0) {
      ssize_t r = ::recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }
  static bool WriteAll(int fd, const void* buf, size_t n) {
    auto* p = static_cast<const uint8_t*>(buf);
    while (n > 0) {
      ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace hvdn

extern "C" {

void* hvdn_kv_server_start(int port) {
  auto* s = new hvdn::KVServer(port);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int hvdn_kv_server_port(void* h) {
  return static_cast<hvdn::KVServer*>(h)->port();
}

void hvdn_kv_server_stop(void* h) {
  auto* s = static_cast<hvdn::KVServer*>(h);
  s->Stop();
  delete s;
}

void* hvdn_kv_client_new(const char* host, int port) {
  auto* c = new hvdn::KVClient(host, port);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

void hvdn_kv_client_free(void* h) { delete static_cast<hvdn::KVClient*>(h); }

long long hvdn_kv_request(void* h, int op, const char* key,
                          const unsigned char* val, unsigned long long vlen,
                          unsigned char* out, unsigned long long outcap) {
  std::vector<uint8_t> payload;
  int64_t st = static_cast<hvdn::KVClient*>(h)->Request(
      static_cast<uint8_t>(op), key, val, vlen, &payload);
  if (st > 0 && out != nullptr) {
    uint64_t n = payload.size() < outcap ? payload.size() : outcap;
    std::memcpy(out, payload.data(), n);
  }
  return st;
}

}  // extern "C"
