"""NHWC-canonical layout pass: channel padding to the MXU lane width.

The static face of the conv-MFU gap (ROADMAP item 1) is hvdhlo rule
HVD204: a conv/dot channel dim that is not a multiple of the 128-wide
vector lanes makes the MXU pad every tile up — at ResNet-50's stage-0
width of 64 that is 50% pure padding FLOPs on every conv touching the
dim, silently, on every step. This pass applies the fix HVD204's
finding text prescribes: pad the channel dims to the lane width ONCE,
in the parameters, so the compiled program only ever sees lane-aligned
shapes and the padding FLOPs become real FLOPs the MXU was spending
anyway.

Mechanics
---------

A model declares its conv stack once (`models/resnet.conv_stack`): every
channel-carrying dim of every param/stat array, tagged with the named
channel EDGE it rides. Edges capture the sharing the pass must respect —
a conv's output channels, its BatchNorm vectors, and the residual trunk
a whole stage adds over must all pad together or shapes stop lining up.
`plan()` then decides per edge, using the same thresholds hvdhlo HVD204
lints with (the 128-lane width, the padding-waste floor):

* pad an edge up to the next lane multiple when its waste is at or
  above the floor (default: the HVD204 floor) AND the growth stays
  within ``HOROVOD_LAYOUT_MAX_GROWTH`` (default 2.0 — 64→128 pads,
  the 3-channel image input's 42x blow-up never does);
* otherwise leave it as declared.

Zero padding is EXACT for conv+BN+ReLU stacks, forward and backward:

* padded weight columns produce zero activations; BN on an all-zero
  channel has mean 0 / var 0 (``rsqrt(eps)`` — finite), and zero
  scale/bias keep the normalized output zero through ReLU and residual
  adds;
* padded weight ROWS (input channels) multiply the zero activations, so
  real outputs are untouched;
* gradients into padded channels are identically zero (the masked
  upstream gradient is zero there, and dx through zero weight rows is
  zero), so SGD/momentum/Adam leave the padding at zero — training
  never drifts into the padded lanes (pinned by tests/test_layout.py).

``plan.pad(tree)`` rewrites params/activations-stats to the padded-lane
shapes; ``plan.strip(tree)`` removes the padding at the boundary
(checkpointing, eval export). `core/autotune.OnlineLayoutTuner` scores
padded vs as-declared by measured step time and broadcasts rank 0's
choice, so all ranks agree under the consistency verifier
(docs/perf.md "conv fast path").
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Mapping, Optional, Tuple

# The SAME analysis HVD204 lints with (docs/static_analysis.md): one
# lane-width constant and one waste formula, shared so the pass and the
# lint can never disagree about what "aligned" means.
from horovod_tpu.analysis.hlo_rules import (LANE, _min_pad_waste_pct,
                                            _pad_waste_pct)

LAYOUT_PAD_ENV = "HOROVOD_LAYOUT_PAD"
LAYOUT_MIN_WASTE_ENV = "HOROVOD_LAYOUT_MIN_WASTE_PCT"
LAYOUT_MAX_GROWTH_ENV = "HOROVOD_LAYOUT_MAX_GROWTH"

#: The layout modes the autotuner arbitrates between (docs/perf.md).
AS_DECLARED = "as_declared"
NHWC_PADDED = "nhwc_padded"


def layout_pad_enabled() -> bool:
    """HOROVOD_LAYOUT_PAD=0 turns plan() into an as-declared no-op."""
    return os.environ.get(LAYOUT_PAD_ENV, "").strip() not in (
        "0", "false", "False")


def _min_waste_pct() -> float:
    """Waste floor below which an unaligned edge is left alone —
    defaults to hvdhlo HVD204's own floor so pass and lint agree."""
    v = os.environ.get(LAYOUT_MIN_WASTE_ENV, "").strip()
    try:
        return float(v) if v else _min_pad_waste_pct()
    except ValueError:
        return _min_pad_waste_pct()


def _max_growth() -> float:
    """Cap on padded/original size: 2.0 admits the 64→128 stage-0 pad
    but rejects padding the 3-channel image input 42x."""
    v = os.environ.get(LAYOUT_MAX_GROWTH_ENV, "").strip()
    try:
        return float(v) if v else 2.0
    except ValueError:
        return 2.0


@dataclasses.dataclass(frozen=True)
class Site:
    """One declared array: `path` (slash-separated keys into the nested
    param/stat dict) and `dims` mapping each channel-carrying dim index
    to its named edge."""

    path: str
    dims: Mapping[int, str]


@dataclasses.dataclass(frozen=True)
class Edge:
    """One named channel stream's layout decision."""

    name: str
    size: int
    padded: int
    waste_pct: float  # MXU padding waste of the UNPADDED size

    @property
    def is_padded(self) -> bool:
        return self.padded != self.size


class LayoutError(ValueError):
    pass


def _get(tree: Any, path: str):
    node = tree
    for key in path.split("/"):
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    return node


def _set(tree: Dict, path: str, value) -> None:
    keys = path.split("/")
    node = tree
    for key in keys[:-1]:
        node = node[key]
    node[keys[-1]] = value


def _copy_tree(tree: Any) -> Any:
    if isinstance(tree, dict):
        return {k: _copy_tree(v) for k, v in tree.items()}
    return tree


class LayoutPlan:
    """The per-edge padding decisions for one declared conv stack, and
    the pad/strip rewrites they imply."""

    def __init__(self, edges: Dict[str, Edge], sites: List[Site]):
        self.edges = edges
        self.sites = sites

    @property
    def mode(self) -> str:
        return NHWC_PADDED if any(e.is_padded for e in
                                  self.edges.values()) else AS_DECLARED

    def padded_edges(self) -> Dict[str, Tuple[int, int]]:
        return {e.name: (e.size, e.padded)
                for e in self.edges.values() if e.is_padded}

    def _site_pads(self, tree, site: Site, reverse: bool):
        arr = _get(tree, site.path)
        if arr is None:
            return None, None  # site lives in the other tree (stats)
        pads = []
        changed = False
        for d in range(getattr(arr, "ndim", 0)):
            edge = site.dims.get(d)
            e = self.edges.get(edge) if edge else None
            if e is None or not e.is_padded:
                pads.append((0, 0))
                continue
            want, have = (e.size, e.padded) if reverse else (e.padded,
                                                             e.size)
            if arr.shape[d] == want:
                pads.append((0, 0))  # already in the target layout
                continue
            if arr.shape[d] != have:
                raise LayoutError(
                    f"layout: {site.path} dim {d} is {arr.shape[d]}, "
                    f"expected {have} (edge {edge!r} "
                    f"{e.size}->{e.padded})")
            pads.append((0, want - have))
            changed = True
        return arr, (pads if changed else None)

    def pad(self, tree):
        """Zero-pad every declared array of `tree` to its padded-lane
        shape (a copy; undeclared leaves are shared). Sites whose path
        is absent are skipped — one stack declares params AND stats,
        each pad() call rewrites the tree it was given."""
        import jax.numpy as jnp

        out = _copy_tree(tree)
        for site in self.sites:
            arr, pads = self._site_pads(out, site, reverse=False)
            if pads is not None:
                _set(out, site.path, jnp.pad(arr, pads))
        return out

    def strip(self, tree):
        """Inverse of pad(): slice every declared array back to its
        as-declared shape (the boundary rewrite — checkpoints and eval
        exports must never see padded lanes)."""
        out = _copy_tree(tree)
        for site in self.sites:
            arr, pads = self._site_pads(out, site, reverse=True)
            if pads is not None:
                # pads carry (0, want-have) with want < have here: a
                # negative hi cuts the dim back down to as-declared
                sl = tuple(slice(0, arr.shape[d] + p[1])
                           for d, p in enumerate(pads))
                _set(out, site.path, arr[sl])
        return out

    def summary(self) -> Dict[str, Any]:
        """Compact stamp for bench sections / perf_gate
        (docs/perf.md): the mode, which edges padded, and the HVD204
        waste the padding removed."""
        padded = self.padded_edges()
        worst = max((e.waste_pct for e in self.edges.values()
                     if e.is_padded), default=0.0)
        return {
            "mode": self.mode,
            "lane": LANE,
            "edges": len(self.edges),
            "padded_edges": {k: list(v) for k, v in sorted(
                padded.items())},
            "max_waste_removed_pct": round(worst, 1),
        }


def plan(tree, stack: List[Site], min_waste_pct: Optional[float] = None,
         max_growth: Optional[float] = None) -> LayoutPlan:
    """Decide the layout for one declared conv stack against the
    as-declared `tree` (typically the params; stats sites simply
    resolve to nothing here and pad along by edge at pad() time).

    HOROVOD_LAYOUT_PAD=0 (or a floor/growth that rejects every edge)
    yields an AS_DECLARED plan whose pad()/strip() are identity.
    """
    floor = _min_waste_pct() if min_waste_pct is None else min_waste_pct
    growth = _max_growth() if max_growth is None else max_growth
    enabled = layout_pad_enabled()
    edges: Dict[str, Edge] = {}
    for site in stack:
        arr = _get(tree, site.path)
        if arr is None:
            continue
        for d, edge in site.dims.items():
            if d >= getattr(arr, "ndim", 0):
                raise LayoutError(
                    f"layout: {site.path} has no dim {d} "
                    f"(shape {getattr(arr, 'shape', None)})")
            size = arr.shape[d]
            prev = edges.get(edge)
            if prev is not None:
                if prev.size != size:
                    raise LayoutError(
                        f"layout: edge {edge!r} declared at two sizes "
                        f"({prev.size} vs {size} at {site.path})")
                continue
            padded = size
            if enabled and size % LANE:
                up = -(-size // LANE) * LANE
                if _pad_waste_pct(size, LANE) >= floor \
                        and up <= growth * size:
                    padded = up
            edges[edge] = Edge(edge, size, padded,
                               _pad_waste_pct(size, LANE)
                               if size % LANE else 0.0)
    return LayoutPlan(edges, list(stack))
