"""Fused 1x1-conv + BatchNorm backward as a Pallas TPU kernel.

THE ResNet-50 step-time lever (round-4 trace, docs/benchmarks.md): the
BN-backward reduction family is ~33% of the step and sits at its own HBM
roofline because XLA materializes the BN-input gradient `dy` between the
BN-backward elementwise pass and the conv backward that consumes it:

    XLA schedule per 1x1-conv+BN site (all full HBM streams):
      pass A   read dz, y            -> dbeta, dgamma   (reductions)
      pass B   read dz, y            -> WRITE dy
      conv dx  read dy (+w)          -> write dx
      conv dW  read dy, x_in         -> write dW

`dy` is written once and read twice — three full streams of the largest
activation family in the network (the 4*width conv3 outputs alone are
~1.4 GB/step at B=128). This kernel fuses pass B INTO both consumer
matmuls: each (block_m, C) tile of dy is formed in registers from
(dz, y, stats, pass-A sums) and immediately fed to the MXU for
dx = dy @ w.T and the dW accumulation — dy never exists in HBM:

    fused:
      pass A   read dz, y            -> dbeta, dgamma   (XLA, unchanged)
      kernel   read dz, y, x_in      -> write dx, dW    (one pass)

Pass A stays in XLA: its reductions must COMPLETE before any dy tile can
be formed (two-phase dependency), and XLA already runs it at the
streaming roofline. Only 1x1 convs qualify (their backward-input is a
matmul the MXU eats directly); 3x3 sites keep XLA's conv custom-calls.

The dW accumulator rides in VMEM scratch across the sequential TPU grid;
dx tiles stream out. bf16 in, f32 accumulation, bf16 out — matching what
XLA does for the unfused sequence.

No reference counterpart (the reference wraps cuDNN's fused
BatchNormBackwardEx, torch/mxnet do the fusion below it); this is the
TPU-native equivalent of that fusion, one level deeper.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block_m(m: int, c: int, cin: int, vmem_budget=7 * 2**20) -> int:
    """Largest row block that divides m, keeps the working set (streamed
    tiles double-buffered + the persistent dW accumulator) inside VMEM,
    and stays a multiple of the 8-row sublane."""
    fixed = cin * c * (4 + 2)  # f32 accumulator + bf16 weights
    for bm in (1024, 512, 448, 256, 128, 64, 32, 16, 8):
        if m % bm:
            continue
        streamed = 2 * bm * (2 * c + 2 * cin) * 2  # dz,y,x_in,dx bf16 x2
        if fixed + streamed + bm * c * 4 <= vmem_budget:
            return bm
    return 8


def _bwd_kernel(dz_ref, y_ref, x_ref, w_ref, g_ref, mean_ref, inv_ref,
                a_ref, b_ref, dx_ref, dw_ref, dw_acc_ref):
    """One (block_m, C) row tile: form dy in registers, feed both MXU
    contractions, accumulate dW across the sequential grid.

    dy = g*dz - A - B*xhat — the full train-mode BN backward (gradients
    through batch mean/var, plus any cotangents on the aux stats outputs)
    pre-folded into per-channel vectors by the wrapper:
      g = gamma*inv,  A = g*dbeta/M - dmean/M,
      B = g*dgamma/M - 2*dvar/(M*inv)."""
    dz = dz_ref[:].astype(jnp.float32)          # (bm, C)
    y = y_ref[:].astype(jnp.float32)            # (bm, C)
    xhat = (y - mean_ref[:]) * inv_ref[:]       # (bm, C), stats bcast (1, C)
    dy = (g_ref[:] * dz - a_ref[:] - b_ref[:] * xhat).astype(dz_ref.dtype)
    dx_ref[:] = jax.lax.dot_general(
        dy, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dx_ref.dtype)
    part = jax.lax.dot_general(                 # x_in^T @ dy -> (Cin, C)
        x_ref[:], dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_acc_ref[:] = part

    @pl.when(pl.program_id(0) > 0)
    def _acc():
        dw_acc_ref[:] += part

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _emit():
        dw_ref[:] = dw_acc_ref[:]


def conv1x1_bn_bwd_fused(dz: jax.Array, y: jax.Array, x_in: jax.Array,
                         w: jax.Array, scale: jax.Array, mean: jax.Array,
                         inv: jax.Array, dbeta: jax.Array,
                         dgamma: jax.Array, dmean=None,
                         dvar=None) -> Tuple[jax.Array, jax.Array]:
    """dx, dw for a 1x1 conv followed by train-mode BN, given the
    upstream gradient dz w.r.t. the BN OUTPUT and pass A's sums.

    dz, y: (M, C) rows (flattened N*H*W); x_in: (M, Cin); w: (Cin, C);
    scale/mean/inv/dbeta/dgamma: (C,) f32. dmean/dvar: optional (C,) f32
    cotangents on the batch-stat outputs (exactly folded into the
    per-channel vectors — see _bwd_kernel). Returns dx (M, Cin) in
    x_in.dtype and dw (Cin, C) f32.
    """
    m, c = dz.shape
    cin = x_in.shape[1]
    minv = 1.0 / m
    g = scale.astype(jnp.float32) * inv
    a_vec = g * dbeta * minv
    b_vec = g * dgamma * minv
    if dmean is not None:
        a_vec = a_vec - dmean * minv
    if dvar is not None:
        b_vec = b_vec - 2.0 * dvar * minv / inv
    # Pad rows to a sublane multiple: padded x_in rows are ZERO, so their
    # (nonzero) dy never reaches dW (0^T @ dy) and their dx rows are
    # sliced off below. minv stays 1/m — the real row count.
    m_pad = -m % 8
    if m_pad:
        pad = lambda a: jnp.pad(a, ((0, m_pad), (0, 0)))  # noqa: E731
        dz, y, x_in = pad(dz), pad(y), pad(x_in)
    mp = m + m_pad
    bm = _pick_block_m(mp, c, cin)
    row = lambda v: v.reshape(1, c).astype(jnp.float32)  # noqa: E731
    dx, dw = pl.pallas_call(
        _bwd_kernel,
        grid=(mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, c), lambda i: (i, 0)),       # dz
            pl.BlockSpec((bm, c), lambda i: (i, 0)),       # y
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),     # x_in
            pl.BlockSpec((cin, c), lambda i: (0, 0)),      # w
            pl.BlockSpec((1, c), lambda i: (0, 0)),        # g
            pl.BlockSpec((1, c), lambda i: (0, 0)),        # mean
            pl.BlockSpec((1, c), lambda i: (0, 0)),        # inv
            pl.BlockSpec((1, c), lambda i: (0, 0)),        # A
            pl.BlockSpec((1, c), lambda i: (0, 0)),        # B
        ],
        out_specs=[
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),     # dx
            pl.BlockSpec((cin, c), lambda i: (0, 0)),      # dw
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cin), x_in.dtype),
            jax.ShapeDtypeStruct((cin, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((cin, c), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),  # sequential: dW accum
        interpret=_interpret(),
    )(dz, y, x_in, w, row(g), row(mean), row(inv), row(a_vec), row(b_vec))
    return (dx[:m] if m_pad else dx), dw


# --------------------------------------------------------------------------
# custom_vjp wrapper: the model-facing fused op
# --------------------------------------------------------------------------

def _bn_sums(dz, y, mean, inv):
    """Pass A (XLA): dbeta = sum(dz), dgamma = sum(dz * xhat) — one fused
    read of dz+y, already at the streaming roofline."""
    dzf = dz.astype(jnp.float32)
    xhat = (y.astype(jnp.float32) - mean) * inv
    return jnp.sum(dzf, axis=0), jnp.sum(dzf * xhat, axis=0)


def _fwd_math(x, w, scale, bias, eps):
    y = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    mean = jnp.mean(y, axis=0, dtype=jnp.float32)
    meansq = jnp.mean(jnp.square(y.astype(jnp.float32)), axis=0)
    var = meansq - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    z = ((y.astype(jnp.float32) - mean) * inv).astype(x.dtype) * scale + bias
    return z, (y, mean, var, inv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def conv1x1_bn(x, w, scale, bias, eps=1e-5):
    """z = batch_norm(x @ w) over flattened rows, train mode — forward in
    plain XLA, backward through the fused Pallas kernel. Returns
    (z, (batch_mean, batch_var)); the aux stats feed running-stat updates
    exactly like models/resnet.batch_norm does."""
    z, (y, mean, var, inv) = _fwd_math(x, w, scale, bias, eps)
    return z, (mean, var)


def _conv1x1_bn_fwd(x, w, scale, bias, eps):
    z, (y, mean, var, inv) = _fwd_math(x, w, scale, bias, eps)
    return (z, (mean, var)), (x, w, scale, y, mean, inv)


def _conv1x1_bn_bwd(eps, res, cts):
    x, w, scale, y, mean, inv = res
    dz, (dmean, dvar) = cts
    dbeta, dgamma = _bn_sums(dz, y, mean, inv)
    # dmean/dvar cotangents (zero in normal training — optax treats batch
    # stats as state — but exact when a loss does use the aux stats) fold
    # into the kernel's per-channel vectors for free.
    dx, dw = conv1x1_bn_bwd_fused(
        dz, y, x, w, scale.astype(jnp.float32).ravel(), mean, inv,
        dbeta, dgamma, dmean=dmean, dvar=dvar)
    return (dx, dw.astype(w.dtype), dgamma.astype(scale.dtype),
            dbeta.astype(scale.dtype))


conv1x1_bn.defvjp(_conv1x1_bn_fwd, _conv1x1_bn_bwd)


def conv1x1_bn_nhwc(x, w, scale, bias, eps=1e-5):
    """NHWC convenience wrapper: x (N, H, W, Cin), w (1, 1, Cin, Cout) or
    (Cin, Cout). Returns (z in NHWC, (mean, var))."""
    n, h, wd, cin = x.shape
    w2 = w.reshape(w.shape[-2], w.shape[-1])
    z, stats = conv1x1_bn(x.reshape(n * h * wd, cin), w2, scale, bias, eps)
    return z.reshape(n, h, wd, -1), stats
