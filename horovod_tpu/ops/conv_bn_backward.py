"""Fused 1x1-conv + BatchNorm backward as a Pallas TPU kernel.

THE ResNet-50 step-time lever (round-4 trace, docs/benchmarks.md): the
BN-backward reduction family is ~33% of the step and sits at its own HBM
roofline because XLA materializes the BN-input gradient `dy` between the
BN-backward elementwise pass and the conv backward that consumes it:

    XLA schedule per 1x1-conv+BN site (all full HBM streams):
      pass A   read dz, y            -> dbeta, dgamma   (reductions)
      pass B   read dz, y            -> WRITE dy
      conv dx  read dy (+w)          -> write dx
      conv dW  read dy, x_in         -> write dW

`dy` is written once and read twice — three full streams of the largest
activation family in the network (the 4*width conv3 outputs alone are
~1.4 GB/step at B=128). This kernel fuses pass B INTO both consumer
matmuls: each (block_m, C) tile of dy is formed in registers from
(dz, y, stats, pass-A sums) and immediately fed to the MXU for
dx = dy @ w.T and the dW accumulation — dy never exists in HBM:

    fused:
      pass A   read dz, y            -> dbeta, dgamma   (XLA, unchanged)
      kernel   read dz, y, x_in      -> write dx, dW    (one pass)

Pass A stays in XLA: its reductions must COMPLETE before any dy tile can
be formed (two-phase dependency), and XLA already runs it at the
streaming roofline. Only 1x1 convs qualify (their backward-input is a
matmul the MXU eats directly); 3x3 sites keep XLA's conv custom-calls.

The dW accumulator rides as a constant-index f32 output block, resident
in VMEM across the sequential (row x C-block) grid; dx tiles stream out.
bf16 in, f32 accumulation, bf16 out — matching what XLA does for the
unfused sequence.

MEASURED OUTCOME (r05, v5e, scripts/bn_conv_bwd_ab.py +
docs/benchmarks.md): the kernel WINS at the layer level — 1.47-1.90x at
the dominant high-resolution conv3 sites, parity at conv1 — but LOSES
integrated into the ResNet-50 train step (80.9 vs 45.2 ms), because the
custom_vjp boundary de-fuses the surrounding graph: relu and its mask
become standalone full-size passes, the BN stat reduces detach from
their neighbors, and XLA inserts {3,0,2,1}<->{3,2,1,0} layout copies
between the flat (M, C) kernel operands and the 3x3 convs' preferred
batch-minor layouts (~tens of ms of copies in the trace). The model
integration therefore defaults OFF (models/resnet.py _fuse_conv_bn);
closing the gap would need relu/residual-add absorbed into the op
boundary AND layout-custom pallas outputs.

No reference counterpart (the reference wraps cuDNN's fused
BatchNormBackwardEx, torch/mxnet do the fusion below it); this is the
TPU-native equivalent of that fusion, one level deeper.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block_m(m: int, bc: int, cin: int, c_full: int,
                  vmem_budget=9 * 2**20) -> int:
    """Largest row block that divides m, keeps the working set (streamed
    tiles double-buffered + the resident f32 dW output accumulator of
    the FULL (Cin, C)) inside VMEM, and stays a multiple of the 8-row
    sublane."""
    fixed = cin * c_full * 4  # resident f32 dW accumulator (output block)
    for bm in (1024, 512, 448, 256, 128, 64, 32, 16, 8):
        if m % bm:
            continue
        streamed = 2 * (2 * bm * bc * 2 + bm * cin * 2 + cin * bc * 2
                        + bm * cin * 2)  # dz,y,x_in,w,dx x2 buffers
        if fixed + streamed + bm * bc * 4 + bm * cin * 4 <= vmem_budget:
            return bm
    return 8


def _bwd_kernel(dz_ref, y_ref, x_ref, w_ref, g_ref, mean_ref, inv_ref,
                a_ref, b_ref, dx_ref, dw_ref, dx_acc_ref):
    """One (bm, bc) tile of a (rows x C-blocks) grid: form dy in
    registers, feed both MXU contractions.

    dy = g*dz - A - B*xhat — the full train-mode BN backward (gradients
    through batch mean/var, plus any cotangents on the aux stats outputs)
    pre-folded into per-channel vectors by the wrapper:
      g = gamma*inv,  A = g*dbeta/M - dmean/M,
      B = g*dgamma/M - 2*dvar/(M*inv).

    Grid is (row blocks, C blocks), C innermost. dx accumulates over the
    inner C loop in f32 scratch and is emitted once per row block; dW
    rides a CONSTANT-index f32 output block — resident in VMEM for the
    whole sequential grid (copy-out only at grid end), accumulated at
    the (0, j*bc) column slice each step."""
    i, j = pl.program_id(0), pl.program_id(1)
    nc = pl.num_programs(1)
    bc = dz_ref.shape[1]
    dz = dz_ref[:].astype(jnp.float32)          # (bm, bc)
    y = y_ref[:].astype(jnp.float32)            # (bm, bc)
    xhat = (y - mean_ref[:]) * inv_ref[:]       # stats bcast (1, bc)
    dy = (g_ref[:] * dz - a_ref[:] - b_ref[:] * xhat).astype(dz_ref.dtype)
    part_dx = jax.lax.dot_general(              # dy @ w_blk^T -> (bm, Cin)
        dy, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _dx_init():
        dx_acc_ref[:] = part_dx

    @pl.when(j > 0)
    def _dx_acc():
        dx_acc_ref[:] += part_dx

    @pl.when(j == nc - 1)
    def _dx_emit():
        dx_ref[:] = dx_acc_ref[:].astype(dx_ref.dtype)

    part_dw = jax.lax.dot_general(              # x^T @ dy -> (Cin, bc)
        x_ref[:], dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = pl.ds(pl.multiple_of(j * bc, 128), bc)

    @pl.when(i == 0)
    def _dw_init():  # uninitialized VMEM may hold NaN bits: store, not 0*
        dw_ref[:, col] = part_dw

    @pl.when(i > 0)
    def _dw_acc():
        dw_ref[:, col] = dw_ref[:, col] + part_dw


def conv1x1_bn_bwd_fused(dz: jax.Array, y: jax.Array, x_in: jax.Array,
                         w: jax.Array, scale: jax.Array, mean: jax.Array,
                         inv: jax.Array, dbeta: jax.Array,
                         dgamma: jax.Array, dmean=None, dvar=None,
                         count=None) -> Tuple[jax.Array, jax.Array]:
    """dx, dw for a 1x1 conv followed by train-mode BN, given the
    upstream gradient dz w.r.t. the BN OUTPUT and pass A's sums.

    dz, y: (M, C) rows (flattened N*H*W); x_in: (M, Cin); w: (Cin, C);
    scale/mean/inv/dbeta/dgamma: (C,) f32. dmean/dvar: optional (C,) f32
    cotangents on the batch-stat outputs (exactly folded into the
    per-channel vectors — see _bwd_kernel). count: total rows behind the
    batch stats (M * axis_size under sync-BN; defaults to M). Returns
    dx (M, Cin) in x_in.dtype and dw (Cin, C) f32.
    """
    m, c = dz.shape
    cin = x_in.shape[1]
    minv = 1.0 / (count if count is not None else m)
    g = scale.astype(jnp.float32) * inv
    a_vec = g * dbeta * minv
    b_vec = g * dgamma * minv
    if dmean is not None:
        a_vec = a_vec - dmean * minv
    if dvar is not None:
        b_vec = b_vec - 2.0 * dvar * minv / inv
    # Pad rows to a sublane multiple: padded x_in rows are ZERO, so their
    # (nonzero) dy never reaches dW (0^T @ dy) and their dx rows are
    # sliced off below. minv stays 1/m — the real row count.
    m_pad = -m % 8
    if m_pad:
        pad = lambda a: jnp.pad(a, ((0, m_pad), (0, 0)))  # noqa: E731
        dz, y, x_in = pad(dz), pad(y), pad(x_in)
    mp = m + m_pad
    # C blocks: cap the per-step tile at 512 lanes so the resident f32
    # dW block (not per-C-block scratch) is the only Cin*C-sized buffer
    # and row blocks stay large at the wide sites (Cin=512, C=2048 used
    # to collapse to 16-row blocks).
    if c <= 512:
        bc = c
    else:  # largest dividing block <= 512, lane-aligned (c % 128 == 0
        # holds for all model channel counts; 768 -> bc=384, 2048 -> 512)
        bc = next((b for b in (512, 384, 256, 128) if c % b == 0), None)
        if bc is None:
            raise ValueError(
                f"conv1x1_bn_bwd_fused: C={c} > 512 must be divisible by "
                f"a 128-multiple block (got C % 128 == {c % 128})")
    bm = _pick_block_m(mp, bc, cin, c)
    row = lambda v: v.reshape(1, c).astype(jnp.float32)  # noqa: E731
    dx, dw = pl.pallas_call(
        _bwd_kernel,
        grid=(mp // bm, c // bc),
        in_specs=[
            pl.BlockSpec((bm, bc), lambda i, j: (i, j)),     # dz
            pl.BlockSpec((bm, bc), lambda i, j: (i, j)),     # y
            pl.BlockSpec((bm, cin), lambda i, j: (i, 0)),    # x_in
            pl.BlockSpec((cin, bc), lambda i, j: (0, j)),    # w
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # g
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # mean
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # inv
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # A
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # B
        ],
        out_specs=[
            pl.BlockSpec((bm, cin), lambda i, j: (i, 0)),    # dx
            # constant index: the f32 dW accumulator stays resident in
            # VMEM across the whole sequential grid, one copy-out at end
            pl.BlockSpec((cin, c), lambda i, j: (0, 0)),     # dw
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cin), x_in.dtype),
            jax.ShapeDtypeStruct((cin, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bm, cin), jnp.float32)],  # dx accum
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),  # sequential
        interpret=_interpret(),
    )(dz, y, x_in, w, row(g), row(mean), row(inv), row(a_vec), row(b_vec))
    return (dx[:m] if m_pad else dx), dw


# --------------------------------------------------------------------------
# custom_vjp wrapper: the model-facing fused op
# --------------------------------------------------------------------------

def _bn_sums(dz, y, mean, inv):
    """Pass A (XLA): dbeta = sum(dz), dgamma = sum(dz * xhat) — one fused
    read of dz+y, already at the streaming roofline."""
    dzf = dz.astype(jnp.float32)
    xhat = (y.astype(jnp.float32) - mean) * inv
    return jnp.sum(dzf, axis=0), jnp.sum(dzf * xhat, axis=0)


def _axis_size(axis_name) -> int:
    return 1 if axis_name is None else jax.lax.axis_size(axis_name)


def _pmean(v, axis_name):
    return v if axis_name is None else jax.lax.pmean(v, axis_name)


def _fwd_math(x, w, scale, bias, eps, axis_name):
    y = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    # With axis_name: cross-replica (sync) batch stats, the fused analog
    # of models/resnet.batch_norm's pmean'd stats.
    mean = _pmean(jnp.mean(y, axis=0, dtype=jnp.float32), axis_name)
    meansq = _pmean(jnp.mean(jnp.square(y.astype(jnp.float32)), axis=0),
                    axis_name)
    var = meansq - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    z = ((y.astype(jnp.float32) - mean) * inv).astype(x.dtype) * scale + bias
    return z, (y, mean, var, inv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def conv1x1_bn(x, w, scale, bias, eps=1e-5, axis_name=None):
    """z = batch_norm(x @ w) over flattened rows, train mode — forward in
    plain XLA, backward through the fused Pallas kernel. With
    `axis_name`, batch stats are synced across that mesh axis (sync-BN
    semantics matching models/resnet.batch_norm). Returns
    (z, (batch_mean, batch_var)); the aux stats feed running-stat updates
    exactly like models/resnet.batch_norm does. Param/input grads are the
    per-rank partials — the framework's gradient psum completes them,
    same as the unfused autodiff path."""
    z, (y, mean, var, inv) = _fwd_math(x, w, scale, bias, eps, axis_name)
    return z, (mean, var)


def _conv1x1_bn_fwd(x, w, scale, bias, eps, axis_name):
    z, (y, mean, var, inv) = _fwd_math(x, w, scale, bias, eps, axis_name)
    return (z, (mean, var)), (x, w, scale, y, mean, inv)


def _conv1x1_bn_bwd(eps, axis_name, res, cts):
    x, w, scale, y, mean, inv = res
    dz, (dmean, dvar) = cts
    dbeta, dgamma = _bn_sums(dz, y, mean, inv)
    # Sync-BN backward needs the GLOBAL reductions and row count in the
    # dy formula; the RETURNED dscale/dbias stay per-rank partials (the
    # framework's later gradient psum makes them global, exactly like
    # unfused autodiff). dmean/dvar cotangents (zero in normal training —
    # optax treats batch stats as state — but exact when a loss does use
    # the aux stats) fold into the kernel's per-channel vectors for free.
    k = _axis_size(axis_name)
    db_g = dbeta if axis_name is None else jax.lax.psum(dbeta, axis_name)
    dg_g = dgamma if axis_name is None else jax.lax.psum(dgamma, axis_name)
    dm_g = dmean if axis_name is None else jax.lax.psum(dmean, axis_name)
    dv_g = dvar if axis_name is None else jax.lax.psum(dvar, axis_name)
    dx, dw = conv1x1_bn_bwd_fused(
        dz, y, x, w, scale.astype(jnp.float32).ravel(), mean, inv,
        db_g, dg_g, dmean=dm_g, dvar=dv_g, count=dz.shape[0] * k)
    return (dx, dw.astype(w.dtype), dgamma.astype(scale.dtype),
            dbeta.astype(scale.dtype))


conv1x1_bn.defvjp(_conv1x1_bn_fwd, _conv1x1_bn_bwd)


def conv1x1_bn_nhwc(x, w, scale, bias, eps=1e-5, axis_name=None):
    """NHWC convenience wrapper: x (N, H, W, Cin), w (1, 1, Cin, Cout) or
    (Cin, Cout). Returns (z in NHWC, (mean, var))."""
    n, h, wd, cin = x.shape
    w2 = w.reshape(w.shape[-2], w.shape[-1])
    z, stats = conv1x1_bn(x.reshape(n * h * wd, cin), w2, scale, bias,
                          eps, axis_name)
    return z.reshape(n, h, wd, -1), stats
