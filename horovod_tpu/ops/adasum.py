"""Adasum: scaling-insensitive gradient combination, TPU-native.

Reference: horovod/common/ops/adasum/adasum.h:195-344 — recursive
vector-halving distance-doubling (VHDD) where each pairwise step computes
dot(a,b), ‖a‖², ‖b‖² and combines

    adasum(a, b) = (1 - a·b / (2‖a‖²)) · a  +  (1 - a·b / (2‖b‖²)) · b

which removes the common (parallel) component once instead of twice, making
the reduction insensitive to learning-rate scaling across replicas.

TPU redesign: the reference halves vectors to spread bandwidth across an
MPI tree (adasum.h FusedAllreduce). On a TPU mesh the exchange is
`lax.ppermute` over ICI at distance 2^l per level — log2(k) exchanges of the
full vector. ICI bandwidth makes halving unnecessary at the gradient sizes
involved, and whole-vector exchange keeps every rank's dot products local
(no extra reduction round per level, where the reference needs an
MPI_Allreduce of [a·b, ‖a‖², ‖b‖²] per pair-group).

The combine is associative only pairwise, so the pairing order matches the
reference's hypercube order: level l pairs rank i with i XOR 2^l. For
non-power-of-two sets, surplus ranks fold into their (i - p2) partner first
and read the result back at the end (reference adasum_mpi.cc remainder
handling).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise Adasum combine in float32 (adasum.h:346+ math)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    na = jnp.vdot(af, af)
    nb = jnp.vdot(bf, bf)
    # Guards: zero-norm operand contributes nothing to the projection
    # (reference: adasum.h checks normsq == 0 → plain sum).
    ca = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)), 1.0)
    cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)), 1.0)
    return (ca * af + cb * bf).astype(a.dtype)


def adasum_reduce_block(block: jax.Array, axis: str, k: int) -> jax.Array:
    """Adasum-allreduce one (1, *shape) per-rank block inside shard_map.

    After log2(p2) ppermute levels every rank in the power-of-two core holds
    the identical combined vector; surplus ranks (non-power-of-two sets) are
    folded in before and read back after.
    """
    x = block[0]
    p2 = 1
    while p2 * 2 <= k:
        p2 *= 2
    idx = lax.axis_index(axis)

    if p2 != k:
        # Fold surplus ranks r ∈ [p2, k) into partner r - p2.
        perm_in = [(r, r - p2) for r in range(p2, k)]
        folded = lax.ppermute(x, axis, perm=perm_in)
        has_partner = idx < (k - p2)
        x = jnp.where(has_partner, _combine(x, folded), x)

    d = 1
    while d < p2:
        pairs = [(i, i ^ d) for i in range(p2)]
        other = lax.ppermute(x, axis, perm=pairs)
        in_core = idx < p2
        x = jnp.where(in_core, _combine(x, other), x)
        d *= 2

    if p2 != k:
        # Send results back to the surplus ranks.
        perm_out = [(r - p2, r) for r in range(p2, k)]
        back = lax.ppermute(x, axis, perm=perm_out)
        x = jnp.where(idx >= p2, back, x)
    return x[None]


def adasum_numpy_reference(tensors) -> "np.ndarray":
    """Host-side reference implementation for tests (plays the role of the
    NumPy oracle in the reference's test_adasum_pytorch.py)."""
    import numpy as np

    def comb(a, b):
        a = a.astype(np.float64)
        b = b.astype(np.float64)
        dot = float(np.vdot(a, b))
        na = float(np.vdot(a, a))
        nb = float(np.vdot(b, b))
        ca = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
        cb = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
        return ca * a + cb * b

    vals = [np.asarray(t, dtype=np.float64) for t in tensors]
    k = len(vals)
    p2 = 1
    while p2 * 2 <= k:
        p2 *= 2
    for r in range(p2, k):
        vals[r - p2] = comb(vals[r - p2], vals[r])
    d = 1
    while d < p2:
        nxt = list(vals[:p2])
        for i in range(p2):
            nxt[i] = comb(vals[i], vals[i ^ d])
        vals[:p2] = nxt
        d *= 2
    return vals[0]
