"""Adasum: scaling-insensitive gradient combination, TPU-native.

Reference: horovod/common/ops/adasum/adasum.h:195-344 — recursive
vector-halving distance-doubling (VHDD) where each pairwise step computes
dot(a,b), ‖a‖², ‖b‖² and combines

    adasum(a, b) = (1 - a·b / (2‖a‖²)) · a  +  (1 - a·b / (2‖b‖²)) · b

which removes the common (parallel) component once instead of twice, making
the reduction insensitive to learning-rate scaling across replicas.

TPU redesign: two exchange strategies, same math.

  default — full-vector ppermute at distance 2^l per level: log2(k)·n
  traffic, but every rank's dot products stay local (no reduction round
  per level). The right trade when gradients fit ICI bandwidth and
  latency dominates.

  HOROVOD_ADASUM_HALVING — the reference's true vector-halving
  distance-doubling (adasum.h:195 FusedAllreduce): each level exchanges
  only half the remaining segment (~2·n total traffic incl. the final
  allgather), with the pair's full-vector dots computed as distributed
  partials psum'd over the growing 2^(l+1)-rank subgroup (reference:
  FusedPairwiseReduceWithComm + per-level reduction communicator,
  adasum_mpi.cc). The right trade for very large gradients or
  bandwidth-constrained (DCN-spanning) sets.

The combine is associative only pairwise, so the pairing order matches the
reference's hypercube order: level l pairs rank i with i XOR 2^l. For
non-power-of-two sets, surplus ranks fold into their (i - p2) partner first
and read the result back at the end (reference adasum_mpi.cc remainder
handling).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _coeffs(dot, na, nb):
    """Projection coefficients with zero-norm guards (reference: adasum.h
    checks normsq == 0 → plain sum)."""
    ca = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)), 1.0)
    cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)), 1.0)
    return ca, cb


def _combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise Adasum combine in float32 (adasum.h:346+ math)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    ca, cb = _coeffs(jnp.vdot(af, bf), jnp.vdot(af, af), jnp.vdot(bf, bf))
    return (ca * af + cb * bf).astype(a.dtype)


def adasum_reduce_block(block: jax.Array, axis: str, k: int,
                        halving: bool = False) -> jax.Array:
    """Adasum-allreduce one (1, *shape) per-rank block inside shard_map.

    After log2(p2) ppermute levels every rank in the power-of-two core holds
    the identical combined vector; surplus ranks (non-power-of-two sets) are
    folded in before and read back after. With `halving`
    (HOROVOD_ADASUM_HALVING) the levels run the reference's true VHDD
    exchange — see _vhdd_core.
    """
    x = block[0]
    p2 = 1
    while p2 * 2 <= k:
        p2 *= 2
    idx = lax.axis_index(axis)

    if p2 != k:
        # Fold surplus ranks r ∈ [p2, k) into partner r - p2.
        perm_in = [(r, r - p2) for r in range(p2, k)]
        folded = lax.ppermute(x, axis, perm=perm_in)
        has_partner = idx < (k - p2)
        x = jnp.where(has_partner, _combine(x, folded), x)

    if halving and p2 > 1:
        x = _vhdd_core(x, axis, p2, idx)
    else:
        d = 1
        while d < p2:
            pairs = [(i, i ^ d) for i in range(p2)]
            other = lax.ppermute(x, axis, perm=pairs)
            in_core = idx < p2
            x = jnp.where(in_core, _combine(x, other), x)
            d *= 2

    if p2 != k:
        # Send results back to the surplus ranks.
        perm_out = [(r - p2, r) for r in range(p2, k)]
        back = lax.ppermute(x, axis, perm=perm_out)
        x = jnp.where(idx >= p2, back, x)
    return x[None]


def _bitrev(j: int, bits: int) -> int:
    out = 0
    for _ in range(bits):
        out = (out << 1) | (j & 1)
        j >>= 1
    return out


def _vhdd_core(x: jax.Array, axis: str, p2: int, idx) -> jax.Array:
    """True vector-halving distance-doubling (reference: adasum.h:195
    FusedAllreduce). At level l only 1/2^(l+1) of the vector crosses the
    wire; each pair's full-vector dot products are computed as distributed
    partials summed over the pair (reference: FusedPairwiseReduceWithComm
    partial dots + per-pair allreduce). Total traffic ≈ 2·n vs the
    full-vector path's log2(p2)·n.
    """
    dtype = x.dtype
    shape = x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.size
    pad = (-n) % p2
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    cur = flat
    levels = p2.bit_length() - 1

    d = 1
    while d < p2:
        pairs = [(i, i ^ d) for i in range(p2)]
        half = cur.size // 2
        h0, h1 = cur[:half], cur[half:]
        bit = (idx // d) % 2            # which half this rank keeps
        keep = jnp.where(bit == 0, h0, h1)
        send = jnp.where(bit == 0, h1, h0)
        recv = lax.ppermute(send, axis, perm=pairs)
        # The level combines subtree vectors A (bit==0 side) and B; their
        # segments are spread over the whole 2d-rank subgroup, so the
        # full-vector dots are a sum of per-rank partials over that group
        # (reference: the growing reduction communicator in
        # FusedPairwiseReduceWithComm, adasum_mpi.cc). Partials are tagged
        # by which side this rank's keep/recv segments belong to.
        kk = jnp.vdot(keep, keep)
        rr = jnp.vdot(recv, recv)
        in_core = (idx < p2).astype(jnp.float32)
        part = jnp.stack([
            jnp.vdot(keep, recv),                  # A·B piece
            jnp.where(bit == 0, kk, rr),           # |A|² piece
            jnp.where(bit == 0, rr, kk),           # |B|² piece
        ]) * in_core                               # surplus contributes 0
        # Group-psum as ONE uniform full-axis psum of group-bucketed
        # partials: TPU lowering rejects unequal axis_index_groups, which
        # any non-power-of-two set would need (core groups of 2d + a
        # surplus remainder). Scatter into this rank's group row instead.
        num_groups = p2 // (2 * d)
        gid = jnp.clip(idx // (2 * d), 0, num_groups - 1)
        buckets = jnp.zeros((num_groups, 3), jnp.float32)
        buckets = lax.dynamic_update_slice(buckets, part[None],
                                           (gid, jnp.int32(0)))
        totals = lax.psum(buckets, axis)           # (num_groups, 3)
        mine = lax.dynamic_slice(totals, (gid, jnp.int32(0)), (1, 3))[0]
        dot, na, nb = mine[0], mine[1], mine[2]
        ca, cb = _coeffs(dot, na, nb)
        # own segment: A-side ranks hold A_seg in keep; B-side in recv.
        cur = jnp.where(bit == 0, ca * keep + cb * recv,
                        cb * keep + ca * recv)
        d *= 2

    # Rank r holds global segment bit_reverse(r): level 0's bit picks the
    # biggest split, so the segment index reads the rank's bits MSB-first.
    gathered = lax.all_gather(cur, axis, axis=0)     # (k, n_pad / p2)
    combined = jnp.concatenate(
        [gathered[_bitrev(j, levels)] for j in range(p2)])
    return combined[:n].reshape(shape).astype(dtype)


def adasum_numpy_reference(tensors) -> "np.ndarray":
    """Host-side reference implementation for tests (plays the role of the
    NumPy oracle in the reference's test_adasum_pytorch.py)."""
    import numpy as np

    def comb(a, b):
        a = a.astype(np.float64)
        b = b.astype(np.float64)
        dot = float(np.vdot(a, b))
        na = float(np.vdot(a, a))
        nb = float(np.vdot(b, b))
        ca = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
        cb = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
        return ca * a + cb * b

    vals = [np.asarray(t, dtype=np.float64) for t in tensors]
    k = len(vals)
    p2 = 1
    while p2 * 2 <= k:
        p2 *= 2
    for r in range(p2, k):
        vals[r - p2] = comb(vals[r - p2], vals[r])
    d = 1
    while d < p2:
        nxt = list(vals[:p2])
        for i in range(p2):
            nxt[i] = comb(vals[i], vals[i ^ d])
        vals[:p2] = nxt
        d *= 2
    return vals[0]
