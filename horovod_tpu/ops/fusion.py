"""Trace-time tensor fusion (the fusion-buffer analog).

Reference: horovod/common/fusion_buffer_manager.cc + the MemcpyInFusionBuffer
machinery (collective_operations.h:89-124) and the FuseResponses rules
(controller.cc:901): only tensors with the same dtype fuse, and a fused
payload stays under HOROVOD_FUSION_THRESHOLD bytes.

TPU redesign: instead of a persistent 64-128MB device buffer plus batched D2D
memcpy kernels (cuda_kernels.cu), fusion happens at trace time — flatten,
concat into ≤-threshold buckets, run ONE collective per bucket, split back.
XLA fuses the reshapes/concats into the collective's prologue/epilogue, which
is exactly what the hand-written memcpy kernels were approximating.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def plan_buckets(shapes_dtypes: Sequence[Tuple[Tuple[int, ...], str]],
                 threshold_bytes: int) -> List[List[int]]:
    """Partition tensor indices into fusion buckets.

    Same-dtype tensors are packed greedily in submission order until the
    bucket would exceed `threshold_bytes` (FuseResponses greedy rule,
    controller.cc:901-980). Returns a list of index lists.
    """
    buckets: List[List[int]] = []
    open_bucket: dict = {}  # dtype -> (bucket_index, bytes_used)
    for i, (shape, dtype) in enumerate(shapes_dtypes):
        nbytes = int(np.prod(shape, dtype=np.int64)) * jnp.dtype(dtype).itemsize
        cur = open_bucket.get(dtype)
        if cur is not None and cur[1] + nbytes <= max(threshold_bytes, nbytes):
            buckets[cur[0]].append(i)
            open_bucket[dtype] = (cur[0], cur[1] + nbytes)
        else:
            buckets.append([i])
            open_bucket[dtype] = (len(buckets) - 1, nbytes)
    return buckets


def fused_reduce_blocks(blocks: Sequence[jax.Array],
                        reduce_fn: Callable[[jax.Array], jax.Array],
                        threshold_bytes: int) -> Tuple[jax.Array, ...]:
    """Reduce many (1, *shape) blocks with one collective per fusion bucket.

    `reduce_fn` maps a (1, n) fused block to its reduced (1, n) result.
    """
    metas = [(tuple(b.shape[1:]), str(b.dtype)) for b in blocks]
    buckets = plan_buckets(metas, threshold_bytes)
    out: List[jax.Array] = [None] * len(blocks)  # type: ignore[list-item]
    for idxs in buckets:
        flats = [blocks[i].reshape(1, -1) for i in idxs]
        sizes = [f.shape[1] for f in flats]
        fused = flats[0] if len(flats) == 1 else jnp.concatenate(flats, axis=1)
        red = reduce_fn(fused)
        off = 0
        for i, n in zip(idxs, sizes):
            piece = red[:, off:off + n]
            out[i] = piece.reshape(blocks[i].shape).astype(blocks[i].dtype)
            off += n
    return tuple(out)


def flatten_and_bucket(tree, threshold_bytes: int):
    """Bucket an arbitrary pytree of arrays (used by DistributedOptimizer).

    Returns (leaves, treedef, buckets) where buckets index into leaves.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    metas = [(tuple(np.shape(l)), str(jnp.asarray(l).dtype)) for l in leaves]
    return leaves, treedef, plan_buckets(metas, threshold_bytes)
