"""Trace-time tensor fusion (the fusion-buffer analog).

Reference: horovod/common/fusion_buffer_manager.cc + the MemcpyInFusionBuffer
machinery (collective_operations.h:89-124) and the FuseResponses rules
(controller.cc:901): only tensors with the same dtype fuse, and a fused
payload stays under HOROVOD_FUSION_THRESHOLD bytes.

TPU redesign: instead of a persistent 64-128MB device buffer plus batched D2D
memcpy kernels (cuda_kernels.cu), fusion happens at trace time — flatten,
concat into ≤-threshold buckets, run ONE collective per bucket, split back.
XLA fuses the reshapes/concats into the collective's prologue/epilogue, which
is exactly what the hand-written memcpy kernels were approximating.

Two properties the original greedy packer lacked, both measured to matter
(BENCH_r05 fusion sweep: 16-64 MB buckets ~2x slower than 1-4 MB on the
8-device mesh):

* **Oversize chunking** — a tensor larger than the threshold used to form
  its own oversized bucket (``max(threshold, nbytes)``), so one 64 MB
  gradient re-created exactly the giant payload the threshold exists to
  prevent. Now such tensors are SPLIT into near-equal chunks of at most
  ``max(threshold, _MIN_CHUNK_BYTES)`` bytes, and the chunks pack into
  buckets like ordinary tensors (PyTorch DDP's gradient-bucketing rule,
  Li et al., VLDB 2020 §4.2).

* **Reverse (backward-production) ordering** — gradients materialize in
  reverse forward order during the backward pass, so packing buckets from
  the LAST leaf backwards aligns each bucket with a contiguous span of
  early-available gradients. Inside one XLA program that lets the
  scheduler launch bucket collectives while the remaining backward compute
  is still running (the role of the reference's background RunLoopOnce
  cycle); with forward-order packing the first bucket depends on the very
  last gradient produced and nothing can overlap.

Both properties are CI-gated at the HLO level, not just unit-tested:
``make hlo-lint`` (hvdhlo rule HVD201, analysis/hlo_rules.py) lowers the
canonical DP step through this planner and fails on any fused all-reduce
payload above the bucket cap surviving to the program — a refactor here
that silently resurrects the pre-bucketing single-giant-allreduce plan
is caught at lower time on CPU-only CI (docs/static_analysis.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Floor on chunk granularity: a sub-1MB chunk of a large tensor costs more
# in per-collective latency than it saves in pipelining, and pathological
# thresholds (tests use 1- and 8-BYTE thresholds to force one bucket per
# tensor) must not explode into thousands of chunks.
_MIN_CHUNK_BYTES = 1 << 20


@dataclasses.dataclass(frozen=True)
class BucketItem:
    """One contiguous slice of a (flattened) tensor inside a bucket."""

    index: int  # position in the submitted tensor list
    start: int  # element offset into the flattened tensor
    size: int   # element count


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One fusion bucket: same-dtype items reduced by ONE collective."""

    dtype: str
    itemsize: int
    items: Tuple[BucketItem, ...]

    @property
    def elems(self) -> int:
        return sum(it.size for it in self.items)

    @property
    def nbytes(self) -> int:
        return self.elems * self.itemsize


def effective_threshold(threshold_bytes: int, cap_bytes: int) -> int:
    """The bucket size actually used: ``min(threshold, cap)``.

    The cap (HOROVOD_BUCKET_CAP, default 4 MB — the measured sweet spot of
    the r05 fusion sweep) bounds the wire payload even when a user or the
    GP autotuner asks for a larger fusion threshold; 0 disables it.
    """
    t = max(int(threshold_bytes), 1)
    return min(t, int(cap_bytes)) if cap_bytes and cap_bytes > 0 else t


def plan_buckets(shapes_dtypes: Sequence[Tuple[Tuple[int, ...], str]],
                 threshold_bytes: int,
                 reverse: bool = False) -> List[Bucket]:
    """Partition tensors (or chunks of them) into fusion buckets.

    Same-dtype items pack greedily in submission order — reversed when
    ``reverse`` is set (see module docstring) — until the bucket would
    exceed ``threshold_bytes`` (FuseResponses greedy rule,
    controller.cc:901-980). Tensors larger than the chunk granularity
    ``max(threshold_bytes, 1MB)`` are split into near-equal chunks first,
    so no bucket ever exceeds the threshold because of a single oversize
    tensor (the 16-64 MB cliff fix). A tensor that exceeds the threshold
    but not the 1MB floor still gets a bucket of its own, preserving the
    tiny-threshold "one bucket per tensor" behavior tests rely on.

    Deterministic: identical inputs yield an identical plan on every rank
    (required — the plan shapes the compiled program every rank runs).
    """
    thresh = max(int(threshold_bytes), 1)
    chunk_bytes = max(thresh, _MIN_CHUNK_BYTES)
    buckets: List[dict] = []  # {"dtype","itemsize","bytes","items"}
    open_bucket: dict = {}    # dtype -> bucket index
    order = range(len(shapes_dtypes) - 1, -1, -1) if reverse \
        else range(len(shapes_dtypes))
    for i in order:
        shape, dtype = shapes_dtypes[i]
        itemsize = jnp.dtype(dtype).itemsize
        total = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = total * itemsize
        if nbytes > chunk_bytes:
            per = max(chunk_bytes // itemsize, 1)
            nchunks = -(-total // per)  # ceil
            base, rem = divmod(total, nchunks)
            pieces = []
            off = 0
            for c in range(nchunks):
                sz = base + (1 if c < rem else 0)
                pieces.append(BucketItem(i, off, sz))
                off += sz
        else:
            pieces = [BucketItem(i, 0, total)]
        for it in pieces:
            it_bytes = it.size * itemsize
            bi = open_bucket.get(dtype)
            if bi is not None and \
                    buckets[bi]["bytes"] + it_bytes <= thresh:
                buckets[bi]["items"].append(it)
                buckets[bi]["bytes"] += it_bytes
            else:
                buckets.append({"dtype": dtype, "itemsize": itemsize,
                                "bytes": it_bytes, "items": [it]})
                open_bucket[dtype] = len(buckets) - 1
    return [Bucket(b["dtype"], b["itemsize"], tuple(b["items"]))
            for b in buckets]


def plan_signature(plan: Sequence[Bucket]) -> str:
    """Short stable fingerprint of a bucket plan.

    Embedded in the collective-dispatch descriptor, so the consistency
    checker / fingerprint verifier catch ranks whose thresholds (and hence
    plans, and hence compiled programs) diverged — the cheap cross-rank
    agreement proof the online bucket tuner leans on.
    """
    h = hashlib.sha256(repr([(b.dtype, b.items) for b in plan]).encode())
    return f"{len(plan)}b:{h.hexdigest()[:10]}"


def fused_reduce_blocks(blocks: Sequence[jax.Array],
                        reduce_fn: Callable[[jax.Array], jax.Array],
                        threshold_bytes: int,
                        reverse: bool = False) -> Tuple[jax.Array, ...]:
    """Reduce many (1, *shape) blocks with one collective per fusion bucket.

    `reduce_fn` maps a (1, n) fused block to its reduced (1, n) result.
    Tensors larger than the threshold are chunked across buckets and
    reassembled here; with ``reverse`` the buckets are packed in backward
    production order (see module docstring).
    """
    metas = [(tuple(b.shape[1:]), str(b.dtype)) for b in blocks]
    plan = plan_buckets(metas, threshold_bytes, reverse=reverse)
    flats = [b.reshape(1, -1) for b in blocks]
    pieces: List[List[Tuple[int, jax.Array]]] = [[] for _ in blocks]
    for bucket in plan:
        segs = [flats[it.index][:, it.start:it.start + it.size]
                for it in bucket.items]
        fused = segs[0] if len(segs) == 1 else jnp.concatenate(segs, axis=1)
        red = reduce_fn(fused)
        off = 0
        for it in bucket.items:
            pieces[it.index].append((it.start, red[:, off:off + it.size]))
            off += it.size
    out: List[jax.Array] = []
    for i, b in enumerate(blocks):
        ps = [p for _, p in sorted(pieces[i], key=lambda t: t[0])]
        flat = ps[0] if len(ps) == 1 else jnp.concatenate(ps, axis=1)
        out.append(flat.reshape(b.shape).astype(b.dtype))
    return tuple(out)
