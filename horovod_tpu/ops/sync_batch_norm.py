"""Cross-replica synchronized batch normalization.

Reference: horovod/tensorflow/sync_batch_norm.py (allreduce of mean/var
across ranks) and horovod/torch/sync_batch_norm.py (count-weighted moment
sync supporting uneven per-rank batches).

Two entry points:
  * `sync_batch_norm` — for use INSIDE shard_map/pjit code: moments are
    pmean'd over the mesh axis (compiled ICI collective). This is the fast
    path ResNet training uses (models/resnet.py batch_norm(axis_name=...)).
  * `SyncBatchNorm` — eager module-style wrapper over the process set for
    Horovod-API parity.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common import types as T
from horovod_tpu.core.process_sets import ProcessSet, global_process_set
from horovod_tpu.ops import collectives


def sync_batch_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
                    axis_name: str = "hvd",
                    eps: float = 1e-5,
                    reduce_axes: Optional[Tuple[int, ...]] = None
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Normalize with cross-replica batch statistics (inside shard_map).

    Count-weighted like the reference torch implementation: each replica
    contributes sum and sum-of-squares with its local count, so uneven
    per-replica batches stay exact.

    Returns (normalized, global_mean, global_var) — the caller owns running
    stats.
    """
    axes = reduce_axes if reduce_axes is not None else \
        tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    local_count = 1.0
    for a in axes:
        local_count *= x.shape[a]
    s = jnp.sum(xf, axis=axes)
    ss = jnp.sum(jnp.square(xf), axis=axes)
    tot = lax.psum(jnp.asarray(local_count, jnp.float32), axis_name)
    s = lax.psum(s, axis_name)
    ss = lax.psum(ss, axis_name)
    mean = s / tot
    var = ss / tot - jnp.square(mean)
    inv = lax.rsqrt(var + eps).astype(x.dtype)
    out = (x - mean.astype(x.dtype)) * inv * scale + bias
    return out, mean, var


class SyncBatchNorm:
    """Eager, Horovod-API-parity wrapper (reference:
    hvd.SyncBatchNormalization). Keeps running stats; call like a layer."""

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.9,
                 process_set: Optional[ProcessSet] = None):
        self.eps = eps
        self.momentum = momentum
        self.process_set = process_set or global_process_set
        self.scale = jnp.ones((num_features,), jnp.float32)
        self.bias = jnp.zeros((num_features,), jnp.float32)
        self.running_mean = jnp.zeros((num_features,), jnp.float32)
        self.running_var = jnp.ones((num_features,), jnp.float32)

    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        axes = tuple(range(x.ndim - 1))
        if not train:
            inv = lax.rsqrt(self.running_var + self.eps).astype(x.dtype)
            return (x - self.running_mean.astype(x.dtype)) * inv * \
                self.scale.astype(x.dtype) + self.bias.astype(x.dtype)
        xf = x.astype(jnp.float32)
        n = 1.0
        for a in axes:
            n *= x.shape[a]
        # Count-weighted cross-rank moments via eager allreduce (SUM).
        stats = jnp.concatenate([
            jnp.sum(xf, axis=axes), jnp.sum(jnp.square(xf), axis=axes),
            jnp.asarray([n], jnp.float32)])
        tot = collectives.allreduce(stats, op=T.ReduceOp.SUM,
                                    process_set=self.process_set)
        c = tot.shape[0] // 2
        count = tot[-1]
        mean = tot[:c] / count
        var = tot[c:2 * c] / count - jnp.square(mean)
        self.running_mean = self.running_mean * self.momentum + \
            mean * (1 - self.momentum)
        self.running_var = self.running_var * self.momentum + \
            var * (1 - self.momentum)
        inv = lax.rsqrt(var + self.eps).astype(x.dtype)
        return (x - mean.astype(x.dtype)) * inv * \
            self.scale.astype(x.dtype) + self.bias.astype(x.dtype)


# Reference-API alias.
SyncBatchNormalization = SyncBatchNorm
