"""Gradient compression hooks.

Reference: horovod/tensorflow/compression.py:1-74 (Compression.none/.fp16).
TPU addition: bf16 is the native reduced precision on the MXU/ICI, so it is
the recommended compressor here.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


class Compressor:
    """Interface for compressing tensors before a collective."""

    @staticmethod
    def compress(tensor: jax.Array) -> Tuple[jax.Array, Any]:
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: jax.Array, ctx: Any) -> jax.Array:
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor: jax.Array) -> Tuple[jax.Array, Any]:
        return tensor, None

    @staticmethod
    def decompress(tensor: jax.Array, ctx: Any) -> jax.Array:
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: Any = jnp.float16

    @classmethod
    def compress(cls, tensor: jax.Array) -> Tuple[jax.Array, Any]:
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor: jax.Array, ctx: Any) -> jax.Array:
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    """Cast float tensors to fp16 on the wire (reference FP16Compressor)."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float tensors to bf16 on the wire — TPU-native default choice."""
    wire_dtype = jnp.bfloat16


class ThresholdedCompressor(Compressor):
    """Apply `inner` only to tensors of at least `min_bytes`.

    The bucket-pipeline wiring for "compress large messages": wire-time
    scales with payload so the multi-MB gradients (the ones the fusion
    buckets chunk) ride bf16/fp16, while the long tail of small
    bias/norm gradients — where cast overhead beats any transfer saving
    and precision matters most — keeps full precision. Buckets are
    planned on the COMPRESSED dtypes (compression runs before
    ops/fusion.plan_buckets in both the in-jit and eager paths), so
    compressed and uncompressed gradients land in separate same-dtype
    buckets.
    """

    def __init__(self, inner=None, min_bytes: int = 1 << 20):
        self.inner = inner if inner is not None else BF16Compressor
        self.min_bytes = int(min_bytes)

    def compress(self, tensor: jax.Array) -> Tuple[jax.Array, Any]:
        import numpy as np
        dtype = getattr(tensor, "dtype", None)
        if dtype is None:
            tensor = jnp.asarray(tensor)
            dtype = tensor.dtype
        nbytes = int(np.prod(np.shape(tensor), dtype=np.int64)) * \
            jnp.dtype(dtype).itemsize
        if nbytes >= self.min_bytes:
            return self.inner.compress(tensor)
        return tensor, None

    def decompress(self, tensor: jax.Array, ctx: Any) -> jax.Array:
        return self.inner.decompress(tensor, ctx)


class Compression:
    """Option namespace (reference compression.py:66-74)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor

    @staticmethod
    def thresholded(inner=None, min_bytes: int = 1 << 20
                    ) -> ThresholdedCompressor:
        """`inner` (default bf16) for tensors ≥ `min_bytes`, identity
        below — the recommended large-message setting for the bucketed
        gradient path (docs/perf.md)."""
        return ThresholdedCompressor(inner, min_bytes)


# Prebuilt large-message compressor: bf16 on the wire for ≥1 MB tensors.
Compression.bf16_large = ThresholdedCompressor(BF16Compressor, 1 << 20)
