"""Gradient compression hooks.

Reference: horovod/tensorflow/compression.py:1-74 (Compression.none/.fp16).
TPU addition: bf16 is the native reduced precision on the MXU/ICI, so it is
the recommended compressor here.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


class Compressor:
    """Interface for compressing tensors before a collective."""

    @staticmethod
    def compress(tensor: jax.Array) -> Tuple[jax.Array, Any]:
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: jax.Array, ctx: Any) -> jax.Array:
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor: jax.Array) -> Tuple[jax.Array, Any]:
        return tensor, None

    @staticmethod
    def decompress(tensor: jax.Array, ctx: Any) -> jax.Array:
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: Any = jnp.float16

    @classmethod
    def compress(cls, tensor: jax.Array) -> Tuple[jax.Array, Any]:
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor: jax.Array, ctx: Any) -> jax.Array:
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    """Cast float tensors to fp16 on the wire (reference FP16Compressor)."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float tensors to bf16 on the wire — TPU-native default choice."""
    wire_dtype = jnp.bfloat16


class Compression:
    """Option namespace (reference compression.py:66-74)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
