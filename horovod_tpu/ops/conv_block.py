"""Fused 1x1-conv + BatchNorm + ReLU block as Pallas TPU kernels.

The conv-MFU gap's kernel-level lever (ROADMAP item 1): ResNet-style
models spend their step in conv+BN+activation triplets, and XLA
schedules each triplet as separate full-HBM streams around the
materialized conv output `y`:

    XLA schedule per 1x1-conv+BN+ReLU site (forward):
      matmul   read x, w          -> WRITE y
      stats    read y             -> mean, meansq   (fused reduce pair)
      norm     read y             -> write z        (normalize+scale+relu)

`y` is written once and read twice. The forward kernel here folds the
stats reduction INTO the matmul pass: each (block_m, C) tile of y is
formed on the MXU and its per-channel partial sums (sum, sum-of-squares)
accumulate into VMEM-resident f32 rows before the tile is stored — one
full stream of y disappears. A single XLA elementwise epilogue then
forms mean/var and applies normalize+scale+relu (that pass XLA already
runs at the streaming roofline, so it stays outside the kernel).

    fused forward:
      kernel   read x, w          -> write y, sum, sumsq   (one pass)
      norm     read y             -> write z

The backward extends ops/conv_bn_backward.py's fused dx/dW kernel with
the ReLU mask folded into the register pipeline: the upstream gradient
dz (w.r.t. the ReLU OUTPUT) is masked, run through the train-mode BN
backward, and fed to both MXU contractions without `dy` (or the mask)
ever existing in HBM:

    fused backward:
      pass A   read dz, y         -> dbeta, dgamma  (masked sums; XLA)
      kernel   read dz, y, x_in   -> write dx, dW   (one pass)

Only 1x1 convs qualify (their backward-input is a matmul the MXU eats
directly); 3x3 sites keep XLA's conv custom-calls. The family degrades
to `relu=False` for the block's conv3/projection sites (BN with no
activation before the residual add).

A plain `jax.lax` reference (`conv_block_reference`) defines the ground
truth; tests/test_conv_block.py pins fused-vs-reference equivalence for
forward, gradients, batch-stat cotangents, and the bf16 path. On
non-TPU backends the kernels run in Pallas interpret mode (same
fallback as flash_attention / conv_bn_backward), so tier-1 exercises
the real pallas_call path on CPU.

Model wiring: HOROVOD_CONV_BLOCK=1 routes models/resnet.py's profitable
1x1 sites through this family (docs/perf.md "conv fast path"); it
supersedes the backward-only HOROVOD_FUSE_CONV_BN opt-in.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horovod_tpu.ops import conv_bn_backward as _cbb
from horovod_tpu.ops.conv_bn_backward import (_axis_size, _pick_block_m,
                                              _pmean)


def _interpret() -> bool:
    # Resolved through the conv_bn_backward MODULE (not a from-import
    # binding) so the TPU compile-only probe's monkeypatch of
    # conv_bn_backward._interpret flips BOTH kernel families to the
    # real Mosaic lowering (tests/tpu_probe.py).
    return _cbb._interpret()

CONV_BLOCK_ENV = "HOROVOD_CONV_BLOCK"


def conv_block_enabled() -> bool:
    """HOROVOD_CONV_BLOCK=1 opts the models into the fused block family
    (docs/perf.md, docs/env_vars.md)."""
    return os.environ.get(CONV_BLOCK_ENV, "").strip() in ("1", "true",
                                                          "True")


# --------------------------------------------------------------------------
# reference (ground truth for the equivalence suite)
# --------------------------------------------------------------------------

def conv_block_reference(x, w, scale, bias, eps=1e-5, axis_name=None,
                         relu=True):
    """Plain jax.lax math of the block over flattened rows: z =
    relu(batch_norm(x @ w)), train mode, returning (z, (mean, var)) —
    exactly what XLA computes unfused, and the contract the fused op
    must match."""
    y = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    mean = _pmean(jnp.mean(y, axis=0, dtype=jnp.float32), axis_name)
    meansq = _pmean(jnp.mean(jnp.square(y.astype(jnp.float32)), axis=0),
                    axis_name)
    var = meansq - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    # The whole epilogue runs in f32 (xhat, scale, bias, ReLU) and only
    # the final z rounds to the storage dtype. This is a deliberate
    # contract: the backward MASK recomputes this exact f32 chain, and
    # f32 is the only dtype whose arithmetic XLA and the Pallas kernel
    # reproduce identically (bf16 mul+add keeps excess precision
    # inconsistently across lowerings, so a bf16 epilogue's boundary
    # signs would be irreproducible in the backward).
    zf = ((y.astype(jnp.float32) - mean) * inv) \
        * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    if relu:
        zf = jax.nn.relu(zf)
    return zf.astype(x.dtype), (mean, var)


# --------------------------------------------------------------------------
# forward kernel: y = x @ w with the BN stat sums fused into the pass
# --------------------------------------------------------------------------

def _pick_fwd_block_m(m: int, bc: int, cin: int, c: int,
                      vmem_budget=9 * 2**20) -> int:
    """Largest row block that divides m and keeps the streamed tiles
    (double-buffered) plus the resident f32 stat rows inside VMEM."""
    fixed = 2 * c * 4  # resident f32 sum + sumsq rows
    for bm in (1024, 512, 448, 256, 128, 64, 32, 16, 8):
        if m % bm:
            continue
        streamed = 2 * (bm * cin * 2 + cin * bc * 2 + bm * bc * 2)
        if fixed + streamed + bm * bc * 4 <= vmem_budget:
            return bm
    return 8


def _fwd_kernel(x_ref, w_ref, y_ref, sum_ref, sq_ref):
    """One (bm, bc) tile: y = x @ w on the MXU; the tile's per-channel
    sum and sum-of-squares accumulate into constant-index f32 rows that
    stay VMEM-resident across the whole sequential grid (copy-out at
    grid end) — the stats reduction never re-reads y from HBM."""
    i, j = pl.program_id(0), pl.program_id(1)
    bc = y_ref.shape[1]
    yt = jax.lax.dot_general(x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    y_ref[:] = yt.astype(y_ref.dtype)
    # Sums of the STORED (rounded) y, not the f32 accumulator values:
    # the batch stats must describe the activations every later pass
    # (epilogue, backward xhat) actually reads, or bf16 boundary signs
    # diverge from the unfused reference.
    ys = yt.astype(y_ref.dtype).astype(jnp.float32)
    part_sum = jnp.sum(ys, axis=0, keepdims=True)        # (1, bc)
    part_sq = jnp.sum(jnp.square(ys), axis=0, keepdims=True)
    col = pl.ds(pl.multiple_of(j * bc, 128), bc)

    @pl.when(i == 0)
    def _init():  # uninitialized VMEM may hold NaN bits: store, not 0*
        sum_ref[:, col] = part_sum
        sq_ref[:, col] = part_sq

    @pl.when(i > 0)
    def _acc():
        sum_ref[:, col] = sum_ref[:, col] + part_sum
        sq_ref[:, col] = sq_ref[:, col] + part_sq


def _lane_block(c: int) -> int:
    """Largest dividing lane-aligned C block <= 512 (same policy as
    conv_bn_backward: the wide sites must not collapse the row blocks)."""
    if c <= 512:
        return c
    bc = next((b for b in (512, 384, 256, 128) if c % b == 0), None)
    if bc is None:
        raise ValueError(
            f"conv_block: C={c} > 512 must be divisible by a "
            f"128-multiple block (got C % 128 == {c % 128})")
    return bc


def conv1x1_fwd_fused(x: jax.Array, w: jax.Array
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """y = x @ w plus the per-channel (sum, sumsq) f32 rows, one fused
    pass. x: (M, Cin); w: (Cin, C). Returns (y (M, C) in x.dtype,
    sum (C,) f32, sumsq (C,) f32) — the sums cover the REAL M rows
    (zero row padding contributes zero to both)."""
    m, cin = x.shape
    c = w.shape[1]
    m_pad = -m % 8
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
    mp = m + m_pad
    bc = _lane_block(c)
    bm = _pick_fwd_block_m(mp, bc, cin, c)
    y, ssum, ssq = pl.pallas_call(
        _fwd_kernel,
        grid=(mp // bm, c // bc),
        in_specs=[
            pl.BlockSpec((bm, cin), lambda i, j: (i, 0)),     # x
            pl.BlockSpec((cin, bc), lambda i, j: (0, j)),     # w
        ],
        out_specs=[
            pl.BlockSpec((bm, bc), lambda i, j: (i, j)),      # y
            # constant index: the f32 stat rows stay resident in VMEM
            # across the whole sequential grid, one copy-out at end
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),        # sum
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),        # sumsq
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, c), x.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),  # sequential
        interpret=_interpret(),
    )(x, w)
    return (y[:m] if m_pad else y), ssum.ravel(), ssq.ravel()


# --------------------------------------------------------------------------
# backward kernel: ReLU mask + BN backward + both MXU contractions
# --------------------------------------------------------------------------

def _bwd_kernel(dz_ref, y_ref, x_ref, w_ref, g_ref, mean_ref, inv_ref,
                a_ref, b_ref, s_ref, bias_ref, dx_ref, dw_ref,
                dx_acc_ref):
    """One (bm, bc) tile: recompute the ReLU mask from (y, stats,
    scale, bias), mask dz, form dy in registers, feed both MXU
    contractions. Layout and accumulator scheme match
    conv_bn_backward._bwd_kernel; the only addition is the mask — for
    relu=False sites the wrapper passes (scale=0, bias=1) rows, which
    make zpre = 1 > 0 everywhere (mask all-true, zero extra cost)."""
    i, j = pl.program_id(0), pl.program_id(1)
    nc = pl.num_programs(1)
    bc = dz_ref.shape[1]
    dz = dz_ref[:].astype(jnp.float32)          # (bm, bc)
    y = y_ref[:].astype(jnp.float32)            # (bm, bc)
    xhat = (y - mean_ref[:]) * inv_ref[:]       # stats bcast (1, bc)
    # The mask recomputes the FORWARD's f32 epilogue chain (see
    # _fwd_math: xhat, scale, bias all f32, only the final z rounds to
    # the storage dtype) — sign decisions are reproducible because no
    # low-precision rounding sits in the decision path.
    zpre = xhat * s_ref[:] + bias_ref[:]
    dzm = jnp.where(zpre > 0.0, dz, 0.0)
    dy = (g_ref[:] * dzm - a_ref[:] - b_ref[:] * xhat).astype(dz_ref.dtype)
    part_dx = jax.lax.dot_general(              # dy @ w_blk^T -> (bm, Cin)
        dy, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _dx_init():
        dx_acc_ref[:] = part_dx

    @pl.when(j > 0)
    def _dx_acc():
        dx_acc_ref[:] += part_dx

    @pl.when(j == nc - 1)
    def _dx_emit():
        dx_ref[:] = dx_acc_ref[:].astype(dx_ref.dtype)

    part_dw = jax.lax.dot_general(              # x^T @ dy -> (Cin, bc)
        x_ref[:], dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = pl.ds(pl.multiple_of(j * bc, 128), bc)

    @pl.when(i == 0)
    def _dw_init():  # uninitialized VMEM may hold NaN bits: store, not 0*
        dw_ref[:, col] = part_dw

    @pl.when(i > 0)
    def _dw_acc():
        dw_ref[:, col] = dw_ref[:, col] + part_dw


def conv1x1_bn_act_bwd_fused(dz: jax.Array, y: jax.Array,
                             x_in: jax.Array, w: jax.Array,
                             scale: jax.Array, bias: jax.Array,
                             mean: jax.Array, inv: jax.Array,
                             dbeta: jax.Array, dgamma: jax.Array,
                             dmean=None, dvar=None, count=None,
                             relu: bool = True
                             ) -> Tuple[jax.Array, jax.Array]:
    """dx, dw for a 1x1 conv + train-mode BN + optional ReLU, given dz
    w.r.t. the BLOCK output and pass A's MASKED sums.

    dz, y: (M, C) rows (flattened N*H*W); x_in: (M, Cin); w: (Cin, C);
    mean/inv/dbeta/dgamma: (C,) f32; scale/bias: (C,) in the MODEL's
    dtype (the mask re-runs the forward's arithmetic chain in those
    dtypes). dbeta/dgamma are already the masked sums `_bn_act_sums`
    computes (with relu=False the mask is identity and they equal the
    plain BN sums).
    dmean/dvar: optional (C,) f32 cotangents on the batch-stat outputs,
    folded exactly into the per-channel vectors. count: total rows
    behind the batch stats (M * axis_size under sync-BN; defaults to
    M). Returns dx (M, Cin) in x_in.dtype and dw (Cin, C) f32."""
    m, c = dz.shape
    cin = x_in.shape[1]
    minv = 1.0 / (count if count is not None else m)
    scale = scale.astype(jnp.float32).ravel()
    g = scale * inv
    a_vec = g * dbeta * minv
    b_vec = g * dgamma * minv
    if dmean is not None:
        a_vec = a_vec - dmean * minv
    if dvar is not None:
        b_vec = b_vec - 2.0 * dvar * minv / inv
    # Padded x_in rows are ZERO, so padded-row dy never reaches dW
    # (0^T @ dy) and padded dx rows are sliced off below; padded-row dz
    # is zero too, so the mask cannot resurrect them. minv stays 1/m —
    # the real row count.
    m_pad = -m % 8
    if m_pad:
        pad = lambda a: jnp.pad(a, ((0, m_pad), (0, 0)))  # noqa: E731
        dz, y, x_in = pad(dz), pad(y), pad(x_in)
    mp = m + m_pad
    bc = _lane_block(c)
    bm = _pick_block_m(mp, bc, cin, c)
    row = lambda v: v.reshape(1, c).astype(jnp.float32)  # noqa: E731
    if relu:  # f32 rows: the mask reruns the forward's f32 epilogue
        s_row, b_row = scale, bias.astype(jnp.float32).ravel()
    else:  # mask all-true: zpre = xhat*0 + 1 > 0 everywhere
        s_row = jnp.zeros((c,), jnp.float32)
        b_row = jnp.ones((c,), jnp.float32)
    dx, dw = pl.pallas_call(
        _bwd_kernel,
        grid=(mp // bm, c // bc),
        in_specs=[
            pl.BlockSpec((bm, bc), lambda i, j: (i, j)),     # dz
            pl.BlockSpec((bm, bc), lambda i, j: (i, j)),     # y
            pl.BlockSpec((bm, cin), lambda i, j: (i, 0)),    # x_in
            pl.BlockSpec((cin, bc), lambda i, j: (0, j)),    # w
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # g
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # mean
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # inv
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # A
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # B
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # scale
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),      # bias
        ],
        out_specs=[
            pl.BlockSpec((bm, cin), lambda i, j: (i, 0)),    # dx
            # constant index: the f32 dW accumulator stays resident in
            # VMEM across the whole sequential grid, one copy-out at end
            pl.BlockSpec((cin, c), lambda i, j: (0, 0)),     # dw
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cin), x_in.dtype),
            jax.ShapeDtypeStruct((cin, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bm, cin), jnp.float32)],  # dx accum
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),  # sequential
        interpret=_interpret(),
    )(dz, y, x_in, w, row(g), row(mean), row(inv), row(a_vec),
      row(b_vec), row(s_row), row(b_row))
    return (dx[:m] if m_pad else dx), dw


# --------------------------------------------------------------------------
# custom_vjp wrapper: the model-facing fused block
# --------------------------------------------------------------------------

def _bn_act_sums(dz, y, mean, inv, scale, bias, relu):
    """Pass A (XLA): the MASKED BN-backward sums — dbeta = sum(dz*mask),
    dgamma = sum(dz*mask*xhat) — one fused read of dz+y, already at the
    streaming roofline (the mask is recomputed from y and the stats, no
    extra stream). dbeta doubles as dbias: dL/dbias = sum of the masked
    upstream gradient."""
    dzf = dz.astype(jnp.float32)
    xhat = (y.astype(jnp.float32) - mean) * inv
    if relu:
        # Same f32 epilogue chain as the forward and the kernel's mask
        # (see _fwd_math / _bwd_kernel): sign decisions match exactly.
        zpre = xhat * scale.astype(jnp.float32).ravel() \
            + bias.astype(jnp.float32).ravel()
        dzf = jnp.where(zpre > 0.0, dzf, 0.0)
    return jnp.sum(dzf, axis=0), jnp.sum(dzf * xhat, axis=0)


def _fwd_math(x, w, scale, bias, eps, axis_name, relu):
    y, ssum, ssq = conv1x1_fwd_fused(x, w)
    m = x.shape[0]
    # With axis_name: cross-replica (sync) batch stats — the fused
    # analog of models/resnet.batch_norm's pmean'd stats.
    mean = _pmean(ssum / m, axis_name)
    meansq = _pmean(ssq / m, axis_name)
    var = meansq - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    # f32 epilogue, final rounding only — the same chain the reference
    # defines and the backward mask recomputes (the reproducibility
    # contract is documented on conv_block_reference).
    zf = ((y.astype(jnp.float32) - mean) * inv) \
        * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    if relu:
        zf = jax.nn.relu(zf)
    return zf.astype(x.dtype), (y, mean, var, inv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def conv1x1_bn_act(x, w, scale, bias, eps=1e-5, axis_name=None,
                   relu=True):
    """z = relu(batch_norm(x @ w)) over flattened rows, train mode —
    forward through the fused stats kernel, backward through the fused
    masked kernel. With `axis_name`, batch stats sync across that mesh
    axis (sync-BN semantics, models/resnet.batch_norm contract).
    `relu=False` drops the activation (the block's conv3/projection
    sites: BN straight into the residual add). Returns
    (z, (batch_mean, batch_var)); the aux stats feed running-stat
    updates exactly like models/resnet.batch_norm. Param/input grads
    are per-rank partials — the framework's gradient psum completes
    them, same as the unfused autodiff path."""
    z, (y, mean, var, inv) = _fwd_math(x, w, scale, bias, eps, axis_name,
                                       relu)
    return z, (mean, var)


def _conv_block_fwd(x, w, scale, bias, eps, axis_name, relu):
    z, (y, mean, var, inv) = _fwd_math(x, w, scale, bias, eps, axis_name,
                                       relu)
    return (z, (mean, var)), (x, w, scale, bias, y, mean, inv)


def _conv_block_bwd(eps, axis_name, relu, res, cts):
    x, w, scale, bias, y, mean, inv = res
    dz, (dmean, dvar) = cts
    dbeta, dgamma = _bn_act_sums(dz, y, mean, inv, scale, bias, relu)
    # Sync-BN backward needs the GLOBAL reductions and row count in the
    # dy formula; the RETURNED dscale/dbias stay per-rank partials (the
    # framework's later gradient psum makes them global, exactly like
    # unfused autodiff). dmean/dvar cotangents (zero in normal training
    # — optax treats batch stats as state — but exact when a loss does
    # use the aux stats) fold into the kernel's per-channel vectors.
    k = _axis_size(axis_name)
    db_g = dbeta if axis_name is None else jax.lax.psum(dbeta, axis_name)
    dg_g = dgamma if axis_name is None else jax.lax.psum(dgamma, axis_name)
    dm_g = dmean if axis_name is None else jax.lax.psum(dmean, axis_name)
    dv_g = dvar if axis_name is None else jax.lax.psum(dvar, axis_name)
    dx, dw = conv1x1_bn_act_bwd_fused(
        dz, y, x, w, scale, bias, mean, inv, db_g, dg_g,
        dmean=dm_g, dvar=dv_g, count=dz.shape[0] * k, relu=relu)
    return (dx, dw.astype(w.dtype), dgamma.astype(scale.dtype),
            dbeta.astype(bias.dtype))


conv1x1_bn_act.defvjp(_conv_block_fwd, _conv_block_bwd)


def conv1x1_bn_relu(x, w, scale, bias, eps=1e-5, axis_name=None):
    """The headline fused block: z = relu(batch_norm(x @ w))."""
    return conv1x1_bn_act(x, w, scale, bias, eps, axis_name, True)


def conv1x1_bn_act_nhwc(x, w, scale, bias, eps=1e-5, axis_name=None,
                        relu=True):
    """NHWC convenience wrapper: x (N, H, W, Cin), w (1, 1, Cin, Cout)
    or (Cin, Cout). Returns (z in NHWC, (mean, var))."""
    n, h, wd, cin = x.shape
    w2 = w.reshape(w.shape[-2], w.shape[-1])
    z, stats = conv1x1_bn_act(x.reshape(n * h * wd, cin), w2, scale,
                              bias, eps, axis_name, relu)
    return z.reshape(n, h, wd, -1), stats
