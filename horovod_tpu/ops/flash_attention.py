"""Flash attention as a Pallas TPU kernel (forward + backward).

The hot op of the long-context story: exact attention with online softmax,
never materializing the (S, S) score matrix — O(S) HBM traffic per row
block instead of O(S²). This is the single-device building block under
`parallel/ring_attention.py` (which shards S over the `sp` axis and rides
ICI); here the block loop runs in VMEM with the MXU doing qkᵀ and pv.

No reference counterpart exists (the reference is a DP framework with no
attention ops); the kernel follows the standard FlashAttention-2
recurrence. Row statistics ride in lane-replicated (block_q, 128) buffers
to satisfy the TPU's (8, 128) tiling (same convention as stock Pallas TPU
kernels). Numerics are validated against
`parallel.ring_attention.blockwise_attention_reference` (forward AND
gradients) in tests/test_flash_attention.py.

Falls back to interpret mode off-TPU so the same code path is testable on
the CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # lane-replication width for row statistics


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _rep(x):
    """Replicate a (bq, 1) column across the 128-lane minor dim."""
    return jnp.broadcast_to(x, (x.shape[0], _LANES))


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: blocks strictly above the diagonal contribute nothing.
    needed = jnp.logical_or(
        jnp.logical_not(causal),
        ik * block_k <= iq * block_q + block_q - 1)

    @pl.when(needed)
    def _step():
        q = q_ref[0].astype(jnp.float32)              # (bq, dh)
        k = k_ref[0].astype(jnp.float32)              # (bk, dh)
        v = v_ref[0].astype(jnp.float32)              # (bk, dh)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        if causal:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:, :1]                          # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)                # (bq, 1)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = _rep(m_new)
        l_ref[:] = _rep(l_new)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(safe_l)   # (bq, 1)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    bh, sq, dh = q.shape
    sk = k.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            # lse rides a (bh, S, 1) array: the (block_q, 1) block is legal
            # tiling (minor dim equals the array dim) and 128x smaller than
            # lane-replicating a VJP residual that lives fwd->bwd.
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, dh), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# Backward
# --------------------------------------------------------------------------

def _attn_block(q_ref, k_ref, lse_ref, *, scale, causal,
                iq, ik, block_q, block_k):
    """Recompute the probability block p = exp(s·scale − lse)."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)
    return jnp.exp(s - lse_ref[0][:, :1])


def _delta_block(o_ref, do_ref):
    """delta = rowsum(do ∘ o): the softmax-jacobian correction term."""
    return jnp.sum(do_ref[0].astype(jnp.float32)
                   * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True)


def _bwd_dkdv_kernel(*refs, scale, causal, block_q, block_k, has_dlse):
    if has_dlse:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dlse_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        dlse_ref = None
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    needed = jnp.logical_or(
        jnp.logical_not(causal),
        iq * block_q + block_q - 1 >= ik * block_k)

    @pl.when(needed)
    def _step():
        p = _attn_block(q_ref, k_ref, lse_ref, scale=scale, causal=causal,
                        iq=iq, ik=ik, block_q=block_q, block_k=block_k)
        do = do_ref[0].astype(jnp.float32)             # (bq, dh)
        v = v_ref[0].astype(jnp.float32)               # (bk, dh)
        delta = _delta_block(o_ref, do_ref)            # (bq, 1)
        # dv += pᵀ · do
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # ds = p ∘ (do·vᵀ − delta [+ dlse]) · scale ;  dk += dsᵀ · q
        # (dlse: ∂lse/∂s = p — the lse output is differentiable so block
        # results can be merged OUTSIDE the kernel, e.g. per ring hop.)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        bracket = dp - delta
        if dlse_ref is not None:
            bracket = bracket + dlse_ref[0][:, :1]
        ds = p * bracket * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, has_dlse):
    if has_dlse:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dlse_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dq_ref, dq_acc) = refs
        dlse_ref = None
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    needed = jnp.logical_or(
        jnp.logical_not(causal),
        ik * block_k <= iq * block_q + block_q - 1)

    @pl.when(needed)
    def _step():
        p = _attn_block(q_ref, k_ref, lse_ref, scale=scale, causal=causal,
                        iq=iq, ik=ik, block_q=block_q, block_k=block_k)
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        delta = _delta_block(o_ref, do_ref)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        bracket = dp - delta
        if dlse_ref is not None:
            bracket = bracket + dlse_ref[0][:, :1]
        ds = p * bracket * scale                       # (bq, bk)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd(q, k, v, o, lse, do, dlse, causal, scale, block_q, block_k):
    """dlse=None compiles lse-cotangent-free kernels (the plain
    flash_attention path never pays for a zero dlse buffer)."""
    bh, sq, dh = q.shape
    sk = k.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    has_dlse = dlse is not None

    q_by_j = pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, j, 0))
    kv_by_i = pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0))
    lse_by_j = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    in_specs = [q_by_j, kv_by_i, kv_by_i, q_by_j, q_by_j, lse_by_j]
    operands = [q, k, v, o, do, lse]
    if has_dlse:
        in_specs.append(lse_by_j)
        operands.append(dlse)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_dlse=has_dlse),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, sk, dh), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dh), jnp.float32),
            pltpu.VMEM((block_k, dh), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)

    q_by_i = pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0))
    kv_by_j = pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0))
    lse_by_i = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    in_specs = [q_by_i, kv_by_j, kv_by_j, q_by_i, q_by_i, lse_by_i]
    operands = [q, k, v, o, do, lse]
    if has_dlse:
        in_specs.append(lse_by_i)
        operands.append(dlse)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_dlse=has_dlse),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=_interpret(),
    )(*operands)
    return dq, dk, dv


# --------------------------------------------------------------------------
# Public API with custom VJP
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_chunk(q, k, v, causal, scale, block_q, block_k):
    """Differentiable (o, lse) pair — lse cotangents feed the ds term so
    block results can be merged OUTSIDE the kernel (per ring hop)."""
    return _fwd(q, k, v, causal, scale, block_q, block_k)


def _flash_chunk_fwd(q, k, v, causal, scale, block_q, block_k):
    o, lse = _fwd(q, k, v, causal, scale, block_q, block_k)
    return (o, lse), (q, k, v, o, lse)


def _flash_chunk_bwd(causal, scale, block_q, block_k, res, cot):
    q, k, v, o, lse = res
    do, dlse = cot
    return _bwd(q, k, v, o, lse, do, dlse, causal, scale, block_q, block_k)


_flash_chunk.defvjp(_flash_chunk_fwd, _flash_chunk_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    o, _ = _fwd(q, k, v, causal, scale, block_q, block_k)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    o, lse = _fwd(q, k, v, causal, scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    # dlse=None: the o-only API never pays for a zero lse cotangent.
    return _bwd(q, k, v, o, lse, do, None, causal, scale, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def can_tile(Sq: int, Sk: Optional[int] = None,
             causal: bool = False) -> bool:
    """Public tileability predicate: True when the kernel path handles
    these sequence lengths (callers like ring_attention auto-dispatch on
    this instead of re-deriving the kernel's constraints)."""
    if _auto_block(Sq) is None:
        return False
    if Sk is not None and _auto_block(Sk) is None:
        return False
    if causal and Sk is not None and Sq != Sk:
        return False
    return True


def flash_attention_chunk(q, k, v, causal: bool = False,
                          scale: Optional[float] = None,
                          block_q: Optional[int] = None,
                          block_k: Optional[int] = None):
    """One attention chunk with mergeable outputs.

    q: (B, H, Sq, dh); k, v: (B, H, Sk, dh) — Sq and Sk may differ (ring
    hops attend local queries against a circulating K/V block). Returns
    (o, lse) with o: (B, H, Sq, dh) normalized within the chunk and
    lse: (B, H, Sq) float32; merge chunks with
    L = logaddexp(L1, L2), o = e^{L1−L}·o1 + e^{L2−L}·o2. Differentiable
    through BOTH outputs.
    """
    B, H, Sq, dh = q.shape
    Sk = k.shape[2]
    if scale is None:
        scale = dh ** -0.5
    bq = min(block_q, Sq) if block_q else _auto_block(Sq)
    bk = min(block_k, Sk) if block_k else _auto_block(Sk)
    if (bq is None or bk is None or Sq % bq or Sk % bk
            or (causal and Sq != Sk)):
        raise ValueError(
            f"flash_attention_chunk cannot tile Sq={Sq}, Sk={Sk} "
            f"(blocks {bq}, {bk}); causal chunks must be square")
    o, lse = _flash_chunk(q.reshape(B * H, Sq, dh),
                          k.reshape(B * H, Sk, dh),
                          v.reshape(B * H, Sk, dh),
                          causal, float(scale), bq, bk)
    return (o.reshape(B, H, Sq, dh),
            lse[..., 0].reshape(B, H, Sq))  # drop the unit minor dim


def _auto_block(S: int) -> Optional[int]:
    """Largest legal block for a sequence length (measured on v5e: big
    blocks win — 1024² blocks are ~2x naive XLA attention at S=8192;
    128² blocks lose to grid overhead)."""
    if S <= 1024:
        return S  # block == full dim is always a legal TPU tiling
    for b in (1024, 512, 256, 128):
        if S % b == 0:
            return b
    return None


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None) -> jax.Array:
    """Exact attention via the Pallas flash kernel.

    q, k, v: (B, H, S, dh). Returns (B, H, S, dh). Differentiable
    (custom VJP with flash backward kernels). Block sizes default to a
    measured heuristic; falls back to the score-materializing reference
    for shapes the kernel cannot tile.
    """
    B, H, S, dh = q.shape
    if scale is None:
        scale = dh ** -0.5
    block_q = min(block_q, S) if block_q else _auto_block(S)
    block_k = min(block_k, S) if block_k else _auto_block(S)
    if (block_q is None or block_k is None
            or S % block_q or S % block_k):
        from horovod_tpu.parallel.ring_attention import (
            blockwise_attention_reference)
        return blockwise_attention_reference(q, k, v, causal=causal,
                                             scale=scale)
    qf = q.reshape(B * H, S, dh)
    kf = k.reshape(B * H, S, dh)
    vf = v.reshape(B * H, S, dh)
    o = _flash(qf, kf, vf, causal, float(scale), block_q, block_k)
    return o.reshape(B, H, S, dh)
