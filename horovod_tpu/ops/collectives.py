"""Eager named-tensor collectives, compiled onto the TPU mesh.

This module replaces the reference's entire L1-L3 stack — EnqueueTensor*
(horovod/common/operations.cc:1408-2058), the controller negotiation
(horovod/common/controller.cc:74), and the NCCL/MPI/Gloo op implementations
(horovod/common/ops/*) — with a TPU-native design:

* Each collective is a `jit(shard_map(...))` program over the process set's
  device mesh. XLA lowers `lax.psum`/`all_gather`/`psum_scatter`/`all_to_all`
  to ICI/DCN collectives directly; there is no runtime negotiation because
  readiness is implicit in the dataflow of a compiled program.

* The *response cache* (horovod/common/response_cache.cc) becomes a compiled-
  executable cache: the first call with a given signature pays a compile,
  every subsequent call is a cache hit that launches immediately. Capacity is
  governed by the same HOROVOD_CACHE_CAPACITY knob.

* The *fusion buffer* (horovod/common/fusion_buffer_manager.cc, 64-128MB
  threshold) becomes trace-time bucketing for grouped ops: tensors are
  flattened, concatenated into ≤-threshold buckets, reduced with one psum
  per bucket, and split back — all inside one XLA program, so the "memcpy
  into fusion buffer" is fused by the compiler instead of a batched D2D
  kernel (cuda_kernels.cu).

* JAX's async dispatch provides the handle/synchronize model natively
  (reference: horovod/torch/handle_manager.h) — returned arrays are futures;
  `synchronize()` is `block_until_ready`.

Per-rank tensor convention: with one process per chip (launcher default),
`allreduce(x)` takes this rank's local tensor. Under a single controller
owning L>1 devices (tests: 8-device CPU mesh; or a whole host), per-rank
tensors are stacked along a leading axis of length L, and results come back
stacked the same way (sharded over the mesh, so they stay distributed).
"""

from __future__ import annotations

import collections
import functools
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.common import types as T
from horovod_tpu.common.exceptions import (DuplicateNameError,
                                           HorovodInternalError,
                                           HorovodTpuError)
from horovod_tpu.core import topology
from horovod_tpu.core.process_sets import ProcessSet, global_process_set
from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import tracing as _tracing
from horovod_tpu.profiler import perfscope as _pscope

_AXIS = "hvd"

# Runtime (not trace-time) failure types: a dead peer / aborted transport
# surfaces as one of these from XLA or the distributed client.
try:
    _COMM_ERRORS: tuple = (jax.errors.JaxRuntimeError,)
except AttributeError:  # older jax spelling
    from jax._src.lib import xla_client as _xc
    _COMM_ERRORS = (_xc.XlaRuntimeError,)

# A dead peer does NOT always surface as a typed runtime error: the CPU
# collectives backend raises plain ValueError("UNKNOWN: Gloo all-reduce
# failed: ... Connection closed by peer ..."), and the coordination client
# has its own wording. Message markers classify those.
_COMM_FAILURE_MARKERS = (
    "connection closed by peer", "connection reset", "connection refused",
    "gloo", "all-reduce failed", "broken pipe", "socket",
    "coordination service", "heartbeat", "task is unhealthy",
    "peer is unavailable", "deadline exceeded",
)


def is_comm_failure(e: BaseException) -> bool:
    """True if `e` looks like a transport/peer failure rather than user
    error — the trigger for HorovodInternalError in elastic mode."""
    if isinstance(e, _COMM_ERRORS):
        return True
    msg = str(e).lower()
    return any(m in msg for m in _COMM_FAILURE_MARKERS)


def _restore_grace_active(first_start: float, shutdown_sec: float) -> bool:
    """True while a peer's checkpoint restore should extend the stall
    deadline: the ckpt restore signal is fresh AND the total wait has
    not exhausted shutdown + HOROVOD_CKPT_RESTORE_GRACE_MAX. Probed at
    most once per armed deadline window (each re-arm buys a full
    shutdown_sec before the next probe), so the KV cost is negligible.
    Guarded: a broken ckpt import must not change watchdog behavior."""
    import time as _time
    try:
        from horovod_tpu.ckpt import resume as _ckpt_resume
        if _time.monotonic() - first_start >= \
                shutdown_sec + _ckpt_resume.grace_max_seconds():
            return False
        return _ckpt_resume.peer_restore_active()
    except Exception:
        return False


class StallWatchdog:
    """Python-side watchdog over a blocking collective wait.

    Built on the StallInspector bindings (native/__init__.py:247, or the
    pure-Python fallback common/resilience.py:PyStallInspector): the wait
    is registered via submit()/done() so the global watcher names it in
    warnings; guard() additionally BOUNDS the wait — it warns once at
    `warn_sec` and at `shutdown_sec` raises HorovodInternalError in the
    waiting thread, so the elastic retry loop (restore → re-rendezvous)
    owns recovery instead of a silent hang (or the non-elastic os._exit).

    Mechanics: `jax.block_until_ready` cannot be interrupted from Python,
    so the blocking call runs in a daemon thread and the caller polls its
    completion. On a shutdown raise the daemon thread stays blocked until
    the elastic reset tears the backend down (or the process exits) — it
    never outlives recovery. The thread is spawned per call on purpose:
    a reusable executor thread would be abandoned mid-block by exactly
    the timeouts this guard exists for, forcing respawn logic that
    degenerates to per-call spawn; the ~100 us spawn cost is noise next
    to a cross-process collective, and only elastic mode pays it.
    """

    def __init__(self, inspector, warn_sec: float, shutdown_sec: float,
                 poll_interval: float = 0.05):
        self.inspector = inspector
        self.warn_sec = warn_sec
        self.shutdown_sec = shutdown_sec
        self.poll_interval = poll_interval

    def guard(self, name: str, fn: Callable[[], Any]) -> Any:
        import time as _time

        from horovod_tpu.common.hvd_logging import get_logger

        self.inspector.submit(name)
        box: dict = {}
        finished = threading.Event()

        def run() -> None:
            try:
                box["value"] = fn()
            except BaseException as e:  # delivered to the caller below
                box["error"] = e
            finally:
                finished.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"hvd-guarded-wait-{name}")
        start = _time.monotonic()
        first_start = start
        t.start()
        warned = False
        try:
            while not finished.wait(self.poll_interval):
                age = _time.monotonic() - start
                if not warned and age >= self.warn_sec:
                    warned = True
                    _mx()["stall_warn"].labels(source="watchdog").inc()
                    _flight.record(
                        "stall", f"collective '{name}' stalled for "
                        f"{age:.1f}s (warning threshold "
                        f"{self.warn_sec:.0f}s)")
                    get_logger().warning(
                        "collective '%s' stalled for %.1fs "
                        "(HOROVOD_STALL_CHECK_TIME_SECONDS=%.0f)",
                        name, age, self.warn_sec)
                if self.shutdown_sec > 0 and age >= self.shutdown_sec \
                        and _restore_grace_active(first_start,
                                                 self.shutdown_sec):
                    # A rank is mid-checkpoint-restore (ckpt/resume
                    # heartbeat): its peers legitimately wait longer
                    # than the stall budget. Re-arm the deadline from
                    # NOW — i.e. from restore time, not round start —
                    # bounded overall by
                    # HOROVOD_CKPT_RESTORE_GRACE_MAX so a wedged
                    # restorer still cannot hang the job forever.
                    start = _time.monotonic()
                    _flight.record(
                        "ckpt", f"stall deadline re-armed for "
                        f"'{name}': peer checkpoint restore in "
                        f"progress (waited "
                        f"{start - first_start:.1f}s total)")
                    get_logger().info(
                        "collective '%s': stall deadline re-armed — "
                        "a peer's checkpoint restore is in progress",
                        name)
                    continue
                if self.shutdown_sec > 0 and age >= self.shutdown_sec:
                    stalled, _ = self.inspector.check()
                    _mx()["stall_shut"].inc()
                    # With HOROVOD_CHECK_COLLECTIVES=1 the fingerprint
                    # verifier turns the bare timeout into a diagnosis:
                    # last agreed call index + first divergent call
                    # (analysis/verifier.py stall_context). Guarded:
                    # the stall report must survive a broken analysis
                    # import.
                    try:
                        from horovod_tpu.analysis import verifier as _vf
                        fp_context = _vf.stall_context()
                    except Exception:
                        fp_context = ""
                    # The shutdown raise is exactly the moment the
                    # flight recorder exists for: every rank's ring
                    # still holds the calls leading into the hang.
                    try:
                        _flight.record(
                            "stall", f"collective '{name}' stalled past "
                            f"shutdown window {self.shutdown_sec:.0f}s")
                        _flight.dump("stall_watchdog")
                        flight_hint = _flight.dump_hint()
                    except Exception:
                        flight_hint = ""
                    raise HorovodInternalError(
                        f"collective '{name}' stalled past "
                        f"HOROVOD_STALL_SHUTDOWN_TIME_SECONDS="
                        f"{self.shutdown_sec:.0f}s"
                        + (f" (outstanding: {', '.join(stalled)})"
                           if stalled else "")
                        + fp_context + flight_hint)
            if "error" in box:
                raise box["error"]
            return box["value"]
        finally:
            self.inspector.done(name)


def _guarded_wait(name: str, fn: Callable[[], Any]) -> Any:
    """Run a blocking host-side wait under the stall inspector.

    Elastic mode with a shutdown window: the StallWatchdog bounds the wait
    (HorovodInternalError within shutdown_sec). Otherwise: plain call with
    submit/done bookkeeping, so the topology watcher can still warn (and,
    non-elastic, enforce its own shutdown via os._exit).
    """
    st = topology.raw_state()
    si = st.stall_inspector
    cfg = st.config
    if si is None or not cfg.elastic or cfg.stall_shutdown_seconds <= 0:
        _stall_submit(name)
        try:
            return fn()
        finally:
            _stall_done(name)
    return StallWatchdog(si, cfg.stall_warning_seconds,
                         cfg.stall_shutdown_seconds).guard(name, fn)


def _execute(fn: Callable, *args):
    """Run a compiled collective with failure propagation.

    Reference: op failures flow error Status → entry callbacks → frontends
    raise HorovodInternalError (SURVEY §5; common/operations.cc callbacks,
    elastic NCCL abort in nccl_operations.cc). Here: in elastic mode we
    force completion so a peer death surfaces HERE — inside the elastic
    retry scope — as HorovodInternalError, instead of as a raw
    XlaRuntimeError at some later readback the retry loop can't catch.
    The forced wait runs under the stall watchdog, so a PEER THAT NEVER
    ARRIVES (as opposed to one that dies loudly) also surfaces as
    HorovodInternalError within the shutdown window instead of hanging.
    Non-elastic runs keep fully async dispatch and raw errors.
    """
    elastic = topology.raw_state().config.elastic
    try:
        if elastic:
            # The guard must cover DISPATCH too: CPU/gloo executes the
            # collective synchronously inside fn(*args), so a missing
            # peer blocks there — before any block_until_ready.
            return _guarded_wait(
                "collective", lambda: jax.block_until_ready(fn(*args)))
        return fn(*args)
    except Exception as e:
        if elastic and is_comm_failure(e):
            # Dump before converting: the elastic retry loop is about
            # to tear the backend down, and this ring holds the calls
            # leading into the peer failure.
            _flight.record("error", f"collective execution failed: {e}")
            _flight.dump("internal_error")
            raise HorovodInternalError(
                f"collective execution failed: {e}") from e
        raise


# --------------------------------------------------------------------------
# Compiled-collective cache (the response-cache analog)
# --------------------------------------------------------------------------

class _CompiledCache:
    """LRU cache of compiled collective executables.

    Reference analog: ResponseCache (horovod/common/response_cache.cc:506) —
    there a hit skips the coordinator round-trip; here a hit skips tracing and
    compilation entirely.
    """

    def __init__(self) -> None:
        self._cache: "collections.OrderedDict[Any, Callable]" = \
            collections.OrderedDict()

    def _capacity(self) -> int:
        return topology.state().config.cache_capacity

    def get_or_build(self, key: Any, builder: Callable[[], Callable]) -> Callable:
        if key in self._cache:
            self._cache.move_to_end(key)
            _mx()["cache"].labels(event="hit").inc()
            return self._cache[key]
        _mx()["cache"].labels(event="miss").inc()
        fn = self._compile_timed(builder(), str(key[0]))
        self._cache[key] = fn
        cap = self._capacity()
        while cap > 0 and len(self._cache) > cap:
            self._cache.popitem(last=False)
        return fn

    @staticmethod
    def _compile_timed(fn: Callable, tag: str) -> Callable:
        """Record the cache miss's trace+compile as a COMPILE timeline span
        (reference: the timeline's per-tensor activity spans, timeline.cc).
        jit defers compilation to the first invocation, so that call — not
        the builder — is what gets timed."""
        first = [True]

        def wrapped(*args):
            if first[0]:
                first[0] = False
                tl = topology.state().timeline
                if tl is not None:
                    tl.span_begin(tag, "COMPILE")
                t0 = time.perf_counter()
                try:
                    return fn(*args)
                finally:
                    # Step-phase attribution (profiler/perfscope.py):
                    # the cache miss's trace+compile is `compile` time,
                    # not whatever phase the step happened to be in.
                    _pscope.attribute("compile",
                                      time.perf_counter() - t0)
                    if tl is not None:
                        tl.span_end(tag, "COMPILE")
            return fn(*args)

        return wrapped

    def clear(self) -> None:
        self._cache.clear()


_cache = _CompiledCache()


def clear_compiled_cache() -> None:
    _cache.clear()


# --------------------------------------------------------------------------
# Per-rank tensor plumbing
# --------------------------------------------------------------------------

def _resolve_ps(process_set: Optional[ProcessSet]) -> ProcessSet:
    ps = process_set if process_set is not None else global_process_set
    if ps.mesh is None:
        raise HorovodTpuError(
            f"process set {ps} is not registered; call hvd.add_process_set")
    return ps


def pidx_of() -> int:
    return jax.process_index()


def _local_member_count(ps: ProcessSet) -> int:
    """How many of this process's devices are in the set."""
    pidx = jax.process_index()
    return sum(1 for d in ps.mesh.devices.flat if d.process_index == pidx)


def _is_stacked(x: Any, ps: ProcessSet, L: int) -> bool:
    if L <= 1:
        return False
    shape = np.shape(x)
    return len(shape) >= 1 and shape[0] == L


def _to_global(x: Any, ps: ProcessSet) -> Tuple[jax.Array, bool]:
    """Lift a local (or locally-stacked) per-rank tensor to a global array
    sharded one-row-per-rank over the set's mesh.

    Returns (global_array, was_stacked). NOTE: the single-process lifting
    rule (stacked pass-through vs broadcast to (L, *shape)) is mirrored
    inside _lift_group's compiled batch lift — change them TOGETHER.
    """
    mesh = ps.mesh
    assert mesh is not None
    L = _local_member_count(ps)
    sharding = NamedSharding(mesh, P(_AXIS))
    stacked = _is_stacked(x, ps, L)
    if isinstance(x, jax.Array) and x.sharding == sharding and stacked:
        return x, True
    arr = jnp.asarray(x)
    T.check_supported_dtype(arr.dtype)
    if stacked:
        local = arr
    else:
        # A plain tensor is "this rank's tensor". When this process owns
        # L > 1 slots (single controller over many devices), replicate it to
        # every local slot — all emulated ranks contribute the same value.
        local = jnp.broadcast_to(arr[None], (max(L, 1),) + arr.shape)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding), stacked
    # Multi-process: assemble the global array from per-slot ON-DEVICE
    # shards — no device→host→device round trip on the hot path.
    k = ps.size()
    global_shape = (k,) + tuple(local.shape[1:])
    my_devs = [d for d in mesh.devices.flat if d.process_index == pidx_of()]
    shards = [jax.device_put(local[i:i + 1], d)
              for i, d in enumerate(my_devs)]
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards), stacked


def _lift_group(tensors: Sequence[Any], ps: ProcessSet):
    """Lift a group of per-rank tensors to their global form — the one
    entry point for grouped ops.

    Single-process, for eligible tensors: ONE compiled program raises
    the whole group to its row-sharded form (out_shardings does the
    placement), collapsing 2N+1 dispatches to ~2 — the dominant cost of
    eager grouped ops on remote/tunneled devices. COMMITTED arrays
    (outputs of previous collectives via _from_global, or user
    device_put-pinned inputs) cannot enter a jit whose out_shardings
    spans other devices ("incompatible devices"), so they take the
    per-tensor _to_global path, as does multi-process mode."""
    if jax.process_count() != 1:
        pairs = [_to_global(t, ps) for t in tensors]
        return [p[0] for p in pairs], [p[1] for p in pairs]
    mesh = ps.mesh
    assert mesh is not None
    L = _local_member_count(ps)
    sharding = NamedSharding(mesh, P(_AXIS))
    flags = []
    need: List[int] = []
    outs: List[Any] = [None] * len(tensors)
    arrs: List[Any] = [None] * len(tensors)
    for i, t in enumerate(tensors):
        stacked = _is_stacked(t, ps, L)
        flags.append(stacked)
        if isinstance(t, jax.Array):
            if t.sharding == sharding and stacked:
                outs[i] = t
                continue
            if getattr(t, "committed", getattr(t, "_committed", True)):
                outs[i] = _to_global(t, ps)[0]
                continue
        a = t if isinstance(t, (jax.Array, np.ndarray)) else jnp.asarray(t)
        T.check_supported_dtype(np.dtype(a.dtype))
        arrs[i] = a
        need.append(i)
    if need:
        key = ("lift", tuple((tuple(np.shape(arrs[i])),
                              str(arrs[i].dtype), flags[i])
                             for i in need), L, ps.cache_token)
        sub_flags = [flags[i] for i in need]

        def build() -> Callable:
            # MIRROR of _to_global's single-process lifting rule (stacked
            # pass-through vs broadcast to (L, *shape)) — keep in lockstep
            def lift(*xs):
                res = []
                for x, st in zip(xs, sub_flags):
                    res.append(x if st else jnp.broadcast_to(
                        x[None], (max(L, 1),) + x.shape))
                return tuple(res)
            return jax.jit(lift, out_shardings=(sharding,) * len(need))

        fn = _cache.get_or_build(key, build)
        lifted = fn(*[arrs[i] for i in need])
        for i, g in zip(need, lifted):
            outs[i] = g
    return outs, flags


def _from_global(y: jax.Array, stacked: bool) -> jax.Array:
    """Return the caller-facing view of a stacked global result."""
    if stacked:
        return y
    shards = y.addressable_shards
    assert shards, "result has no addressable shards on this process"
    shard = min(shards, key=lambda s: s.index[0].start or 0)
    return shard.data[0]


# --------------------------------------------------------------------------
# Reduction kernels (run inside shard_map; block shape (1, *tensor_shape))
# --------------------------------------------------------------------------

def _apply_reduce(block: jax.Array, op: T.ReduceOp, k: int,
                  prescale: float, postscale: float) -> jax.Array:
    """One rank's fused reduce body. block: (1, *shape)."""
    x = block
    if prescale != 1.0:
        x = x * jnp.asarray(prescale, x.dtype)
    if op in (T.ReduceOp.SUM, T.ReduceOp.AVERAGE):
        y = lax.psum(x, _AXIS)
        if op == T.ReduceOp.AVERAGE:
            if jnp.issubdtype(y.dtype, jnp.integer):
                y = y // jnp.asarray(k, y.dtype)
            else:
                y = y / jnp.asarray(k, y.dtype)
    elif op == T.ReduceOp.MIN:
        y = lax.pmin(x, _AXIS)
    elif op == T.ReduceOp.MAX:
        y = lax.pmax(x, _AXIS)
    elif op == T.ReduceOp.PRODUCT:
        g = lax.all_gather(x, _AXIS, axis=0)  # (k, 1, *shape)
        y = jnp.prod(g, axis=0)
    elif op == T.ReduceOp.ADASUM:
        from horovod_tpu.ops import adasum as adasum_mod
        y = adasum_mod.adasum_reduce_block(
            x, _AXIS, k, halving=topology.state().config.adasum_halving)
    else:
        raise HorovodTpuError(f"unsupported reduce op {op}")
    if postscale != 1.0:
        y = y * jnp.asarray(postscale, y.dtype)
    return y


def _replicated_reduce_one(x: jax.Array, op: T.ReduceOp, k: int,
                           prescale: float, postscale: float) -> jax.Array:
    """_apply_reduce's algebra when all k contributions are IDENTICAL.

    Single-controller mode with a non-stacked input means every emulated
    rank contributes the same tensor, so the collective has a closed
    form: sum = k·x, average/min/max = x, product = x^k. Computing it
    directly skips the per-tensor lift (broadcast + device_put — two
    dispatches EACH, which dominates eager-optimizer steps on
    remote/tunneled devices) and the fused psum program entirely.
    Semantics match _apply_reduce exactly, including integer-average
    flooring and pre/post scaling order.
    """
    if prescale != 1.0:
        x = x * jnp.asarray(prescale, x.dtype)
    if op == T.ReduceOp.SUM:
        y = x * jnp.asarray(k, x.dtype)
    elif op == T.ReduceOp.AVERAGE:
        if jnp.issubdtype(x.dtype, jnp.integer):
            y = (x * jnp.asarray(k, x.dtype)) // jnp.asarray(k, x.dtype)
        else:
            y = x
    elif op in (T.ReduceOp.MIN, T.ReduceOp.MAX):
        y = x
    elif op == T.ReduceOp.PRODUCT:
        y = x ** k
    elif op == T.ReduceOp.ADASUM:
        # Adasum of identical vectors is the vector itself: combine(a,a)
        # has dot = |a|^2 = na = nb, so a·(1 - dot/(2na)) + a·(1 -
        # dot/(2nb)) = a — at every VHDD level (adasum.h:195's combine is
        # idempotent on equal inputs, and the non-pow2 fold likewise).
        y = x
    else:  # pragma: no cover - all ops handled above
        raise HorovodTpuError(f"unsupported replicated reduce {op}")
    if postscale != 1.0:
        y = y * jnp.asarray(postscale, y.dtype)
    return y


def _replicated_fast_ok(ps: ProcessSet, rop: T.ReduceOp, hm,
                        tensors) -> bool:
    """Eligibility for the identical-contributions closed form: one
    process (multi-process inputs genuinely differ per rank), no
    hierarchical mesh, and no stacked per-slot inputs. Adasum qualifies
    too — its combine is idempotent on identical inputs (see
    _replicated_reduce_one) — which matters because the full path's
    per-tensor lift dominates eager Adasum optimizer steps.
    HOROVOD_NO_REPLICATED_FAST=1 forces the full collective machinery
    (used by benchmarks that measure it)."""
    from horovod_tpu.common.config import _env_bool

    if _env_bool("HOROVOD_NO_REPLICATED_FAST"):
        return False
    if jax.process_count() != 1 or hm is not None:
        return False
    L = _local_member_count(ps)
    return not any(_is_stacked(t, ps, L) for t in tensors)


def _builder_allreduce(mesh: Mesh, k: int, op: T.ReduceOp,
                       prescale: float, postscale: float,
                       num_tensors: int, donate: bool) -> Callable:
    def body(*blocks):
        outs = [_apply_reduce(b, op, k, prescale, postscale) for b in blocks]
        return tuple(outs) if num_tensors > 1 else outs[0]

    specs_in = (P(_AXIS),) * num_tensors
    specs_out = (P(_AXIS),) * num_tensors if num_tensors > 1 else P(_AXIS)
    fn = jax.shard_map(body, mesh=mesh, in_specs=specs_in,
                       out_specs=specs_out, check_vma=False)
    donate_argnums = tuple(range(num_tensors)) if donate else ()
    return jax.jit(fn, donate_argnums=donate_argnums)


# --------------------------------------------------------------------------
# Hierarchical (ici × dcn) variants
# --------------------------------------------------------------------------

_HIER_SPEC = P(("dcn", "ici"))  # dim 0 sharded over both axes, dcn-major —
# row r lands on the same device as the flat P("hvd") layout, so inputs
# lifted by _to_global need no resharding.


def _hier_usable(ps: ProcessSet) -> Optional[Mesh]:
    """The ("dcn","ici") mesh if hierarchical mode applies to this set."""
    if ps.ranks is not None:  # sub-sets keep the flat path
        return None
    return topology.state().hier_mesh


def _apply_reduce_hier(block: jax.Array, op: T.ReduceOp, k: int,
                       k_ici: int, prescale: float,
                       postscale: float) -> jax.Array:
    """ReduceScatter over ici → Allreduce over dcn → Allgather over ici.

    The reference's NCCLHierarchicalAllreduce structure
    (nccl_operations.cc:308: intra-node ncclReduceScatter → cross-node
    MPI_Allreduce → intra-node ncclAllgather), expressed as XLA
    collectives over the two mesh axes: only 1/k_ici of the payload
    crosses the slow dcn axis per rank.
    """
    x = block[0]
    if prescale != 1.0:
        x = x * jnp.asarray(prescale, x.dtype)
    v = x.reshape(-1)
    n = v.shape[0]
    pad = -n % k_ici
    if pad:
        v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
    s = lax.psum_scatter(v, "ici", scatter_dimension=0, tiled=True)
    s = lax.psum(s, "dcn")
    v = lax.all_gather(s, "ici", axis=0, tiled=True)
    if pad:
        v = v[:n]
    y = v.reshape(x.shape)
    if op == T.ReduceOp.AVERAGE:
        if jnp.issubdtype(y.dtype, jnp.integer):
            y = y // jnp.asarray(k, y.dtype)
        else:
            y = y / jnp.asarray(k, y.dtype)
    if postscale != 1.0:
        y = y * jnp.asarray(postscale, y.dtype)
    return y[None]


def _builder_allreduce_hier(hmesh: Mesh, k: int, op: T.ReduceOp,
                            prescale: float, postscale: float,
                            donate: bool) -> Callable:
    k_ici = hmesh.shape["ici"]

    def body(block):
        return _apply_reduce_hier(block, op, k, k_ici, prescale, postscale)

    fn = jax.shard_map(body, mesh=hmesh, in_specs=_HIER_SPEC,
                       out_specs=_HIER_SPEC, check_vma=False)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


# --------------------------------------------------------------------------
# Public eager API
# --------------------------------------------------------------------------

def allreduce(tensor: Any,
              average: Optional[bool] = None,
              name: Optional[str] = None,
              op: Any = None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None,
              donate: bool = False) -> jax.Array:
    """Reduce a per-rank tensor across the process set.

    Reference API: hvd.allreduce (horovod/torch/mpi_ops.py:260,
    EnqueueTensorAllreduce operations.cc:1408). `average`/`op` semantics
    match: default AVERAGE.
    """
    ps = _resolve_ps(process_set)
    cfg = topology.state().config
    rop = _normalize_op(average, op)
    donate = donate or cfg.donate_buffers
    k = ps.size()
    hm = _hier_usable(ps) if (cfg.hierarchical_allreduce
                              and rop in (T.ReduceOp.SUM,
                                          T.ReduceOp.AVERAGE)) else None
    if _replicated_fast_ok(ps, rop, hm, (tensor,)):
        shape = tuple(np.shape(tensor))
        # np.result_type on a LIST parses it as a dtype spec (numpy 2.x);
        # np.asarray handles lists/scalars/arrays uniformly.
        dtype = tensor.dtype if hasattr(tensor, "dtype") \
            else np.asarray(tensor).dtype
        T.check_supported_dtype(np.dtype(dtype))
        key = ("ar_rep", shape, str(dtype), int(rop), ps.cache_token,
               float(prescale_factor), float(postscale_factor), k)
        # Output committed to the set's first mesh device — the same
        # placement _from_global's shard view gives on the full path
        # (subset process sets may exclude the default device).
        out_sh = jax.sharding.SingleDeviceSharding(
            ps.mesh.devices.flat[0])
        fn = _cache.get_or_build(key, lambda: jax.jit(
            lambda x: _replicated_reduce_one(
                x, rop, k, prescale_factor, postscale_factor),
            out_shardings=out_sh))
        _consistency(f"allreduce(shape={(k,) + shape},dtype={dtype},"
                     f"op={int(rop)},ps={ps.process_set_id})", ps,
                     name=name or "allreduce")
        with _instrument(name or "allreduce", "ALLREDUCE",
                         axis=getattr(ps, "mesh_axis", None),
                         nbytes_fn=lambda: (
                             (math.prod(shape) * k *
                              _dtype_info(dtype)[0]),
                             _dtype_info(dtype)[1])):
            return _execute(fn, jnp.asarray(tensor))
    g, stacked = _to_global(tensor, ps)
    key = ("ar", g.shape, str(g.dtype), int(rop), ps.cache_token,
           float(prescale_factor), float(postscale_factor), bool(donate),
           hm is not None,
           bool(cfg.adasum_halving) and rop == T.ReduceOp.ADASUM)
    if hm is not None:
        fn = _cache.get_or_build(key, lambda: _builder_allreduce_hier(
            hm, k, rop, prescale_factor, postscale_factor, donate))
    else:
        fn = _cache.get_or_build(key, lambda: _builder_allreduce(
            ps.mesh, k, rop, prescale_factor, postscale_factor, 1, donate))
    _consistency(f"allreduce(shape={g.shape},dtype={g.dtype},op={int(rop)},"
                 f"ps={ps.process_set_id})", ps, name=name or "allreduce")
    with _instrument(name or "allreduce", "ALLREDUCE", arrays=(g,),
                     axis=getattr(ps, "mesh_axis", None)):
        return _from_global(_execute(fn, g), stacked)


def grouped_allreduce(tensors: Sequence[Any],
                      average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Any = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: Optional[ProcessSet] = None) -> List[jax.Array]:
    """Reduce a group of tensors atomically, fused into ≤-threshold buckets.

    Reference: EnqueueTensorAllreduces (operations.cc:1436) + FuseResponses
    (controller.cc:901) + the fusion buffer. Here the group is one XLA
    program: tensors are bucketed (fusion.py) and each bucket is one psum.
    """
    ps = _resolve_ps(process_set)
    rop = _normalize_op(average, op)
    if not tensors:
        return []
    k = ps.size()
    cfg = topology.state().config
    hm = _hier_usable(ps) if (cfg.hierarchical_allreduce
                              and rop in (T.ReduceOp.SUM,
                                          T.ReduceOp.AVERAGE)) else None
    if _replicated_fast_ok(ps, rop, hm, tensors):
        shapes = tuple(tuple(np.shape(t)) for t in tensors)
        # np.asarray, not np.result_type: the latter parses a list input
        # as a dtype spec on numpy 2.x
        dtypes = tuple(str(t.dtype) if hasattr(t, "dtype")
                       else str(np.asarray(t).dtype) for t in tensors)
        for d in dtypes:  # same gate _to_global applies on the full path
            T.check_supported_dtype(np.dtype(d))
        key = ("gar_rep", shapes, dtypes, int(rop), ps.cache_token,
               float(prescale_factor), float(postscale_factor), k)
        out_sh = jax.sharding.SingleDeviceSharding(
            ps.mesh.devices.flat[0])

        def build_fast() -> Callable:
            def body(*xs):
                return tuple(_replicated_reduce_one(
                    x, rop, k, prescale_factor, postscale_factor)
                    for x in xs)
            return jax.jit(body, out_shardings=out_sh)

        fn = _cache.get_or_build(key, build_fast)
        _consistency(f"grouped_allreduce(n={len(tensors)},shapes="
                     f"{[(k,) + s for s in shapes]},op={int(rop)},"
                     f"ps={ps.process_set_id})", ps,
                     name=name or "grouped_allreduce")
        with _instrument(name or "grouped_allreduce", "ALLREDUCE",
                         ntensors=len(tensors),
                         axis=getattr(ps, "mesh_axis", None),
                         nbytes_fn=lambda: (
                             sum(math.prod(s) * k * _dtype_info(d)[0]
                                 for s, d in zip(shapes, dtypes)),
                             dtypes[0] if dtypes else "")):
            outs = _execute(fn, *[jnp.asarray(t) for t in tensors])
        return list(outs)
    gs, stackeds = _lift_group(tensors, ps)
    from horovod_tpu.ops import fusion
    eff_thresh = fusion.effective_threshold(cfg.fusion_threshold_bytes,
                                            cfg.bucket_cap_bytes)
    key = ("gar", tuple((g.shape, str(g.dtype)) for g in gs), int(rop),
           ps.cache_token, float(prescale_factor), float(postscale_factor),
           eff_thresh, cfg.bucket_reverse, cfg.disable_group_fusion,
           hm is not None,
           bool(cfg.adasum_halving) and rop == T.ReduceOp.ADASUM)

    def build() -> Callable:
        mesh_ = hm if hm is not None else ps.mesh
        spec = _HIER_SPEC if hm is not None else P(_AXIS)
        if hm is not None:
            k_ici = hm.shape["ici"]
            reduce_one = lambda b: _apply_reduce_hier(  # noqa: E731
                b, rop, k, k_ici, prescale_factor, postscale_factor)
        else:
            reduce_one = lambda b: _apply_reduce(  # noqa: E731
                b, rop, k, prescale_factor, postscale_factor)

        def body(*blocks):
            if cfg.disable_group_fusion or rop in (T.ReduceOp.ADASUM,):
                return tuple(reduce_one(b) for b in blocks)
            return fusion.fused_reduce_blocks(
                blocks, reduce_one, eff_thresh,
                reverse=cfg.bucket_reverse)

        fn = jax.shard_map(body, mesh=mesh_,
                           in_specs=(spec,) * len(gs),
                           out_specs=(spec,) * len(gs),
                           check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    _consistency(f"grouped_allreduce(n={len(gs)},shapes="
                 f"{[tuple(g.shape) for g in gs]},op={int(rop)},"
                 f"ps={ps.process_set_id})", ps,
                 name=name or "grouped_allreduce")
    with _instrument(name or "grouped_allreduce", "ALLREDUCE",
                     arrays=tuple(gs), ntensors=len(gs),
                     axis=getattr(ps, "mesh_axis", None)):
        outs = _execute(fn, *gs)
    return [_from_global(o, s) for o, s in zip(outs, stackeds)]


# --------------------------------------------------------------------------
# Bucketed, pipelined allreduce (the backward-overlap path; docs/perf.md)
# --------------------------------------------------------------------------

class _BucketStats:
    """Cross-thread bucket-scheduler accounting (dispatch counters + the
    last measured overlap fraction, read by metrics/tests)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.dispatched = 0  # guarded-by: _lock
        self.profiled_calls = 0  # guarded-by: _lock
        self.last_overlap: float = 0.0  # guarded-by: _lock

    def record(self, n_buckets: int, overlap) -> None:
        with self._lock:
            self.dispatched += n_buckets
            if overlap is not None:
                self.profiled_calls += 1
                self.last_overlap = float(overlap)

    def snapshot(self) -> Tuple[int, int, float]:
        with self._lock:
            return self.dispatched, self.profiled_calls, self.last_overlap


_bucket_stats = _BucketStats()
# Per-thread (nbytes, seconds) samples of the most recent PROFILED call —
# thread-local on purpose: concurrent callers must not splice each other's
# timing vectors, and the consumer (the optimizer's tuner hook) reads it
# on the same thread right after its own call returns.
_bucket_tls = threading.local()


def last_bucket_timings() -> List[Tuple[int, float]]:
    """(global_payload_bytes, seconds) per bucket of this thread's most
    recent profiled `bucketed_allreduce` (empty if that call ran fully
    async). Feeds the online bucket tuner (core/autotune.py)."""
    return list(getattr(_bucket_tls, "timings", ()))


def bucket_overlap_stats() -> Tuple[int, int, float]:
    """(buckets_dispatched, profiled_calls, last_overlap_fraction)."""
    return _bucket_stats.snapshot()


def bucketed_allreduce(tensors: Sequence[Any],
                       average: Optional[bool] = None,
                       name: Optional[str] = None,
                       op: Any = None,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       process_set: Optional[ProcessSet] = None,
                       profile: Optional[bool] = None) -> List[jax.Array]:
    """Reduce a group of tensors as independently dispatched fusion buckets.

    Where `grouped_allreduce` compiles the whole group into ONE XLA
    program (every bucket's psum fenced by the same program boundary),
    this path compiles one program PER bucket and dispatches them
    back-to-back without blocking: JAX's async dispatch keeps several
    buckets' ICI transfers in flight concurrently — the role of the
    reference's background thread draining the fusion buffer
    (operations.cc RunLoopOnce), and the eager counterpart of the
    in-jit overlap `reduce_gradients_in_jit` gets from the XLA scheduler.
    Oversize tensors are chunked across buckets (ops/fusion.py) and
    reassembled here.

    `profile=True` (or HOROVOD_BUCKET_PROFILE=1) forces completion of
    each bucket and records per-bucket wall times plus an
    `overlap_fraction` estimate (1 - wall_window / sum_of_bucket_spans,
    i.e. the fraction of in-flight time shared with another bucket) —
    the samples the online bucket tuner and the
    `horovod_overlap_fraction` gauge consume.

    Falls back to `grouped_allreduce` where per-bucket dispatch cannot
    help: single tensor, Adasum (never fused), hierarchical meshes,
    HOROVOD_DISABLE_GROUP_FUSION, HOROVOD_BUCKET_PIPELINE=0, or the
    replicated fast path.
    """
    ps = _resolve_ps(process_set)
    rop = _normalize_op(average, op)
    if not tensors:
        return []
    cfg = topology.state().config
    hm = _hier_usable(ps) if (cfg.hierarchical_allreduce
                              and rop in (T.ReduceOp.SUM,
                                          T.ReduceOp.AVERAGE)) else None
    if (len(tensors) == 1 or rop == T.ReduceOp.ADASUM
            or cfg.disable_group_fusion or hm is not None
            or not cfg.bucket_pipeline
            or _replicated_fast_ok(ps, rop, hm, tensors)):
        _bucket_tls.timings = ()
        return grouped_allreduce(
            tensors, name=name, op=rop, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=ps)
    from horovod_tpu.ops import fusion

    k = ps.size()
    gs, stackeds = _lift_group(tensors, ps)
    eff = fusion.effective_threshold(cfg.fusion_threshold_bytes,
                                     cfg.bucket_cap_bytes)
    metas = [(tuple(g.shape[1:]), str(g.dtype)) for g in gs]
    plan = fusion.plan_buckets(metas, eff, reverse=cfg.bucket_reverse)
    # The descriptor embeds the effective threshold AND the plan
    # fingerprint: ranks whose bucket thresholds diverged (a broken tuner
    # sync) dispatch visibly different descriptors, so the consistency
    # checker / fingerprint verifier name the divergence instead of the
    # mismatched programs deadlocking.
    _consistency(
        f"bucketed_allreduce(n={len(gs)},shapes="
        f"{[tuple(g.shape) for g in gs]},op={int(rop)},thresh={eff},"
        f"plan={fusion.plan_signature(plan)},ps={ps.process_set_id})",
        ps, name=name or "bucketed_allreduce")
    if profile is None:
        profile = cfg.bucket_profile
    base = name or "bucketed_allreduce"
    tl = topology.state().timeline
    records = []  # (bucket, members, layout, outs)
    launches: List[float] = []
    with _instrument(base, "ALLREDUCE", arrays=tuple(gs),
                     ntensors=len(gs), axis=getattr(ps, "mesh_axis", None)):
        for bi, bucket in enumerate(plan):
            members: List[int] = []
            pos_of: Dict[int, int] = {}
            layout: List[Tuple[int, int, int, bool]] = []
            for it in bucket.items:
                if it.index not in pos_of:
                    pos_of[it.index] = len(members)
                    members.append(it.index)
                whole = it.start == 0 and it.size == int(
                    np.prod(gs[it.index].shape[1:], dtype=np.int64))
                layout.append((pos_of[it.index], it.start, it.size, whole))
            lay = tuple(layout)
            key = ("bar",
                   tuple((tuple(gs[i].shape), str(gs[i].dtype))
                         for i in members),
                   lay, int(rop), ps.cache_token,
                   float(prescale_factor), float(postscale_factor))
            first_build = key not in _cache._cache

            def build(lay=lay, nmem=len(members)) -> Callable:
                def body(*blocks):
                    segs = [blocks[pos].reshape(1, -1)[:, s:s + n]
                            for pos, s, n, _w in lay]
                    fused = segs[0] if len(segs) == 1 \
                        else jnp.concatenate(segs, axis=1)
                    red = _apply_reduce(fused, rop, k, prescale_factor,
                                        postscale_factor)
                    outs, off = [], 0
                    for pos, _s, n, whole in lay:
                        piece = red[:, off:off + n]
                        outs.append(piece.reshape(blocks[pos].shape)
                                    if whole else piece)
                        off += n
                    return tuple(outs) if len(lay) > 1 else outs[0]

                specs_out = (P(_AXIS),) * len(lay) if len(lay) > 1 \
                    else P(_AXIS)
                fn = jax.shard_map(body, mesh=ps.mesh,
                                   in_specs=(P(_AXIS),) * nmem,
                                   out_specs=specs_out, check_vma=False)
                return jax.jit(fn)

            fn = _cache.get_or_build(key, build)
            if first_build:
                # One ring event per DISTINCT bucket program (not per
                # dispatch — steady-state steps must not evict the
                # collective history hvddoctor merges).
                _flight.record(
                    "bucket", f"{base} b{bi}/{len(plan)} "
                    f"{bucket.nbytes >> 10}KB x{len(bucket.items)} "
                    f"{bucket.dtype} (new program)")
            if tl is not None:
                tl.span_begin(f"{base}/b{bi}", "ALLREDUCE")
            launches.append(time.perf_counter())
            outs = _execute(fn, *[gs[i] for i in members])
            if tl is not None:
                tl.span_end(f"{base}/b{bi}", "ALLREDUCE")
            if len(layout) == 1:
                outs = (outs,)
            records.append((bucket, members, layout, outs))
        timings: List[Tuple[int, float]] = []
        overlap = None
        if profile and records:
            completes: List[float] = []
            for bi, (_, _, _, outs) in enumerate(records):
                # The complete half of the per-bucket track: the launch
                # span above covers dispatch; this WAIT span ends when
                # the bucket's collective actually finished, so a trace
                # shows the in-flight windows overlapping.
                if tl is not None:
                    tl.span_begin(f"{base}/b{bi}", "WAIT_FOR_DATA")
                jax.block_until_ready(outs)
                if tl is not None:
                    tl.span_end(f"{base}/b{bi}", "WAIT_FOR_DATA")
                completes.append(time.perf_counter())
            spans = [c - l for l, c in zip(launches, completes)]
            total = completes[-1] - launches[0]
            ssum = sum(spans)
            if len(spans) > 1 and ssum > 0:
                overlap = max(0.0, min(1.0, 1.0 - total / ssum))
            # Wire (per-rank) bucket bytes, the quantity the fusion
            # threshold bounds — what the bucket tuner's size classes key on.
            timings = [(rec[0].nbytes, s)
                       for rec, s in zip(records, spans)]
            if len(spans) > 1:
                med = sorted(spans)[len(spans) // 2]
                for bi, (rec, s) in enumerate(zip(records, spans)):
                    if med > 0 and s > 3.0 * med and s > 0.005:
                        _flight.record(
                            "bucket",
                            f"SLOW {base} b{bi}/{len(plan)} "
                            f"{rec[0].nbytes >> 10}KB took {s * 1e3:.1f}ms "
                            f"(median {med * 1e3:.1f}ms)")
        _bucket_tls.timings = tuple(timings)
        from horovod_tpu.observability import metrics as _m
        if _m.registry().enabled:
            mx = _mx()
            mx["bucket_n"].inc(len(plan))
            for bucket in plan:
                mx["bucket_bytes"].observe(bucket.nbytes * k)
            for _, s in timings:
                mx["bucket_secs"].observe(s)
            if overlap is not None:
                mx["overlap"].set(overlap)
        _bucket_stats.record(len(plan), overlap)
    results: List[Optional[jax.Array]] = [None] * len(gs)
    chunk_map: List[List[Tuple[int, jax.Array]]] = [[] for _ in gs]
    for _, members, layout, outs in records:
        for (pos, start, _n, whole), o in zip(layout, outs):
            if whole:
                results[members[pos]] = o
            else:
                chunk_map[members[pos]].append((start, o))
    for i, g in enumerate(gs):
        if results[i] is None:
            parts = [p for _, p in
                     sorted(chunk_map[i], key=lambda t: t[0])]
            flat = parts[0] if len(parts) == 1 \
                else jnp.concatenate(parts, axis=1)
            results[i] = flat.reshape(g.shape).astype(g.dtype)
    return [_from_global(r, s) for r, s in zip(results, stackeds)]


def broadcast(tensor: Any, root_rank: int,
              name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> jax.Array:
    """Broadcast the root rank's tensor to every rank in the set.

    Reference: EnqueueTensorBroadcast (operations.cc:1710).
    """
    ps = _resolve_ps(process_set)
    g, stacked = _to_global(tensor, ps)
    root = ps.rank_index(root_rank)
    k = ps.size()
    key = ("bc", g.shape, str(g.dtype), root, ps.cache_token)

    def build() -> Callable:
        def body(block):
            gathered = lax.all_gather(block, _AXIS, axis=0)  # (k, 1, *shape)
            return gathered[root]

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=P(_AXIS),
                           out_specs=P(_AXIS), check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    _consistency(f"broadcast(shape={g.shape},dtype={g.dtype},root={root},"
                 f"ps={ps.process_set_id})", ps, name=name or "broadcast")
    with _instrument(name or "broadcast", "BROADCAST", arrays=(g,)):
        return _from_global(_execute(fn, g), stacked)


def allgather(tensor: Any, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> jax.Array:
    """Concatenate per-rank tensors along dim 0; first dims may differ.

    Reference: EnqueueTensorAllgather (operations.cc:1551). Uneven first
    dimensions are negotiated with a size-exchange collective first (the
    role of the controller's response construction, controller.cc:447+).
    """
    ps = _resolve_ps(process_set)
    g, stacked = _to_global(tensor, ps)
    if g.ndim < 2:
        raise HorovodTpuError(
            "allgather requires per-rank tensors with at least one dimension")
    k = ps.size()
    # Consistency check BEFORE the blocking size exchange — a rank calling a
    # different collective would otherwise deadlock inside _exchange_sizes
    # before the diagnostic could fire. The signature excludes dim 0, which
    # may legitimately differ per rank (uneven allgather).
    _consistency(f"allgather(rest={tuple(g.shape[2:])},ndim={g.ndim},"
                 f"dtype={g.dtype},ps={ps.process_set_id})", ps,
                 name=name or "allgather")
    if stacked:
        # Single-controller stacked input: all rows share a shape — even path.
        sizes = (int(g.shape[1]),) * k
    else:
        sizes = _exchange_sizes(int(g.shape[1]), ps)
    max_d0 = max(sizes) if sizes else 0
    cfg = topology.state().config
    hm = _hier_usable(ps) if (cfg.hierarchical_allgather
                              and len(set(sizes)) == 1) else None
    key = ("ag", g.shape, str(g.dtype), tuple(sizes), ps.cache_token,
           hm is not None)

    def build() -> Callable:
        total = sum(sizes)

        if hm is not None:
            # Even sizes: gather within the fast ici axis first, then
            # across dcn — dcn-major rank order matches the flat layout
            # (reference structure: hierarchical allgather,
            # HOROVOD_HIERARCHICAL_ALLGATHER).
            def hier_body(block):
                x = block[0]
                g1 = lax.all_gather(x, "ici", axis=0, tiled=True)
                g2 = lax.all_gather(g1, "dcn", axis=0, tiled=True)
                return g2[None]

            fn = jax.shard_map(hier_body, mesh=hm, in_specs=_HIER_SPEC,
                               out_specs=_HIER_SPEC, check_vma=False)
            return jax.jit(fn)

        def body(block):
            x = block[0]  # (d0_local, *rest) — same static d0 across ranks here
            pad = max_d0 - x.shape[0]
            if pad:
                x = jnp.concatenate(
                    [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
            gathered = lax.all_gather(x, _AXIS, axis=0)  # (k, max_d0, *rest)
            pieces = [lax.slice_in_dim(gathered[i], 0, sizes[i], axis=0)
                      for i in range(k)]
            out = jnp.concatenate(pieces, axis=0)
            assert out.shape[0] == total
            return out[None]

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=P(_AXIS),
                           out_specs=P(_AXIS), check_vma=False)
        return jax.jit(fn)

    if len(set(sizes)) > 1 and not stacked:
        # Uneven: each rank pads its own tensor to max_d0 before the shared
        # program runs (shapes must agree across the SPMD program). After
        # the pre-pad, `build`'s in-program pad is a no-op and the cache key
        # (which includes the padded shape + per-rank sizes) distinguishes
        # this case — the same builder serves both paths.
        pad = max_d0 - (g.shape[1])
        if pad > 0:
            g = jnp.concatenate(
                [g, jnp.zeros((g.shape[0], pad) + g.shape[2:], g.dtype)], axis=1)
        key = ("ag", g.shape, str(g.dtype), tuple(sizes), ps.cache_token)
    fn = _cache.get_or_build(key, build)
    with _instrument(name or "allgather", "ALLGATHER", arrays=(g,)):
        return _from_global(_execute(fn, g), stacked)


def reducescatter(tensor: Any, op: Any = T.ReduceOp.AVERAGE,
                  name: Optional[str] = None,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0,
                  process_set: Optional[ProcessSet] = None) -> jax.Array:
    """Reduce across ranks, then scatter slices of dim 0.

    Reference: EnqueueTensorReducescatter (operations.cc:1774). Rank i
    receives rows [sum(sizes[:i]), sum(sizes[:i+1])) where sizes follow
    Horovod's uneven rule: d0//k + (1 if i < d0%k else 0).
    """
    ps = _resolve_ps(process_set)
    rop = _normalize_op(None, op) if op is not None else T.ReduceOp.AVERAGE
    if rop not in (T.ReduceOp.SUM, T.ReduceOp.AVERAGE):
        raise HorovodTpuError("reducescatter supports SUM and AVERAGE only")
    g, stacked = _to_global(tensor, ps)
    k = ps.size()
    d0 = int(g.shape[1])
    even = (d0 % k == 0)
    key = ("rs", g.shape, str(g.dtype), int(rop), even, ps.cache_token,
           float(prescale_factor), float(postscale_factor))

    def build() -> Callable:
        def body(block):
            return _rs_block(block[0], k, rop, prescale_factor,
                             postscale_factor, d0)[None]

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=P(_AXIS),
                           out_specs=P(_AXIS), check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    _consistency(f"reducescatter(shape={g.shape},dtype={g.dtype},"
                 f"op={int(rop)},ps={ps.process_set_id})", ps,
                 name=name or "reducescatter")
    with _instrument(name or "reducescatter", "REDUCESCATTER",
                     arrays=(g,)):
        out = _execute(fn, g)
    return _rs_trim(out, stacked, d0, k, ps)


def _rs_block(x, k: int, rop, prescale_factor: float,
              postscale_factor: float, d0: int):
    """Per-tensor reduce-scatter body (shared by single + grouped paths)."""
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, x.dtype)
    if d0 % k == 0:
        y = lax.psum_scatter(x, _AXIS, scatter_dimension=0, tiled=True)
        if rop == T.ReduceOp.AVERAGE:
            y = y / jnp.asarray(k, y.dtype)
        if postscale_factor != 1.0:
            y = y * jnp.asarray(postscale_factor, y.dtype)
        return y
    # Uneven: full psum then per-rank slice of varying size. The slice
    # sizes differ per rank, which SPMD can't express with one static
    # shape — pad every slice to ceil; the wrapper trims on the way out.
    y = lax.psum(x, _AXIS)
    if rop == T.ReduceOp.AVERAGE:
        y = y / jnp.asarray(k, y.dtype)
    if postscale_factor != 1.0:
        y = y * jnp.asarray(postscale_factor, y.dtype)
    idx = lax.axis_index(_AXIS)
    big = d0 // k + 1
    rem = d0 % k
    start = jnp.minimum(idx, rem) * big + \
        jnp.maximum(idx - rem, 0) * (big - 1)
    return lax.dynamic_slice_in_dim(
        jnp.concatenate(
            [y, jnp.zeros((big,) + y.shape[1:], y.dtype)], axis=0),
        start, big, axis=0)


def _rs_trim(out, stacked: bool, d0: int, k: int, ps: ProcessSet):
    """Undo the uneven-path padding (shared by single + grouped paths)."""
    if d0 % k == 0:
        return _from_global(out, stacked)
    big = d0 // k + 1
    rem = d0 % k
    sizes = [big if i < rem else big - 1 for i in range(k)]
    if stacked:
        # Ragged per-rank sizes cannot stay stacked; trim on host view.
        return [out[i, :sizes[i]] for i in range(k)]
    my = _from_global(out, stacked)
    my_rank_in_set = ps.rank_index(topology.rank())
    return my[: sizes[my_rank_in_set]]


def grouped_reducescatter(tensors: Sequence[Any], op: Any = T.ReduceOp.AVERAGE,
                          name: Optional[str] = None,
                          prescale_factor: float = 1.0,
                          postscale_factor: float = 1.0,
                          process_set: Optional[ProcessSet] = None) -> List[Any]:
    """Atomic fused reduce-scatter of a group: ONE XLA program for all
    tensors (reference: grouped RS is an atomic fused response,
    tensorflow/mpi_ops.cc:1415 — not a loop of singles)."""
    ps = _resolve_ps(process_set)
    if not tensors:
        return []
    rop = _normalize_op(None, op) if op is not None else T.ReduceOp.AVERAGE
    if rop not in (T.ReduceOp.SUM, T.ReduceOp.AVERAGE):
        raise HorovodTpuError("reducescatter supports SUM and AVERAGE only")
    gs, stackeds = _lift_group(tensors, ps)
    k = ps.size()
    d0s = [int(g.shape[1]) for g in gs]
    key = ("grs", tuple((g.shape, str(g.dtype)) for g in gs), int(rop),
           ps.cache_token, float(prescale_factor), float(postscale_factor))

    def build() -> Callable:
        def body(*blocks):
            return tuple(
                _rs_block(b[0], k, rop, prescale_factor, postscale_factor,
                          d0s[i])[None]
                for i, b in enumerate(blocks))

        fn = jax.shard_map(body, mesh=ps.mesh,
                           in_specs=(P(_AXIS),) * len(gs),
                           out_specs=(P(_AXIS),) * len(gs), check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    _consistency(f"grouped_reducescatter(n={len(gs)},shapes="
                 f"{[tuple(g.shape) for g in gs]},op={int(rop)},"
                 f"ps={ps.process_set_id})", ps,
                 name=name or "grouped_reducescatter")
    with _instrument(name or "grouped_reducescatter", "REDUCESCATTER",
                     arrays=tuple(gs), ntensors=len(gs)):
        outs = _execute(fn, *gs)
    return [_rs_trim(o, st, d0, k, ps)
            for o, st, d0 in zip(outs, stackeds, d0s)]


def grouped_allgather(tensors: Sequence[Any],
                      name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None) -> List[Any]:
    """Atomic fused allgather of a group: ONE XLA program and ONE size
    exchange for the whole group (reference: grouped allgather is an
    atomic fused response, tensorflow/mpi_ops.cc:788; the single-tensor
    path pays one blocking size exchange per call — the group pays one)."""
    ps = _resolve_ps(process_set)
    if not tensors:
        return []
    gs, stackeds = _lift_group(tensors, ps)
    for g in gs:
        if g.ndim < 2:
            raise HorovodTpuError(
                "allgather requires per-rank tensors with at least one "
                "dimension")
    k = ps.size()
    n = len(gs)
    _consistency(f"grouped_allgather(n={n},"
                 f"rests={[tuple(g.shape[2:]) for g in gs]},"
                 f"dtypes={[str(g.dtype) for g in gs]},"
                 f"ps={ps.process_set_id})", ps,
                 name=name or "grouped_allgather")
    if jax.process_count() == 1:
        sizes_matrix = np.tile(
            np.asarray([[int(g.shape[1]) for g in gs]], np.int64), (k, 1))
    else:
        sizes_matrix = _exchange_rows(
            np.asarray([int(g.shape[1]) for g in gs], np.int64), ps)
    max_d0 = sizes_matrix.max(axis=0)  # per tensor
    padded = []
    for i, g in enumerate(gs):
        pad = int(max_d0[i]) - int(g.shape[1])
        if pad > 0:
            g = jnp.concatenate(
                [g, jnp.zeros((g.shape[0], pad) + g.shape[2:], g.dtype)],
                axis=1)
        padded.append(g)
    cfg = topology.state().config
    all_even = all(len(set(sizes_matrix[:, i].tolist())) == 1
                   for i in range(n))
    hm = _hier_usable(ps) if (cfg.hierarchical_allgather
                              and all_even) else None
    key = ("gag", tuple((g.shape, str(g.dtype)) for g in padded),
           tuple(map(tuple, sizes_matrix.tolist())), ps.cache_token,
           hm is not None)

    def build() -> Callable:
        sm = sizes_matrix

        if hm is not None:
            # Even sizes: gather within the fast ici axis, then across dcn
            # — the same HOROVOD_HIERARCHICAL_ALLGATHER decomposition as
            # the single-tensor path, applied per group member.
            def hier_body(*blocks):
                outs = []
                for b in blocks:
                    g1 = lax.all_gather(b[0], "ici", axis=0, tiled=True)
                    g2 = lax.all_gather(g1, "dcn", axis=0, tiled=True)
                    outs.append(g2[None])
                return tuple(outs)

            fn = jax.shard_map(hier_body, mesh=hm,
                               in_specs=(_HIER_SPEC,) * n,
                               out_specs=(_HIER_SPEC,) * n, check_vma=False)
            return jax.jit(fn)

        def body(*blocks):
            outs = []
            for i, b in enumerate(blocks):
                gathered = lax.all_gather(b[0], _AXIS, axis=0)
                pieces = [lax.slice_in_dim(gathered[r], 0, int(sm[r, i]),
                                           axis=0) for r in range(k)]
                outs.append(jnp.concatenate(pieces, axis=0)[None])
            return tuple(outs)

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=(P(_AXIS),) * n,
                           out_specs=(P(_AXIS),) * n, check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    with _instrument(name or "grouped_allgather", "ALLGATHER",
                     arrays=tuple(padded), ntensors=len(padded)):
        outs = _execute(fn, *padded)
    return [_from_global(o, st) for o, st in zip(outs, stackeds)]


def alltoall(tensor: Any, splits: Optional[Any] = None,
             name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None) -> Tuple[jax.Array, jax.Array]:
    """Scatter dim-0 slices to every rank, gather received slices.

    Reference: EnqueueTensorAlltoall (operations.cc:1904). Returns
    (output, received_splits) like the reference torch API. With no
    `splits`, dim 0 must divide evenly by the set size.
    """
    ps = _resolve_ps(process_set)
    g, stacked = _to_global(tensor, ps)
    k = ps.size()
    if g.ndim < 2:
        # The stacked-input rule read a 1-D length-k tensor as k per-rank
        # SCALARS, which alltoall cannot split. The caller almost
        # certainly meant the classic one-element-per-peer alltoall —
        # re-lift as a replicated (k,) vector.
        g, stacked = _to_global(np.asarray(tensor)[None], ps)
        g = jnp.squeeze(g, axis=1) if g.ndim == 3 else g
        if g.ndim < 2:
            raise HorovodTpuError(
                "alltoall needs at least one dimension to split per rank")
    d0 = int(g.shape[1])
    if splits is None:
        if d0 % k:
            raise HorovodTpuError(
                f"alltoall without splits requires dim0 ({d0}) divisible by "
                f"set size ({k})")
        my_splits = np.full((k,), d0 // k, dtype=np.int64)
    else:
        my_splits = np.asarray(splits, dtype=np.int64)
        if my_splits.shape != (k,) or int(my_splits.sum()) != d0:
            raise HorovodTpuError("splits must have one entry per rank and "
                                  "sum to dim 0")

    # Consistency check BEFORE the blocking splits exchange (see allgather);
    # dim 0 = sum(splits) may legitimately differ per rank.
    _consistency(f"alltoall(rest={tuple(g.shape[2:])},ndim={g.ndim},"
                 f"dtype={g.dtype},ps={ps.process_set_id})", ps,
                 name=name or "alltoall")
    # Exchange the full splits matrix (controller's AlltoallGetRecvSplits,
    # controller.h:63). In stacked mode rows share `my_splits`.
    if stacked and splits is not None:
        raise HorovodTpuError(
            "stacked (single-controller) alltoall takes per-rank splits via "
            "a (k, k) splits matrix; pass splits=None or use multi-process")
    splits_matrix = np.tile(my_splits, (k, 1)) if (stacked or splits is None) \
        else _exchange_rows(my_splits, ps)

    recv_splits = splits_matrix[:, :]  # [src, dst]
    max_chunk = int(splits_matrix.max()) if splits_matrix.size else 0
    key = ("a2a", g.shape, str(g.dtype),
           tuple(map(tuple, splits_matrix.tolist())), ps.cache_token)

    def build() -> Callable:
        sm = jnp.asarray(splits_matrix)

        def body(block):
            x = block[0]  # (d0, *rest)
            idx = lax.axis_index(_AXIS)
            my = sm[idx]  # (k,) chunk sizes this rank sends
            starts = jnp.concatenate(
                [jnp.zeros((1,), my.dtype), jnp.cumsum(my)[:-1]])
            xpad = jnp.concatenate(
                [x, jnp.zeros((max_chunk,) + x.shape[1:], x.dtype)], axis=0)
            # One gather for all destinations — O(1) program size where a
            # per-destination dynamic-slice loop would be O(k) (matters at
            # 256 ranks).
            row_idx = starts[:, None] + \
                jnp.arange(max_chunk, dtype=starts.dtype)[None, :]
            chunks = xpad[row_idx]  # (k, max_chunk, *rest)
            recvd = lax.all_to_all(chunks, _AXIS, split_axis=0, concat_axis=0)
            # recvd[i] = chunk sent by rank i to me, padded to max_chunk.
            return recvd[None]

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=P(_AXIS),
                           out_specs=P(_AXIS), check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    with _instrument(name or "alltoall", "ALLTOALL", arrays=(g,)):
        out = _execute(fn, g)  # (k_local_rows, k, max_chunk, *rest)

    def trim(rank_in_set: int, rowdata):
        pieces = [rowdata[i, : int(splits_matrix[i, rank_in_set])]
                  for i in range(k)]
        return jnp.concatenate(pieces, axis=0), \
            jnp.asarray(splits_matrix[:, rank_in_set])

    if stacked:
        results = [trim(i, out[i]) for i in range(k)]
        return results  # list of (output, recv_splits) per rank
    my_row = _from_global(out, stacked)
    my_rank_in_set = ps.rank_index(topology.rank())
    return trim(my_rank_in_set, my_row)


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    """Block until every rank reaches the barrier.

    Reference: EnqueueBarrier (operations.cc:2020). A 1-element psum forces a
    full-mesh rendezvous; block_until_ready makes it synchronous host-side.
    """
    ps = _resolve_ps(process_set)
    key = ("barrier", ps.cache_token)

    def build() -> Callable:
        def body(block):
            return lax.psum(block, _AXIS)

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=P(_AXIS),
                           out_specs=P(_AXIS), check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    L = max(1, _local_member_count(ps))
    ones = np.ones((L, 1), np.int32)
    g, _ = _to_global(ones if L > 1 else ones[0], ps)
    _consistency(f"barrier(ps={ps.process_set_id})", ps)
    # Blocking point: if another rank never arrives we hang here — exactly
    # what the stall inspector watches (reference: stall_inspector.cc).
    _stall_submit("barrier")
    try:
        with _instrument("barrier", "BARRIER"):
            jax.block_until_ready(_execute(fn, g))
    finally:
        _stall_done("barrier")


def synchronize(handle: Any) -> Any:
    """Wait for an async collective result (reference: mpi_ops.py:1269).

    JAX arrays are futures under async dispatch, so the handle IS the result.
    The wait runs under the stall watchdog (elastic mode: bounded by
    HOROVOD_STALL_SHUTDOWN_TIME_SECONDS → HorovodInternalError).
    """
    try:
        return _guarded_wait("synchronize",
                             lambda: jax.block_until_ready(handle))
    except Exception as e:
        if isinstance(e, HorovodInternalError):
            raise
        if topology.raw_state().config.elastic and is_comm_failure(e):
            raise HorovodInternalError(f"synchronize failed: {e}") from e
        raise


def poll(handle: Any) -> bool:
    """Non-blocking readiness check (reference: horovod_torch_poll)."""
    if hasattr(handle, "is_ready"):
        try:
            return bool(handle.is_ready())
        except Exception:
            pass
    return True


# Async aliases: JAX dispatch is already asynchronous; these exist for
# reference API parity (horovod/torch/mpi_ops.py allreduce_async etc.).
allreduce_async = allreduce
grouped_allreduce_async = grouped_allreduce
bucketed_allreduce_async = bucketed_allreduce
allgather_async = allgather
broadcast_async = broadcast
alltoall_async = alltoall
reducescatter_async = reducescatter


# --------------------------------------------------------------------------
# Helpers
# --------------------------------------------------------------------------

# In-flight named-operation registry (reference: TensorQueue's duplicate
# name detection -> DUPLICATE_NAME_ERROR, common/tensor_queue.cc:29-70).
# Sync eager ops complete before returning, so only truly-async surfaces
# (frontend async handles) can overlap; they register their name for the
# handle's lifetime.
_inflight_names: set = set()
_inflight_lock = threading.Lock()


def register_inflight_name(name: Optional[str]) -> bool:
    """Claim `name` until release_inflight_name; raises DuplicateNameError
    if an operation with that name is still pending. Returns False for
    anonymous ops (no claim)."""
    if not name:
        return False
    with _inflight_lock:
        if name in _inflight_names:
            raise DuplicateNameError(
                f"an operation named '{name}' is already in flight — "
                f"synchronize it before reusing the name (reference: "
                f"DUPLICATE_NAME_ERROR, common/tensor_queue.cc)")
        _inflight_names.add(name)
        return True


def release_inflight_name(name: Optional[str]) -> None:
    if name:
        with _inflight_lock:
            _inflight_names.discard(name)


def _normalize_op(average: Optional[bool], op: Any) -> T.ReduceOp:
    if average is not None and op is not None:
        raise HorovodTpuError("specify either average or op, not both "
                              "(reference: mpi_ops.py handle_average_backwards_"
                              "compatibility)")
    if op is not None:
        return T.normalize_reduce_op(op)
    if average is None:
        return T.ReduceOp.AVERAGE
    return T.ReduceOp.AVERAGE if average else T.ReduceOp.SUM


def _exchange_sizes(d0: int, ps: ProcessSet) -> Tuple[int, ...]:
    """All ranks learn every rank's dim-0 size (controller duty in the
    reference: Allgather2Ints, controller.h:67)."""
    k = ps.size()
    if jax.process_count() == 1:
        return (d0,) * k
    row = _exchange_rows(np.asarray([d0], np.int64), ps)
    return tuple(int(v) for v in row[:, 0])


def _exchange_rows(my_row: np.ndarray, ps: ProcessSet) -> np.ndarray:
    """Gather one small int row per rank → (k, len(row)) matrix on host."""
    k = ps.size()
    key = ("xrow", my_row.shape, ps.cache_token)

    def build() -> Callable:
        def body(block):
            return lax.all_gather(block[0], _AXIS, axis=0)[None]

        fn = jax.shard_map(body, mesh=ps.mesh, in_specs=P(_AXIS),
                           out_specs=P(_AXIS), check_vma=False)
        return jax.jit(fn)

    fn = _cache.get_or_build(key, build)
    g, _ = _to_global(my_row.astype(np.int64), ps)
    # Host readback blocks until every rank contributed — stall watchpoint.
    _stall_submit("exchange_rows")
    try:
        out = _execute(fn, g)
        shard = out.addressable_shards[0].data[0]
        return np.asarray(shard)
    except Exception as e:
        if isinstance(e, HorovodInternalError):
            raise
        if topology.raw_state().config.elastic and is_comm_failure(e):
            raise HorovodInternalError(
                f"size exchange failed: {e}") from e
        raise
    finally:
        _stall_done("exchange_rows")


def _stall_submit(name: str) -> None:
    si = topology.raw_state().stall_inspector
    if si is not None:
        si.submit(name)


def _stall_done(name: str) -> None:
    si = topology.raw_state().stall_inspector
    if si is not None:
        si.done(name)


def _consistency(desc: str, ps: ProcessSet,
                 name: Optional[str] = None) -> None:
    """Dispatch choke point for cross-rank call-sequence checking.

    Two independent verifiers hook here:

    * HOROVOD_CONSISTENCY_CHECK (core/consistency.py): synchronous
      per-call agreement on `desc` — the coordinator's mismatch
      checking, controller.cc:74-447, as an opt-in. Agreement runs
      among the process set's members only, on the set's own sequence —
      subset-set collectives must not involve (or desynchronize)
      outsiders.
    * HOROVOD_CHECK_COLLECTIVES (analysis/verifier.py): rolling
      fingerprint of (op-signature, name) tuples, cross-checked through
      the rendezvous KV every N calls — asymptotically free, raises
      CollectiveDivergenceError naming the divergent rank and call.
    """
    # Flight recorder first (observability/flight.py): one ring append
    # per dispatched collective, reusing the descriptor this choke point
    # already formatted — the always-on black box the doctor merges.
    _flight.record_collective(ps.process_set_id, desc, name or "")
    # hvdtrace ordering marker: an instant span under the ambient step
    # trace for dispatches whose duration the host cannot see (the
    # compiled path). Gated to a few loads when no trace is ambient.
    if _tracing.active():
        _tracing.record_dispatch(desc, name or "")
    from horovod_tpu.core import consistency as _cc
    from horovod_tpu.analysis import verifier as _vf
    checker = _cc.get()
    v = _vf.get()
    if checker is None and v is None:
        return
    ranks = ps.ranks  # None ⇒ world
    if ranks is None:
        group = "world"
    else:
        import hashlib as _hl
        member_tag = _hl.sha256(repr(tuple(ranks)).encode()).hexdigest()
        group = f"ps{ps.process_set_id}-{member_tag[:12]}"
    if checker is not None:
        checker.check(desc, ranks=ranks, group=group)
    if v is not None:
        # Scoped per process set, like the checker: only members
        # dispatch on a subset set, so it has its own sequence.
        v.record(f"{desc}|name={name}" if name else desc,
                 ranks=ranks, group=group)


# ---------------------------------------------------------------- metrics

_mx_cache = None
_cum_bytes: Dict[str, float] = {}
_cum_lock = threading.Lock()
_dtype_cache: Dict[Any, Tuple[int, str]] = {}


def _dtype_info(dt) -> Tuple[int, str]:
    """(itemsize, canonical name) memoized per dtype object — np.dtype()
    construction and str(dtype) cost ~10 us each, too hot for per-call."""
    info = _dtype_cache.get(dt)
    if info is None:
        ndt = np.dtype(dt)
        info = (ndt.itemsize, str(ndt))
        _dtype_cache[dt] = info
    return info


def _mx():
    """Lazy hot-path instrument handles (observability/metrics.py).
    Cached per registry instance; when metrics are disabled every family
    is the shared NOOP, so recording costs one no-op method call."""
    global _mx_cache
    from horovod_tpu.observability import metrics as m
    reg = m.registry()
    if _mx_cache is None or _mx_cache[0] is not reg:
        _mx_cache = (reg, {
            "calls": reg.counter(
                "horovod_collective_calls_total",
                "Eager collective calls", labelnames=("op", "dtype")),
            "bytes": reg.counter(
                "horovod_collective_bytes_total",
                "Global payload bytes moved by collectives",
                labelnames=("op", "dtype")),
            "seconds": reg.histogram(
                "horovod_collective_seconds",
                "Host-side wall time per collective call (dispatch under "
                "async, full completion in elastic mode)",
                labelnames=("op",), buckets=m.TIME_BUCKETS),
            "group": reg.histogram(
                "horovod_grouped_fusion_tensors",
                "Tensors per grouped (fused) collective call",
                labelnames=("op",), buckets=m.COUNT_BUCKETS),
            "cache": reg.counter(
                "horovod_compile_cache_total",
                "Compiled-executable cache lookups",
                labelnames=("event",)),
            "bucket_n": reg.counter(
                "horovod_bucket_dispatch_total",
                "Fusion buckets dispatched by the pipelined allreduce"),
            "bucket_bytes": reg.histogram(
                "horovod_bucket_bytes",
                "Global payload bytes per dispatched fusion bucket",
                buckets=m.SIZE_BUCKETS),
            "bucket_secs": reg.histogram(
                "horovod_bucket_seconds",
                "Per-bucket launch-to-complete wall time (profiled "
                "bucketed_allreduce calls only)",
                buckets=m.TIME_BUCKETS),
            "overlap": reg.gauge(
                "horovod_overlap_fraction",
                "Estimated fraction of bucket in-flight time shared with "
                "another bucket (1 - wall_window / sum_of_bucket_spans; "
                "profiled calls only)"),
            "axis_bytes": reg.counter(
                "horovod_axis_comms_bytes_total",
                "Eager collective payload bytes attributed to a named "
                "mesh axis (process sets built by axis_process_set; "
                "docs/parallelism.md)", labelnames=("axis", "op")),
            "stall_warn": reg.counter(
                "horovod_stall_warnings_total",
                "Stall warnings", labelnames=("source",)),
            "stall_shut": reg.counter(
                "horovod_stall_shutdowns_total",
                "Stall shutdown raises (elastic watchdog)"),
        })
    return _mx_cache[1]


def _record(activity: str, arrays, nbytes_fn, ntensors, seconds,
            tl, axis=None) -> None:
    """Post-call accounting (metrics enabled only): counters, the wall-
    time histogram, and a per-op cumulative-bytes counter track in the
    live timeline so the trace shows byte throughput next to the spans."""
    op = activity.lower()
    mx = _mx()
    nbytes = 0
    dtype = ""
    for a in arrays:
        try:
            isize, dname = _dtype_info(a.dtype)
            dtype = dtype or dname
            nbytes += int(a.size) * isize
        except Exception:
            pass
    if nbytes_fn is not None:
        try:
            extra_bytes, extra_dtype = nbytes_fn()
            nbytes += extra_bytes
            dtype = dtype or extra_dtype
        except Exception:
            pass
    mx["calls"].labels(op=op, dtype=dtype).inc()
    if nbytes:
        mx["bytes"].labels(op=op, dtype=dtype).inc(nbytes)
        if axis:
            # Per-axis comms attribution (docs/parallelism.md): eager
            # traffic over an axis_process_set sub-communicator lands in
            # its axis's series — the dp/tp split the hybrid backend's
            # scaling analysis reads.
            mx["axis_bytes"].labels(axis=axis, op=op).inc(nbytes)
    mx["seconds"].labels(op=op).observe(seconds)
    if ntensors is not None:
        mx["group"].labels(op=op).observe(ntensors)
    if tl is not None:
        with _cum_lock:
            _cum_bytes[op] = cum = _cum_bytes.get(op, 0.0) + nbytes
        tl.counter("horovod_collective_bytes_total", {op: cum})


class _instrument:
    """EXECUTE-style timeline span + metrics around eager dispatch
    (reference: the per-tensor op-activity spans, timeline.cc +
    operations.cc:286-330). Under async dispatch the measured window
    covers host-side dispatch; in elastic mode (_execute forces
    completion) it covers the full collective.

    Byte counts are computed lazily — from `arrays` (already-lifted
    global payloads) or `nbytes_fn` (fast paths that never materialize a
    global array) — only when metrics are enabled, so with both
    HOROVOD_METRICS=0 and HOROVOD_PERFSCOPE=0 the hot path pays a couple
    of cheap gates and no clock reads. With perfscope live the window is
    also attributed to the step's `comms` phase (minus whatever inner
    hooks — a compile on a cache miss — already re-attributed)."""

    __slots__ = ("name", "activity", "arrays", "nbytes_fn", "ntensors",
                 "tl", "enabled", "ps", "timed", "t0", "attr_mark",
                 "axis")

    def __init__(self, name: str, activity: str, arrays: Sequence = (),
                 nbytes_fn: Optional[Callable] = None,
                 ntensors: Optional[int] = None,
                 axis: Optional[str] = None) -> None:
        self.name = name
        self.activity = activity
        self.arrays = arrays
        self.nbytes_fn = nbytes_fn
        self.ntensors = ntensors
        self.axis = axis

    def __enter__(self) -> "_instrument":
        from horovod_tpu.observability import metrics as m
        self.enabled = m.registry().enabled
        self.ps = _pscope.get()
        self.tl = topology.state().timeline
        if self.tl is not None:
            self.tl.span_begin(self.name, self.activity)
        # Clock reads only when someone consumes the window (metrics
        # or a live perfscope) — the fully-disabled path stays free.
        self.timed = self.enabled or self.ps is not _pscope.NOOP
        if self.timed:
            self.attr_mark = self.ps.attributed_marker()
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.timed:
            dt = time.perf_counter() - self.t0
        if self.tl is not None:
            self.tl.span_end(self.name, self.activity)
        if self.timed:
            # Step-phase attribution (profiler/perfscope.py): this
            # window is `comms` time, minus nested re-attributions.
            nested = self.ps.attributed_marker() - self.attr_mark
            self.ps.attribute("comms", dt - nested)
            if _tracing.active():
                # Per-collective child span under the ambient step
                # trace (observability/tracing.py) — the measured eager
                # dispatch window, with bytes when they are computable
                # without lifting anything.
                nbytes = None
                try:
                    if self.arrays:
                        nbytes = float(sum(a.nbytes for a in self.arrays))
                    elif self.nbytes_fn is not None:
                        nbytes = float(self.nbytes_fn())
                except Exception:
                    nbytes = None
                _tracing.collective_span(self.name, self.activity, dt,
                                         nbytes)
        if self.enabled:
            _record(self.activity, self.arrays, self.nbytes_fn,
                    self.ntensors, dt, self.tl, axis=self.axis)
        return False
