"""Max pooling with a fast backward (select-and-scatter replacement).

XLA lowers the gradient of `lax.reduce_window(max)` to SelectAndScatter,
which is notoriously slow on TPU — measured ~24% of the Inception V3
train step (4 stride-2 3x3 pools; docs/benchmarks.md r05). This module
keeps the FORWARD as the stock reduce_window (fast) and replaces only
the backward with the standard one-hot formulation, expressed entirely
in elementwise ops + static slices + interior-padded adds that XLA
fuses freely:

    for window offset k (iteration order):
        m_k      = (x_shifted_k == y)            # max attained here?
        chosen_k = m_k and not (m_0 or ... or m_{k-1})   # FIRST max
        dx      += scatter_k(chosen_k * dy)      # interior-padded add

The first-match tie-break replicates SelectAndScatter's GE-select
semantics exactly, so gradients are bit-comparable to the stock VJP
(tie cases pinned in tests/test_pooling.py).

MEASURED OUTCOME (r05, v5e, scripts/maxpool_bwd_ab.py): the one-hot
backward is 7-20x SLOWER than SelectAndScatter at every real pool site
(68 vs 3.6 ms at Inception's 147x147x64 stem pool). The formulation is
fusion-friendly HLO, but its building blocks — stride-2 `lax.slice`
reads and interior-padded writes — are pathological for the TPU's
(8, 128) tiled layouts (every strided row access breaks sublane tiles),
and 9 window offsets multiply that cost. SelectAndScatter is slow; this
is slower. The op therefore ships UNWIRED — models keep the stock
reduce_window VJP — and stands as the measured record that the
"obvious" XLA-level replacement loses (an input-centric Pallas kernel
could theoretically hit ~2.5 streams, but the conv+BN experience —
ops/conv_bn_backward.py — shows the boundary/layout costs of an opaque
kernel in this position, and the remaining upside is a few ms/step).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _out_dim(size: int, win: int, stride: int, pad_lo: int,
             pad_hi: int) -> int:
    return (size + pad_lo + pad_hi - win) // stride + 1


def _resolve_padding(padding, h, w, wh, ww, sh, sw):
    """'VALID'/'SAME' or explicit ((lo,hi),(lo,hi)) for the two spatial
    dims."""
    if isinstance(padding, str):
        if padding.upper() == "VALID":
            return (0, 0), (0, 0)
        if padding.upper() == "SAME":
            def same(size, win, stride):
                out = -(-size // stride)
                total = max((out - 1) * stride + win - size, 0)
                return total // 2, total - total // 2
            return same(h, wh, sh), same(w, ww, sw)
        raise ValueError(f"padding {padding!r}")
    (ph, pw) = padding
    return tuple(ph), tuple(pw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pool(x: jax.Array, window: Sequence[int] = (3, 3),
             strides: Sequence[int] = (2, 2),
             padding="VALID") -> jax.Array:
    """NHWC max pool over the two spatial dims; forward is the stock
    reduce_window, backward the fast one-hot path."""
    return _fwd_pool(x, window, strides, padding)


def _fwd_pool(x, window, strides, padding):
    wh, ww = window
    sh, sw = strides
    ph, pw = _resolve_padding(padding, x.shape[1], x.shape[2],
                              wh, ww, sh, sw)
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min,
        lax.max, (1, wh, ww, 1), (1, sh, sw, 1),
        ((0, 0), ph, pw, (0, 0)))


def _max_pool_fwd(x, window, strides, padding):
    y = _fwd_pool(x, window, strides, padding)
    return y, (x, y)


def _max_pool_bwd(window, strides, padding, res, dy):
    x, y = res
    wh, ww = window
    sh, sw = strides
    n, h, w, c = x.shape
    ph, pw = _resolve_padding(padding, h, w, wh, ww, sh, sw)
    oh, ow = y.shape[1], y.shape[2]
    # Work on the padded input so every window is full; slices below are
    # all static. Padding value never equals a real max (-inf).
    pad_val = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
               else jnp.iinfo(x.dtype).min)
    hp = h + ph[0] + ph[1]
    wp = w + pw[0] + pw[1]
    xp = jnp.pad(x, ((0, 0), ph, pw, (0, 0)), constant_values=pad_val)
    dxp = jnp.zeros((n, hp, wp, c), dy.dtype)
    taken = None
    dyf = dy
    for a in range(wh):
        for b in range(ww):
            # window-offset (a, b) element of every window: shape (oh, ow)
            xs = lax.slice(
                xp, (0, a, b, 0),
                (n, a + (oh - 1) * sh + 1, b + (ow - 1) * sw + 1, c),
                (1, sh, sw, 1))
            m = xs == y
            chosen = m if taken is None else jnp.logical_and(
                m, jnp.logical_not(taken))
            taken = m if taken is None else jnp.logical_or(taken, m)
            contrib = jnp.where(chosen, dyf, jnp.zeros((), dy.dtype))
            # scatter to input positions (a + sh*i, b + sw*j): interior
            # padding re-dilates the output grid onto the input grid
            dxp = dxp + lax.pad(
                contrib, jnp.zeros((), dy.dtype),
                ((0, 0, 0),
                 (a, hp - a - ((oh - 1) * sh + 1), sh - 1),
                 (b, wp - b - ((ow - 1) * sw + 1), sw - 1),
                 (0, 0, 0)))
    dx = lax.slice(dxp, (0, ph[0], pw[0], 0),
                   (n, ph[0] + h, pw[0] + w, c))
    return (dx.astype(x.dtype),)


max_pool.defvjp(_max_pool_fwd, _max_pool_bwd)
