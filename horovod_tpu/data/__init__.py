"""Data loading helpers (reference: horovod/data/data_loader_base.py)."""

from horovod_tpu.data.data_loader import (  # noqa: F401
    AsyncDataLoaderMixin, BaseDataLoader, DeviceFeed, ShardedDataset,
)
