"""Data loaders: per-rank sharding + background prefetch.

Reference: horovod/data/data_loader_base.py — `BaseDataLoader` and
`AsyncDataLoaderMixin` (:48-135, background-thread prefetch queue) — plus
the ElasticSampler's shard-by-rank semantics (torch/elastic/sampler.py).

TPU notes: the prefetch thread overlaps host-side batch assembly with
device steps (JAX dispatch is async, so one queue depth of prefetch hides
most input latency); `ShardedDataset` shards by (rank, size) the way every
reference example does (`dataset.shard(num_shards=hvd.size(),
index=hvd.rank())`).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional


class BaseDataLoader:
    """Iterable loader contract (reference: data_loader_base.py:20).

    Subclasses may define __len__; the base deliberately does not — a
    raising __len__ would break list(loader), which probes len() as a
    preallocation hint.
    """

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Background-thread prefetch (reference: data_loader_base.py:48).

    Mix in BEFORE the loader class:
        class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader): ...
    `async_loader_queue_size=0` disables prefetch (synchronous passthrough).
    """

    def __init__(self, *args, async_loader_queue_size: int = 4, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._closing = False
        super().__init__(*args, **kwargs)

    def close_async_loader(self) -> None:
        """Reference: close_async_loader (:73) — drain and join."""
        self._closing = True
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._started = False

    def _async_worker(self) -> None:
        """Producer thread (reference: _async_worker :95)."""
        try:
            for batch in super()._iterate():
                if self._closing:
                    break
                self._queue.put(batch)
        finally:
            self._queue.put(None)  # end-of-epoch sentinel

    def _iterate(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            yield from super()._iterate()
            return
        self._queue = queue.Queue(self.async_loader_queue_size)
        self._closing = False
        self._thread = threading.Thread(target=self._async_worker,
                                        daemon=True)
        self._thread.start()
        while True:
            batch = self._queue.get()
            if batch is None:
                break
            yield batch
        self._thread.join(timeout=10)
        self._thread = None


class ShardedDataset(BaseDataLoader):
    """Shard an indexable dataset by rank (reference pattern:
    torch DistributedSampler / elastic sampler shard semantics —
    torch/elastic/sampler.py). Supports set_epoch for reshuffling and
    record skipping for elastic mid-epoch resume
    (ElasticSampler.record_batch)."""

    def __init__(self, data, rank: int, size: int, batch_size: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True):
        import numpy as np
        self.data = data
        self.rank = rank
        self.size = size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.processed_indices: int = 0
        self._np = np

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.processed_indices = 0

    def record_batch(self) -> None:
        """Mark one batch consumed (for elastic resume)."""
        self.processed_indices += self.batch_size

    def _indices(self):
        np = self._np
        n = len(self.data)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        # Pad to a multiple of size*batch so every rank sees equal batches.
        per = self.size * self.batch_size
        if self.drop_last:
            idx = idx[: (n // per) * per]
        else:
            pad = (-n) % per
            idx = np.concatenate([idx, idx[:pad]])
        mine = idx[self.rank::self.size]
        return mine[self.processed_indices:]

    def __len__(self) -> int:
        return len(self._indices()) // self.batch_size

    def _iterate(self):
        mine = self._indices()
        for i in range(0, len(mine) - self.batch_size + 1, self.batch_size):
            batch_idx = mine[i:i + self.batch_size]
            yield [self.data[int(j)] for j in batch_idx]
