"""Data loaders: per-rank sharding, background prefetch, and the
device-resident double-buffered feed.

Reference: horovod/data/data_loader_base.py — `BaseDataLoader` and
`AsyncDataLoaderMixin` (:48-135, background-thread prefetch queue) — plus
the ElasticSampler's shard-by-rank semantics (torch/elastic/sampler.py).

TPU notes: the prefetch thread overlaps host-side batch assembly with
device steps (JAX dispatch is async, so one queue depth of prefetch hides
most input latency); `ShardedDataset` shards by (rank, size) the way every
reference example does (`dataset.shard(num_shards=hvd.size(),
index=hvd.rank())`). `DeviceFeed` goes one level further (ROADMAP conv-MFU
item, docs/perf.md "conv fast path"): the prefetch thread also stages the
*next* batch onto the device (`jax.device_put` off the critical path), so
the training thread's `next()` hands back an already-device-resident batch
and the step never pays a host→device transfer on the critical path.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional


class BaseDataLoader:
    """Iterable loader contract (reference: data_loader_base.py:20).

    Subclasses may define __len__; the base deliberately does not — a
    raising __len__ would break list(loader), which probes len() as a
    preallocation hint.
    """

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Background-thread prefetch (reference: data_loader_base.py:48).

    Mix in BEFORE the loader class:
        class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader): ...
    `async_loader_queue_size=0` disables prefetch (synchronous passthrough).
    """

    def __init__(self, *args, async_loader_queue_size: int = 4, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._closing = False
        super().__init__(*args, **kwargs)

    def close_async_loader(self) -> None:
        """Reference: close_async_loader (:73) — drain and join."""
        self._closing = True
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._started = False

    def _async_worker(self) -> None:
        """Producer thread (reference: _async_worker :95)."""
        try:
            for batch in super()._iterate():
                if self._closing:
                    break
                self._queue.put(batch)
        finally:
            self._queue.put(None)  # end-of-epoch sentinel

    def _iterate(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            yield from super()._iterate()
            return
        self._queue = queue.Queue(self.async_loader_queue_size)
        self._closing = False
        self._thread = threading.Thread(target=self._async_worker,
                                        daemon=True)
        self._thread.start()
        while True:
            batch = self._queue.get()
            if batch is None:
                break
            yield batch
        self._thread.join(timeout=10)
        self._thread = None


class ShardedDataset(BaseDataLoader):
    """Shard an indexable dataset by rank (reference pattern:
    torch DistributedSampler / elastic sampler shard semantics —
    torch/elastic/sampler.py). Supports set_epoch for reshuffling and
    record skipping for elastic mid-epoch resume
    (ElasticSampler.record_batch)."""

    def __init__(self, data, rank: int, size: int, batch_size: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True):
        import numpy as np
        self.data = data
        self.rank = rank
        self.size = size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.processed_indices: int = 0
        self._np = np

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.processed_indices = 0

    def record_batch(self) -> None:
        """Mark one batch consumed (for elastic resume)."""
        self.processed_indices += self.batch_size

    def skip_to(self, processed: int) -> None:
        """Position the stream at an absolute per-rank record offset —
        the checkpoint data-cursor restore
        (elastic.TrainLoopState.apply_to_loader): a mid-epoch resume
        continues from the first unconsumed record of the SAME
        shuffled order (epoch seed unchanged) instead of replaying the
        epoch from record 0."""
        self.processed_indices = max(0, int(processed))

    def _indices(self):
        np = self._np
        n = len(self.data)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        # Pad to a multiple of size*batch so every rank sees equal batches.
        per = self.size * self.batch_size
        if self.drop_last:
            idx = idx[: (n // per) * per]
        else:
            pad = (-n) % per
            idx = np.concatenate([idx, idx[:pad]])
        mine = idx[self.rank::self.size]
        return mine[self.processed_indices:]

    def __len__(self) -> int:
        return len(self._indices()) // self.batch_size

    def _iterate(self):
        mine = self._indices()
        for i in range(0, len(mine) - self.batch_size + 1, self.batch_size):
            batch_idx = mine[i:i + self.batch_size]
            yield [self.data[int(j)] for j in batch_idx]


class DeviceFeed:
    """Device-resident double-buffered input feed (docs/perf.md).

    A background thread pulls host batches from `source`, stages each
    one onto the device with ``jax.device_put`` (under `sharding` when
    given), and parks the resulting device arrays in a bounded queue.
    While the current step runs, the NEXT batch's host→device transfer
    is already in flight — `depth=2` is classic double buffering: one
    slot being consumed, one being staged, alternating. Consumed slots
    are simply dropped (JAX frees the donated-out buffer as soon as the
    training step's last reference dies), so at most `depth` batches
    are ever device-resident.

    perfscope integration: the ONLY blocking point — the queue get when
    the producer has fallen behind — is wrapped in the ambient scope's
    ``input_wait`` phase, so starvation is *measured*, not guessed
    (the acceptance metric for the device-resident pipeline:
    ``input_wait`` < 5% of step wall). A fully prefetched feed spends
    ~0 there; a starved one parks exactly the starvation time.

    ``depth=0`` degrades to the synchronous path (pull + stage inline
    inside ``input_wait``) — the "before" configuration the perfscope
    regression test pins against the double-buffered "after".

    The producer's per-batch hook ``data.feed.produce`` is a
    testing/faults.py injection site (latency there simulates a slow
    preprocessing tier, docs/resilience.md).
    """

    _SENTINEL = object()

    def __init__(self, source: Iterable[Any], sharding=None,
                 depth: int = 2, put: Optional[Callable] = None,
                 scope=None):
        self.source = iter(source)
        self.sharding = sharding
        self.depth = int(depth)
        self._put_fn = put
        self._scope = scope
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        if self.depth > 0:
            self._q = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(target=self._produce,
                                            name="hvd-device-feed",
                                            daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ staging
    def _stage(self, batch):
        """Host batch → device arrays (every array leaf device_put,
        non-array leaves passed through)."""
        if self._put_fn is not None:
            return self._put_fn(batch)
        import jax

        def put(leaf):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                return jax.device_put(leaf, self.sharding) \
                    if self.sharding is not None else jax.device_put(leaf)
            return leaf

        return jax.tree_util.tree_map(put, batch)

    def _produce(self) -> None:
        from horovod_tpu.testing import faults
        try:
            for batch in self.source:
                if self._stop.is_set():
                    return
                faults.inject("data.feed.produce")
                staged = self._stage(batch)
                if not self._bounded_put(staged):
                    return
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            self._bounded_put(self._SENTINEL)

    def _bounded_put(self, item) -> bool:
        """Bounded-queue put that stays responsive to close() — same
        rationale as data/service._Stream._put: a plain put() leaks the
        producer thread blocked forever once the consumer is gone."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    # ----------------------------------------------------------- consume
    def _perfscope(self):
        if self._scope is not None:
            return self._scope
        from horovod_tpu.profiler import perfscope
        return perfscope.get()

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        if self.depth <= 0:
            # synchronous "before" path: pull + stage on the critical
            # path, all of it measured as input_wait
            from horovod_tpu.testing import faults
            with self._perfscope().phase("input_wait"):
                batch = next(self.source)
                faults.inject("data.feed.produce")
                return self._stage(batch)
        with self._perfscope().phase("input_wait"):
            # Stop-aware poll, not a bare get(): close() drains the
            # queue and the stopped producer's sentinel put is refused
            # (_bounded_put), so a consumer already blocked here — or
            # arriving after close() — would otherwise hang forever.
            while True:
                try:
                    item = self._q.get(timeout=0.2)
                    break
                except queue.Empty:
                    if self._stop.is_set():
                        raise StopIteration  # feed closed under us
        if item is self._SENTINEL:
            self._q.put(item)  # keep raising for later calls
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def _drain(self) -> None:
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def close(self, timeout: float = 2.0) -> bool:
        """Stop the producer and drop staged batches (their device
        buffers free when the last consumer reference dies). Returns
        True when the producer thread actually exited.

        A producer blocked INSIDE the source — a data-service stream's
        framed-TCP recv, say — cannot be interrupted from here: the
        stop flag is only checked between batches and in the bounded
        put. The (daemon) thread then exits at the source's next
        yield/raise; unblock it by closing the source's transport
        (stopping the data workers / dispatcher). In that case the
        thread reference is deliberately KEPT — returning False with
        the thread observable beats pretending it is gone — and the
        queue is left empty, so `_bounded_put` (stop flag set) can
        never park another device batch."""
        self._stop.set()
        if self._q is not None:
            self._drain()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                return False
            self._thread = None
        if self._q is not None:
            self._drain()  # a put that raced the first drain
        return True
