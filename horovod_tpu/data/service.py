"""Standalone data-preprocessing service.

Reference: horovod/tensorflow/data/compute_service.py (+compute_worker.py)
— a tf.data service (dispatcher + N workers) runs inside/alongside the
training job so input preprocessing scales independently of the trainers.

TPU-first redesign: trainers are MXU-bound and must never stall on host
preprocessing; the service here is framework-free (numpy batches over
length-prefixed TCP frames) so the same workers feed JAX, torch, or TF
trainers. Topology follows the reference's two-sided split:

  * `DataDispatcher` — registry only (worker addresses + pickled dataset
    fns). Batches never flow through it, so it is never a bandwidth
    bottleneck (the reference dispatcher likewise only coordinates).
  * `DataWorker` — owns shard `i of n` of a registered dataset: runs the
    user's `dataset_fn(shard, num_shards)` generator and serves batches
    to clients on demand, with a small prefetch queue per stream.
  * `DataServiceClient.stream(name)` — iterator over all shards'
    batches, fanned in round-robin from every worker.

All frames carry the job's HMAC digest (runner/secret.py); a secret is
REQUIRED (frames are pickled — see _require_secret) — same trust model
as the rendezvous KV.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from horovod_tpu.runner import secret as secret_mod

_LEN = struct.Struct("!I")
_MAX_FRAME = 1 << 30


class DataServiceError(RuntimeError):
    pass


def _require_secret(secret: Optional[bytes]) -> bytes:
    """Authentication is NOT optional: frames are pickled (and
    register_dataset ships cloudpickled callables by design), so an
    unauthenticated listener on 0.0.0.0 is arbitrary code execution for
    anyone who can reach the port. The reference's service wire protocol
    likewise requires the per-job secret unconditionally
    (runner/common/service/*, secret-keyed wire). Falls back to the job
    secret in HOROVOD_SECRET_KEY (set by the launcher)."""
    secret = secret or secret_mod.secret_from_env()
    if not secret:
        raise ValueError(
            "the data service requires an HMAC secret: pass secret=..., "
            "or run under the launcher / set HOROVOD_SECRET_KEY "
            "(see horovod_tpu.runner.secret.make_secret_key)")
    return secret


# ----------------------------------------------------------------------
# framing
# ----------------------------------------------------------------------

def _send_frame(sock: socket.socket, obj: Any,
                secret: Optional[bytes]) -> None:
    # hvdtrace context propagation: when a sampled trace is ambient on
    # this thread, the frame object is wrapped so the causal identifier
    # crosses the process boundary. No wire-format change — the whole
    # object is pickled either way, and _recv_frame unwraps
    # transparently (observability/tracing.py).
    try:
        from horovod_tpu.observability import tracing
        ctx = tracing.current_context()
        if ctx is not None and not tracing.suppressed():
            obj = {"__hvdtrace__": ctx, "o": obj}
    except Exception:
        pass  # tracing must never break the data plane
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    digest = (secret_mod.compute_digest(secret, "FRAME", "data", payload)
              .encode() if secret else b"")
    head = _LEN.pack(len(digest)) + digest + _LEN.pack(len(payload))
    sock.sendall(head + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


_MAX_DIGEST = 256  # hex sha256 is 64 bytes; anything bigger is hostile


def _recv_frame(sock: socket.socket, secret: Optional[bytes]) -> Any:
    dlen = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    if dlen > _MAX_DIGEST:
        raise DataServiceError(f"digest length {dlen} exceeds bound")
    digest = _recv_exact(sock, dlen) if dlen else b""
    plen = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    if plen > _MAX_FRAME:
        raise DataServiceError(f"frame too large: {plen}")
    payload = _recv_exact(sock, plen)
    if secret:
        if not secret_mod.check_digest(secret, "FRAME", "data", payload,
                                       digest.decode() if digest else None):
            raise DataServiceError("bad or missing frame HMAC")
    obj = pickle.loads(payload)
    if isinstance(obj, dict) and "__hvdtrace__" in obj and "o" in obj:
        # A trace context rode this frame: make it the receiving
        # thread's ambient parent, then hand the caller the original
        # object. Server loops clear the ambient context after each
        # handled request (_serve) so it cannot leak across requests.
        try:
            from horovod_tpu.observability import tracing
            tracing.adopt(obj["__hvdtrace__"])
        except Exception:
            pass
        obj = obj["o"]
    return obj


def _routable_local_addr(peer: Tuple[str, int]) -> str:
    """The local address of the route to `peer` (no traffic sent)."""
    try:
        with socket.create_connection(peer, timeout=10) as s:
            return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def _rpc(addr: Tuple[str, int], obj: Any, secret: Optional[bytes],
         timeout: float = 30.0) -> Any:
    with socket.create_connection(addr, timeout=timeout) as s:
        _send_frame(s, obj, secret)
        return _recv_frame(s, secret)


class _FrameServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _serve(handler: Callable[[Any], Any], secret: Optional[bytes],
           port: int = 0) -> Tuple[_FrameServer, int]:
    class H(socketserver.BaseRequestHandler):
        def handle(self):
            # Persistent connections: serve request/response pairs until
            # the peer hangs up — the client's stream iterator holds one
            # socket per worker instead of reconnecting per batch.
            while True:
                try:
                    req = _recv_frame(self.request, secret)
                except (ConnectionError, OSError):
                    return
                except Exception as e:
                    # bad HMAC, unpicklable payload, oversized digest …:
                    # reply with a diagnosable error, then drop the peer
                    try:
                        _send_frame(self.request,
                                    ("error",
                                     f"{type(e).__name__}: {e}"), secret)
                    except (ConnectionError, OSError):
                        pass
                    return
                try:
                    resp = handler(req)
                except Exception as e:
                    resp = ("error", f"{type(e).__name__}: {e}")
                try:
                    _send_frame(self.request, resp, secret)
                except (ConnectionError, OSError):
                    return
                finally:
                    # A traced request's adopted context must not leak
                    # into the NEXT request on this persistent
                    # connection (the reply above still rides it).
                    try:
                        from horovod_tpu.observability import tracing
                        tracing.clear()
                    except Exception:
                        pass

    srv = _FrameServer(("0.0.0.0", port), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


# ----------------------------------------------------------------------
# dispatcher
# ----------------------------------------------------------------------

class DataDispatcher:
    """Coordination point (reference: compute_service.py dispatcher side).

    Holds worker registrations and pickled dataset definitions; assigns
    shard ids first-come-first-served per dataset.
    """

    def __init__(self, expected_workers: int,
                 secret: Optional[bytes] = None):
        self.expected_workers = expected_workers
        self._secret = _require_secret(secret)
        self._lock = threading.Lock()
        self._workers: List[Tuple[str, int]] = []
        self._datasets: Dict[str, bytes] = {}
        self._shard_next: Dict[str, int] = {}
        self._srv = None
        self.port: Optional[int] = None

    def start(self) -> int:
        self._srv, self.port = _serve(self._handle, self._secret)
        return self.port

    def stop(self) -> None:
        if self._srv:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None

    def _handle(self, req):
        kind = req[0]
        with self._lock:
            if kind == "register_worker":
                addr = tuple(req[1])
                if addr not in self._workers:
                    self._workers.append(addr)
                return ("ok", len(self._workers))
            if kind == "register_dataset":
                _, name, blob = req
                self._datasets[name] = blob
                self._shard_next.setdefault(name, 0)
                return ("ok", None)
            if kind == "get_dataset":
                _, name = req
                blob = self._datasets.get(name)
                if blob is None:
                    return ("pending", None)
                shard = self._shard_next[name]
                if shard >= self.expected_workers:
                    # All shards assigned: a late/restarted worker gets
                    # none — serving a wrapped shard id would silently
                    # duplicate data into training.
                    return ("exhausted", None)
                self._shard_next[name] = shard + 1
                return ("ok", (blob, shard, self.expected_workers))
            if kind == "workers":
                ready = len(self._workers) >= self.expected_workers
                return ("ok", (list(self._workers), ready))
            if kind == "datasets":
                return ("ok", list(self._datasets))
        return ("error", f"unknown request {kind!r}")


# ----------------------------------------------------------------------
# worker
# ----------------------------------------------------------------------

class DataWorker:
    """Owns one shard per registered dataset and serves its batches.

    `dataset_fn(shard, num_shards)` must return an iterator/generator of
    batches (any picklable object — typically dict of numpy arrays).
    A prefetch thread keeps `prefetch` batches ready per stream so client
    latency hides preprocessing time (reference analog: tf.data service
    workers prefetch; here it is explicit).
    """

    def __init__(self, dispatcher: Tuple[str, int],
                 secret: Optional[bytes] = None, prefetch: int = 4,
                 poll_interval: float = 0.1,
                 dispatcher_timeout: float = 300.0,
                 advertise_addr: Optional[str] = None):
        self.dispatcher = dispatcher
        self._secret = _require_secret(secret)
        self.advertise_addr = advertise_addr
        self.prefetch = prefetch
        self.poll_interval = poll_interval
        self.dispatcher_timeout = dispatcher_timeout
        self._streams: Dict[str, "_Stream"] = {}
        self._lock = threading.Lock()
        self._srv = None
        self.port: Optional[int] = None

    def start(self) -> int:
        self._srv, self.port = _serve(self._handle, self._secret)
        # Advertise the address the DISPATCHER route actually uses — on
        # multi-NIC/container hosts gethostbyname(gethostname()) commonly
        # resolves to 127.0.0.1 or an unroutable NIC (the silent failure
        # runner/network.py exists to fix).
        host = self.advertise_addr or _routable_local_addr(self.dispatcher)
        st = _rpc(self.dispatcher,
                  ("register_worker", (host, self.port)), self._secret)
        if st[0] != "ok":
            raise DataServiceError(f"worker registration failed: {st}")
        # Discover datasets proactively so prefetch starts at
        # registration time, not at the first client request.
        self._stopping = threading.Event()
        self._poller = threading.Thread(target=self._poll_datasets,
                                        daemon=True)
        self._poller.start()
        return self.port

    def _poll_datasets(self) -> None:
        while not self._stopping.is_set():
            try:
                st = _rpc(self.dispatcher, ("datasets",), self._secret,
                          timeout=5.0)
                if st[0] == "ok":
                    for name in st[1]:
                        self._stream(name)
            except (OSError, ConnectionError, DataServiceError):
                pass  # dispatcher restarting/stopping; retry
            self._stopping.wait(self.poll_interval)

    def stop(self) -> None:
        if getattr(self, "_stopping", None):
            self._stopping.set()
        if self._srv:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        with self._lock:
            for s in self._streams.values():
                s.stop()

    def _stream(self, name: str) -> "_Stream":
        with self._lock:
            st = self._streams.get(name)
            if st is None:
                st = _Stream(self, name)
                self._streams[name] = st
            return st

    def _handle(self, req):
        if req[0] == "next_batch":
            _, name = req
            return self._stream(name).next_response()
        return ("error", f"unknown request {req[0]!r}")


class _Stream:
    """One dataset shard's produced-batch queue on a worker."""

    def __init__(self, worker: DataWorker, name: str):
        import queue

        self.name = name
        self.q: "queue.Queue" = queue.Queue(maxsize=worker.prefetch)
        self._done = False
        self._stop = threading.Event()
        self._worker = worker
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded-queue put that stays responsive to stop().

        A plain q.put() blocks FOREVER once the prefetch queue is full
        and the consumer is gone — the exact shape of an abrupt client
        disconnect: the handler thread dies with the connection, nobody
        drains the queue, and the producer thread leaks blocked in put()
        past worker.stop(). Poll with a short timeout instead, so the
        producer notices the stop flag and exits promptly.
        """
        import queue

        while not self._stop.is_set():
            try:
                self.q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self):
        import cloudpickle

        w = self._worker
        deadline = None  # armed at the FIRST failed poll, reset on success
        while not self._stop.is_set():  # wait for the dataset definition
            try:
                st = _rpc(w.dispatcher, ("get_dataset", self.name),
                          w._secret)
                deadline = None
            except (OSError, ConnectionError, DataServiceError) as e:
                # transient dispatcher outage: keep polling, but bounded —
                # dying silently here would leave clients blocked in
                # next_batch with no 'end'/'error' sentinel ever queued
                if deadline is None:
                    deadline = time.monotonic() + w.dispatcher_timeout
                if time.monotonic() > deadline:
                    self._put(("error",
                               f"dispatcher unreachable: {e}"))
                    self._put(("end", None))
                    return
                time.sleep(w.poll_interval)
                continue
            if st[0] == "ok":
                blob, shard, num_shards = st[1]
                break
            if st[0] == "exhausted":
                # late/restarted worker: no shard left — empty stream
                self._put(("end", None))
                return
            time.sleep(w.poll_interval)
        else:
            return
        try:
            fn = cloudpickle.loads(blob)
            for batch in fn(shard, num_shards):
                if self._stop.is_set():
                    return
                if not self._put(("batch", batch)):
                    return  # stopped while the queue was full
        except Exception as e:  # surface preprocessing errors to clients
            self._put(("error", f"{type(e).__name__}: {e}"))
        self._put(("end", None))

    def next_response(self):
        item = self.q.get()
        if item[0] == "end":
            self._done = True
            self.q.put(item)  # keep returning end to later requests
        return item

    def stop(self):
        self._stop.set()


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------

class DataServiceClient:
    """Training-side handle (reference: compute_service.py's
    send_to_data_service / TfDataServiceConfig round trip)."""

    def __init__(self, dispatcher: Tuple[str, int],
                 secret: Optional[bytes] = None):
        self.dispatcher = dispatcher
        self._secret = _require_secret(secret)

    def register_dataset(self, name: str,
                         dataset_fn: Callable[[int, int], Iterator[Any]]
                         ) -> None:
        import cloudpickle

        st = _rpc(self.dispatcher,
                  ("register_dataset", name, cloudpickle.dumps(dataset_fn)),
                  self._secret)
        if st[0] != "ok":
            raise DataServiceError(f"register_dataset failed: {st}")

    def wait_for_workers(self, timeout: float = 60.0) -> List[Tuple[str,
                                                                    int]]:
        deadline = time.monotonic() + timeout
        while True:
            st = _rpc(self.dispatcher, ("workers",), self._secret)
            workers, ready = st[1]
            if ready:
                return [tuple(w) for w in workers]
            if time.monotonic() > deadline:
                raise DataServiceError(
                    f"only {len(workers)} data workers registered "
                    f"before timeout")
            time.sleep(0.1)

    def device_stream(self, name: str, sharding=None, depth: int = 2,
                      timeout: float = 60.0):
        """`stream(name)` through the device-resident double-buffered
        feed (data/data_loader.DeviceFeed, docs/perf.md): the feed's
        prefetch thread pulls the next batch off the workers AND stages
        it onto the device while the current step runs, so the trainer
        never pays worker latency or the host→device transfer on the
        critical path, and any residual starvation is measured as
        perfscope ``input_wait``. Call `.close()` when done (stops the
        prefetch thread; the underlying worker connections close when
        the wrapped stream iterator is collected)."""
        from horovod_tpu.data.data_loader import DeviceFeed

        return DeviceFeed(self.stream(name, timeout=timeout),
                          sharding=sharding, depth=depth)

    def stream(self, name: str, timeout: float = 60.0) -> Iterator[Any]:
        """Yield batches from every worker's shard, round-robin fan-in.

        One persistent connection per worker for the whole stream — this
        is the training hot path, so per-batch connect/teardown churn
        (latency + ephemeral ports) is not acceptable.
        """
        workers = self.wait_for_workers(timeout)
        conns: Dict[Tuple[str, int], socket.socket] = {}
        try:
            for addr in workers:
                try:
                    conns[addr] = socket.create_connection(
                        addr, timeout=timeout)
                except OSError as e:
                    raise DataServiceError(
                        f"cannot connect to data worker {addr}: {e}")
            live = list(workers)
            while live:
                for addr in list(live):
                    s = conns[addr]
                    _send_frame(s, ("next_batch", name), self._secret)
                    st = _recv_frame(s, self._secret)
                    if st[0] == "batch":
                        yield st[1]
                    elif st[0] == "end":
                        live.remove(addr)
                    elif st[0] == "error":
                        raise DataServiceError(
                            f"data worker {addr} failed: {st[1]}")
        finally:
            for s in conns.values():
                try:
                    s.close()
                except OSError:
                    pass


def run_worker(dispatcher_addr: str, secret: Optional[bytes] = None
               ) -> DataWorker:
    """Convenience entry (reference: compute_worker.py main): start one
    worker against `host:port` and return it running."""
    host, port = dispatcher_addr.rsplit(":", 1)
    w = DataWorker((host, int(port)),
                   secret=secret or secret_mod.secret_from_env())
    w.start()
    return w
