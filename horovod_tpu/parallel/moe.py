"""Expert parallelism: top-1 routed mixture-of-experts FFN over the `ep` axis.

Not present in the reference (SURVEY.md §2.6 — `alltoall` is the substrate
it exposes for users to build this). TPU-native design: experts are sharded
one-group-per-rank over `ep`; tokens are dispatched with a capacity-bounded
one-hot einsum + `lax.all_to_all` (compiled onto ICI), processed by the
local experts' batched matmuls (MXU-friendly: one big einsum over
[experts_local, capacity, d]), and combined back with the transposed
all_to_all. Static shapes throughout — capacity bounds make the program
shape-stable for XLA, with overflow tokens dropped (standard Switch-style
routing).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(x: jax.Array,
            router_w: jax.Array,
            w1: jax.Array,
            w2: jax.Array,
            axis_name: str = "ep",
            capacity_factor: float = 1.25) -> jax.Array:
    """Top-1 MoE feed-forward.

    Per-shard shapes:
      x: (T, D) local tokens (flatten batch*seq before calling)
      router_w: (D, E) with E = total experts across the axis
      w1: (E_local, D, F), w2: (E_local, F, D) — this rank's experts
    Returns (T, D).
    """
    P = lax.axis_size(axis_name)
    T, D = x.shape
    E_local = w1.shape[0]
    E = E_local * P
    assert router_w.shape[1] == E, "router width must equal total experts"

    logits = x @ router_w                       # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)         # (T,)
    gate = jnp.max(probs, axis=-1)              # (T,)

    cap = max(1, int(capacity_factor * T / E))
    onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)          # (T, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot          # slot per token
    keep = (pos < cap) & (onehot > 0)
    slot = jnp.clip(pos.astype(jnp.int32), 0, cap - 1)
    # dispatch[t, e, c] = 1 iff token t goes to expert e at slot c.
    dispatch = keep.astype(x.dtype)[:, :, None] * \
        jax.nn.one_hot(slot, cap, dtype=x.dtype)               # (T, E, cap)

    xs = jnp.einsum("td,tec->ecd", x, dispatch)                # (E, cap, D)
    # Re-shard: chunk e∈[p*E_local,(p+1)*E_local) goes to rank p; received
    # slabs (one per source rank) stack along capacity → (E_local, P*cap, D)
    # where capacity segment s holds rank s's tokens.
    xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=1,
                        tiled=True)

    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xs, w1))
    ys = jnp.einsum("ecf,efd->ecd", h, w2)                     # (E_local, P*cap, D)

    # Inverse re-shard: capacity segment s returns to rank s; received
    # expert groups stack along axis 0 in rank (= global expert) order.
    ys = lax.all_to_all(ys, axis_name, split_axis=1, concat_axis=0,
                        tiled=True)                            # (E, cap, D)
    out = jnp.einsum("tec,ecd->td", dispatch, ys)
    return out * gate[:, None]
