"""Pipeline parallelism: GPipe-style microbatch schedule over the `pp` axis.

Not present in the reference (SURVEY.md §2.6). TPU-native design: every
pipeline stage is the same SPMD program; stage identity comes from
`lax.axis_index(pp)`, activations hop stage→stage with `lax.ppermute`, and
the schedule is a `lax.scan` of length (n_micro + pp - 1) so the whole
pipeline — including its reverse-order backward, obtained by jax.grad
through the scan+ppermute — is one compiled XLA program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable[[jax.Array, jax.Array], jax.Array],
                   stage_params,
                   x_micro: jax.Array,
                   axis_name: str = "pp") -> jax.Array:
    """Run microbatches through the pipeline; returns last-stage outputs.

    stage_fn(stage_params, act) -> act, applied by every stage to whatever
    activation it currently holds.
    stage_params: this stage's parameter slice (pp-sharded pytree).
    x_micro: (n_micro, *act_shape) — stage 0's input microbatches. Other
      stages pass the same-shaped array (its values are ignored there).

    Returns (n_micro, *act_shape): on the LAST stage these are the pipeline
    outputs in microbatch order; on other stages zeros. Reduce/select over
    the pp axis afterwards (e.g. compute loss under `axis_index == pp-1`).
    """
    P = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    perm = [(i, (i + 1) % P) for i in range(P)]

    def tick(carry, t):
        held = carry  # activation each stage currently holds
        # Stage 0 injects microbatch t (clamped; ticks past n_micro-1 are
        # drain ticks whose stage-0 output is discarded downstream).
        inject = x_micro[jnp.minimum(t, n_micro - 1)]
        cur = jnp.where(stage == 0, inject, held)
        out = stage_fn(stage_params, cur)
        # Last stage emits microbatch (t - (P-1)) at tick t.
        emit_valid = jnp.logical_and(stage == P - 1,
                                     jnp.logical_and(t >= P - 1, t < n_micro + P - 1))
        emitted = jnp.where(emit_valid, out, jnp.zeros_like(out))
        nxt = lax.ppermute(out, axis_name, perm)
        return nxt, emitted

    held0 = jnp.zeros(act_shape, x_micro.dtype)
    _, emitted = lax.scan(tick, held0, jnp.arange(n_micro + P - 1))
    # emitted[t] is microbatch t-(P-1); slice the valid window.
    return lax.dynamic_slice_in_dim(emitted, P - 1, n_micro, axis=0)
