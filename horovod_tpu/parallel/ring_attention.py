"""Ring attention: exact long-context attention over a sequence-parallel axis.

Absent from the reference (SURVEY.md §5 — Horovod has no sequence/context
parallelism; `alltoall` at operations.cc:1904 is the only substrate). Here it
is first-class: sequences are sharded over the `sp` mesh axis and K/V blocks
circulate the ring via `lax.ppermute`, overlapping each hop with the local
blockwise-attention compute. Softmax is streamed flash-style (running max /
running denominator), so the result is exact at any sequence length while
per-chip memory stays O(S/sp).

Differentiable: jax.grad through the ppermute ring yields the reverse ring
automatically, which is the standard backward pass for ring attention.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, q_off, kv_off, causal, scale):
    """One streaming-softmax update of (m, l, o) against a K/V block.

    q: (B, H, Sq, dh); k, v: (B, H, Sk, dh); m, l: (B, H, Sq, 1);
    o: (B, H, Sq, dh). Offsets are global token positions of element 0.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[2])[:, None]
        kv_pos = kv_off + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = True,
                   scale: Optional[float] = None,
                   use_flash: Optional[bool] = None) -> jax.Array:
    """Exact attention with K/V rotating around the `axis_name` ring.

    Call inside shard_map with the sequence dimension sharded over
    `axis_name`. Shapes per shard: q, k, v = (B, H, S_local, dh).
    Block layout is contiguous: ring rank r holds tokens
    [r*S_local, (r+1)*S_local).

    When the shard tiles (default-auto), each hop's block attention runs
    on the Pallas flash kernel with the (o, lse) chunks merged in log
    space (ring_flash_attention); otherwise the streaming jnp path below.
    """
    if use_flash is None:
        from horovod_tpu.ops.flash_attention import can_tile
        use_flash = can_tile(q.shape[2], k.shape[2], causal=causal)
    if use_flash:
        return ring_flash_attention(q, k, v, axis_name, causal=causal,
                                    scale=scale)
    B, H, S, dh = q.shape
    P = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if scale is None:
        scale = dh ** -0.5

    # Streaming-softmax state accumulates in f32 regardless of input dtype:
    # bf16 running max/denominator compounds error over P·S keys, and the
    # division guard (1e-30) underflows to zero in bf16.
    in_dtype = q.dtype
    acc = jnp.float32
    q32, k32, v32 = q.astype(acc), k.astype(acc), v.astype(acc)
    m0 = jnp.full((B, H, S, 1), _NEG_INF, acc)
    l0 = jnp.zeros((B, H, S, 1), acc)
    o0 = jnp.zeros((B, H, S, dh), acc)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def step(carry, t):
        kt, vt, m, l, o = carry
        # After t hops rank r holds the block that originated on rank
        # (r - t) mod P.
        kv_off = ((r - t) % P) * S
        m, l, o = _block_attn(q32, kt, vt, m, l, o, r * S, kv_off, causal,
                              scale)
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        return (kt, vt, m, l, o), None

    # lax.scan (not fori_loop/while): scan is reverse-differentiable, and
    # jax.grad through the ppermute ring gives the reverse-ring backward.
    (_, _, m, l, o), _ = lax.scan(step, (k32, v32, m0, l0, o0),
                                  jnp.arange(P))
    # Rows with no visible keys (never happens for causal contiguous layout,
    # but keep the guard for masked variants) divide by max(l, tiny).
    out = o / jnp.maximum(l, jnp.asarray(1e-30, l.dtype))
    return out.astype(in_dtype)


def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, causal: bool = True,
                         scale: Optional[float] = None) -> jax.Array:
    """Ring attention with the Pallas flash kernel inside each hop.

    Each hop computes (o_chunk, lse_chunk) for the local queries against
    the circulating K/V block (ops/flash_attention.py
    flash_attention_chunk — differentiable through BOTH outputs), and the
    chunks merge in log space:

        L' = logaddexp(L, lse);  o' = e^{L−L'}·o + e^{lse−L'}·o_chunk

    The merge is plain JAX, so jax.grad flows through the scan (reverse
    ring via ppermute) and the per-chunk custom VJP — no streaming state
    ever enters the kernel. Per-hop causality is block-level: a hop's K/V
    block is entirely before (full attention), at (causal chunk), or
    after (skipped) the query block, selected with lax.switch.
    """
    from horovod_tpu.ops.flash_attention import flash_attention_chunk

    B, H, S, dh = q.shape
    P = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if scale is None:
        scale = dh ** -0.5
    in_dtype = q.dtype
    # f32 end to end like the streaming path: chunk outputs in bf16 would
    # quantize ONCE PER HOP before the merge instead of once at the end.
    q, k, v = (t.astype(jnp.float32) for t in (q, k, v))

    def chunk(kt, vt, causal_flag):
        return flash_attention_chunk(q, kt, vt, causal=causal_flag,
                                     scale=scale)

    def hop(carry, t):
        kt, vt, o, L = carry
        src = (r - t) % P          # origin rank of the block we now hold
        if causal:
            # 0: src block after ours → skip; 1: diagonal → causal chunk;
            # 2: before ours → full chunk.
            case = jnp.where(src == r, 1, jnp.where(src < r, 2, 0))
            o_b, lse_b = lax.switch(
                case,
                [lambda kv: (jnp.zeros((B, H, S, dh), jnp.float32),
                             jnp.full((B, H, S), _NEG_INF, jnp.float32)),
                 lambda kv: chunk(kv[0], kv[1], True),
                 lambda kv: chunk(kv[0], kv[1], False)],
                (kt, vt))
        else:
            o_b, lse_b = chunk(kt, vt, False)
        L_new = jnp.logaddexp(L, lse_b)
        w_old = jnp.exp(L - L_new)[..., None]
        w_new = jnp.exp(lse_b - L_new)[..., None]
        o = o * w_old + o_b * w_new
        perm = [(i, (i + 1) % P) for i in range(P)]
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        return (kt, vt, o, L_new), None

    o0 = jnp.zeros((B, H, S, dh), jnp.float32)
    L0 = jnp.full((B, H, S), _NEG_INF, jnp.float32)
    (_, _, o, _), _ = lax.scan(hop, (k, v, o0, L0), jnp.arange(P))
    return o.astype(in_dtype)


def blockwise_attention_reference(q, k, v, causal: bool = True,
                                  scale: Optional[float] = None):
    """Single-device exact attention, used as the numerical oracle in tests
    (role of the reference's NumPy oracles, e.g. test_adasum_pytorch.py)."""
    B, H, S, dh = q.shape
    if scale is None:
        scale = dh ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
