"""Multi-axis device mesh construction.

Replaces the reference's flat rank space + process sets
(horovod/common/process_set.cc) with a named-axis `jax.sharding.Mesh`:

  dp — data parallel (gradient psum; Horovod's whole purpose)
  pp — pipeline stages (ppermute ring between stages)
  tp — tensor parallel (sharded matmuls, psum on row-parallel outputs)
  sp — sequence/context parallel (ring attention over this axis)
  ep — expert parallel (all_to_all token dispatch)

Axis ordering puts dp outermost so that, on a real pod, dp rides DCN across
slices while tp/sp (the latency-sensitive axes) stay on ICI — mirroring the
reference's hierarchical allreduce split (nccl_operations.cc:308: NCCL
within node, MPI across).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from horovod_tpu.common.exceptions import HorovodTpuError

# Canonical axis order: latency-tolerant axes first (outermost / DCN),
# latency-sensitive last (innermost / ICI neighbours).
AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "ep", "sp", "tp")

#: The HOROVOD_MESH spec grammar (docs/parallelism.md): comma-separated
#: `axis=size` entries over the canonical axes, e.g. "dp=2,tp=4".
#: `auto` (or -1) gives one axis every device the others don't claim —
#: "tp=4" alone on 8 devices means dp=2 x tp=4, the same rule
#: MeshSpec.infer applies.
_SPEC_ENTRY_RE = re.compile(r"^([a-z]+)\s*=\s*(auto|-1|\d+)$")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes per named parallelism axis; 1 = axis unused (but still present
    so the same compiled program works at any configuration)."""
    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def __post_init__(self) -> None:
        # A zero/negative axis silently reshapes to an empty device
        # grid and every later error is a numpy shape crash — fail at
        # construction with the axis named.
        for a in AXIS_ORDER:
            if getattr(self, a) < 1:
                raise HorovodTpuError(
                    f"mesh axis {a}={getattr(self, a)} must be >= 1 "
                    "(use 1 for an unused axis)")

    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @property
    def total(self) -> int:
        return int(math.prod(self.sizes()))

    @staticmethod
    def infer(n_devices: int, tp: int = 1, sp: int = 1, pp: int = 1,
              ep: int = 1) -> "MeshSpec":
        """Fix the model axes; give every remaining device to dp."""
        if n_devices < 1:
            raise HorovodTpuError(f"n_devices={n_devices} must be >= 1")
        inner = tp * sp * pp * ep
        if inner < 1 or n_devices % inner:
            raise HorovodTpuError(
                f"n_devices={n_devices} not divisible by tp*sp*pp*ep={inner}")
        return MeshSpec(dp=n_devices // inner, pp=pp, ep=ep, sp=sp, tp=tp)

    @staticmethod
    def parse(text: str, n_devices: Optional[int] = None) -> "MeshSpec":
        """Parse a ``HOROVOD_MESH``-grammar spec: ``"dp=2,tp=4"``.

        Axes are the canonical five (dp/pp/ep/sp/tp); unmentioned axes
        default to 1 — except ``dp``, which defaults to ``auto`` when
        `n_devices` is known, so ``HOROVOD_MESH=tp=4`` on an 8-device
        job means dp=2 x tp=4 (the MeshSpec.infer rule). At most one
        axis may be ``auto``/``-1``; with `n_devices` given, the spec's
        total must cover the devices exactly — a silent mismatch would
        strand devices outside every collective.
        """
        sizes: Dict[str, int] = {}
        auto_axis: Optional[str] = None
        for part in text.strip().split(","):
            part = part.strip()
            if not part:
                continue
            m = _SPEC_ENTRY_RE.match(part)
            if not m:
                raise HorovodTpuError(
                    f"bad HOROVOD_MESH entry {part!r}: expected "
                    f"axis=size with axis in {AXIS_ORDER} "
                    "(e.g. \"dp=2,tp=4\")")
            axis, val = m.group(1), m.group(2)
            if axis not in AXIS_ORDER:
                raise HorovodTpuError(
                    f"unknown mesh axis {axis!r} in HOROVOD_MESH "
                    f"(choose from {AXIS_ORDER})")
            if axis in sizes or axis == auto_axis:
                raise HorovodTpuError(
                    f"duplicate mesh axis {axis!r} in HOROVOD_MESH")
            if val in ("auto", "-1"):
                if auto_axis is not None:
                    raise HorovodTpuError(
                        "at most one HOROVOD_MESH axis may be auto")
                auto_axis = axis
            else:
                sizes[axis] = int(val)
        if not sizes and auto_axis is None:
            raise HorovodTpuError(f"empty HOROVOD_MESH spec {text!r}")
        if auto_axis is None and "dp" not in sizes and n_devices:
            auto_axis = "dp"  # the infer rule: leftover devices ride dp
        if auto_axis is not None:
            if not n_devices:
                raise HorovodTpuError(
                    f"HOROVOD_MESH axis {auto_axis}=auto needs a known "
                    "device count")
            fixed = math.prod(sizes.values()) if sizes else 1
            if fixed < 1 or n_devices % fixed:
                raise HorovodTpuError(
                    f"HOROVOD_MESH {text!r}: {n_devices} devices not "
                    f"divisible by the fixed axes' product {fixed}")
            sizes[auto_axis] = n_devices // fixed
        spec = MeshSpec(**sizes)
        if n_devices and spec.total != n_devices:
            raise HorovodTpuError(
                f"HOROVOD_MESH {text!r} covers {spec.total} devices, "
                f"job has {n_devices}")
        return spec

    def describe(self) -> str:
        """Canonical round-trippable spec string ("dp=2,tp=4"): only the
        axes with size > 1, in canonical order; "dp=1" for the trivial
        single-device mesh."""
        parts = [f"{a}={getattr(self, a)}" for a in AXIS_ORDER
                 if getattr(self, a) > 1]
        return ",".join(parts) if parts else "dp=1"

    def axis_groups(self, axes) -> List[List[int]]:
        """Partition of the flat rank space ``range(total)`` into the
        sub-communicators of `axes` (an axis name or a set of them):
        ranks in one group differ only in their coordinates along
        `axes`. This is the process-set face of the mesh — the TPU
        analog of the reference's per-axis NCCL sub-communicators
        (nccl_operations.cc:308 node/local split), used by
        core/process_sets.axis_process_set and by the per-axis comms
        attribution (analysis/shard.comms_by_axis).
        """
        wanted = {axes} if isinstance(axes, str) else set(axes)
        bad = wanted - set(AXIS_ORDER)
        if bad:
            raise HorovodTpuError(f"unknown mesh axes {sorted(bad)}")
        sizes = self.sizes()
        strides = [1] * len(sizes)
        for i in range(len(sizes) - 2, -1, -1):
            strides[i] = strides[i + 1] * sizes[i + 1]
        moving = [i for i, a in enumerate(AXIS_ORDER) if a in wanted]
        fixed = [i for i in range(len(sizes)) if i not in moving]
        groups: List[List[int]] = []
        for fcoord in itertools.product(*(range(sizes[i]) for i in fixed)):
            base = sum(c * strides[i] for c, i in zip(fcoord, fixed))
            group = [base + sum(c * strides[i] for c, i in
                                zip(mcoord, moving))
                     for mcoord in itertools.product(
                         *(range(sizes[i]) for i in moving))]
            groups.append(group)
        return groups

    def group_of(self, axis: str, rank: int) -> List[int]:
        """The ranks sharing `rank`'s sub-communicator along `axis`
        (rank included), in mesh order."""
        for g in self.axis_groups(axis):
            if rank in g:
                return g
        raise HorovodTpuError(
            f"rank {rank} outside the {self.sizes()} mesh")


def spec_from_env(n_devices: int) -> Optional[MeshSpec]:
    """The HOROVOD_MESH-derived MeshSpec, or None when the knob is
    unset/empty (pure data-parallel world)."""
    text = os.environ.get("HOROVOD_MESH", "").strip()
    if not text:
        return None
    return MeshSpec.parse(text, n_devices)


def build_mesh(spec: MeshSpec,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with all five named axes from a flat device list.

    Device order follows the same canonical (process_index, id) sort as the
    global topology (core/topology.py:_canonical_devices) so innermost axes
    land on devices that are ICI neighbours on real hardware.
    """
    devs = list(devices) if devices is not None else sorted(
        jax.devices(), key=lambda d: (d.process_index, d.id))
    if spec.total != len(devs):
        raise HorovodTpuError(
            f"mesh spec {spec.sizes()} needs {spec.total} devices, "
            f"got {len(devs)}")
    if len({id(d) for d in devs}) != len(devs):
        raise HorovodTpuError(
            "duplicate devices in the mesh device list — a repeated "
            "device aliases two mesh coordinates and every collective "
            "over the affected axes deadlocks or double-counts")
    arr = np.asarray(devs, dtype=object).reshape(spec.sizes())
    return Mesh(arr, AXIS_ORDER)


def mesh_axis_sizes(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def slice_groups(n_devices: int, slices: int) -> List[List[int]]:
    """Partition of the flat rank space into `slices` equal contiguous
    slices — the declared ICI domain boundary of a hierarchical mesh
    (``HOROVOD_MESH_SLICES``; docs/parallelism.md). Ranks inside one
    slice talk over ICI; crossing a boundary rides the slow DCN tier.
    Contiguity in the flat C-order space keeps slices aligned with the
    outermost (dp) axis, matching how multi-slice deployments lay pods
    out. The hvdsched staging lint (HVD404, analysis/sched_rules.py)
    and the ICI/DCN cost model consume the same ``rank // per_slice``
    arithmetic on the analysis side.
    """
    if slices <= 0 or n_devices % slices:
        raise HorovodTpuError(
            f"HOROVOD_MESH_SLICES={slices} does not divide the "
            f"{n_devices}-device world into equal slices")
    per = n_devices // slices
    return [list(range(s * per, (s + 1) * per)) for s in range(slices)]
