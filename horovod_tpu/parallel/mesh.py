"""Multi-axis device mesh construction.

Replaces the reference's flat rank space + process sets
(horovod/common/process_set.cc) with a named-axis `jax.sharding.Mesh`:

  dp — data parallel (gradient psum; Horovod's whole purpose)
  pp — pipeline stages (ppermute ring between stages)
  tp — tensor parallel (sharded matmuls, psum on row-parallel outputs)
  sp — sequence/context parallel (ring attention over this axis)
  ep — expert parallel (all_to_all token dispatch)

Axis ordering puts dp outermost so that, on a real pod, dp rides DCN across
slices while tp/sp (the latency-sensitive axes) stay on ICI — mirroring the
reference's hierarchical allreduce split (nccl_operations.cc:308: NCCL
within node, MPI across).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from horovod_tpu.common.exceptions import HorovodTpuError

# Canonical axis order: latency-tolerant axes first (outermost / DCN),
# latency-sensitive last (innermost / ICI neighbours).
AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes per named parallelism axis; 1 = axis unused (but still present
    so the same compiled program works at any configuration)."""
    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def __post_init__(self) -> None:
        # A zero/negative axis silently reshapes to an empty device
        # grid and every later error is a numpy shape crash — fail at
        # construction with the axis named.
        for a in AXIS_ORDER:
            if getattr(self, a) < 1:
                raise HorovodTpuError(
                    f"mesh axis {a}={getattr(self, a)} must be >= 1 "
                    "(use 1 for an unused axis)")

    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @property
    def total(self) -> int:
        return int(math.prod(self.sizes()))

    @staticmethod
    def infer(n_devices: int, tp: int = 1, sp: int = 1, pp: int = 1,
              ep: int = 1) -> "MeshSpec":
        """Fix the model axes; give every remaining device to dp."""
        if n_devices < 1:
            raise HorovodTpuError(f"n_devices={n_devices} must be >= 1")
        inner = tp * sp * pp * ep
        if inner < 1 or n_devices % inner:
            raise HorovodTpuError(
                f"n_devices={n_devices} not divisible by tp*sp*pp*ep={inner}")
        return MeshSpec(dp=n_devices // inner, pp=pp, ep=ep, sp=sp, tp=tp)


def build_mesh(spec: MeshSpec,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with all five named axes from a flat device list.

    Device order follows the same canonical (process_index, id) sort as the
    global topology (core/topology.py:_canonical_devices) so innermost axes
    land on devices that are ICI neighbours on real hardware.
    """
    devs = list(devices) if devices is not None else sorted(
        jax.devices(), key=lambda d: (d.process_index, d.id))
    if spec.total != len(devs):
        raise HorovodTpuError(
            f"mesh spec {spec.sizes()} needs {spec.total} devices, "
            f"got {len(devs)}")
    if len({id(d) for d in devs}) != len(devs):
        raise HorovodTpuError(
            "duplicate devices in the mesh device list — a repeated "
            "device aliases two mesh coordinates and every collective "
            "over the affected axes deadlocks or double-counts")
    arr = np.asarray(devs, dtype=object).reshape(spec.sizes())
    return Mesh(arr, AXIS_ORDER)


def mesh_axis_sizes(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
