"""Ulysses-style sequence parallelism: all_to_all head/sequence re-shard.

The alternative to ring attention for long context: instead of circulating
K/V, one `all_to_all` converts sequence-sharded activations into
head-sharded activations, full attention runs locally per head group, and a
second `all_to_all` converts back. Built on the same collective the
reference exposes as `hvd.alltoall` (operations.cc:1904) — but compiled into
the XLA program over ICI rather than dispatched through a runtime queue.

Requires num_heads % axis_size == 0. Communication volume is 2x activations
(vs. ring's K+V circulation); preferable when heads are plentiful and the
axis is small.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from horovod_tpu.parallel.ring_attention import blockwise_attention_reference


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = True,
                      scale: Optional[float] = None) -> jax.Array:
    """Per-shard shapes (B, H, S_local, dh), sequence sharded over axis.

    Internally re-shards to (B, H/P, S_global, dh), runs exact local
    attention, and re-shards back.
    """
    P = lax.axis_size(axis_name)
    # (B, H, S/P, dh) -> split heads into P groups, concat sequence:
    # result (B, H/P, S, dh) on each rank.
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    # After the re-shard each device holds the FULL sequence for its head
    # group, so the local attention is exactly the single-device problem —
    # the Pallas flash kernel applies directly (it falls back to the exact
    # reference off-TPU-untileable shapes).
    from horovod_tpu.ops.flash_attention import flash_attention
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    # Back to sequence-sharded layout.
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
