"""Parallelism strategies over the TPU device mesh.

The reference is pure data-parallel (SURVEY.md §2.6); its only substrate for
other strategies is `alltoall` + process sets. Here TP/PP/SP(ring)/EP are
first-class, built on `jax.sharding.Mesh` axes + XLA collectives over
ICI/DCN — the TPU-native generalisation of Horovod's process-set sub-
communicators (reference: horovod/common/process_set.h).
"""

from horovod_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER, MeshSpec, build_mesh, mesh_axis_sizes, spec_from_env,
)
from horovod_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention, blockwise_attention_reference,
)
from horovod_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from horovod_tpu.parallel.moe import moe_ffn  # noqa: F401
from horovod_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
