"""Unified resilience layer: retry/backoff policies and circuit breaking.

Reference analogs: the elastic retry loop (horovod/common/elastic.py:151 —
HorovodInternalError → restore → reinit) gives the DATA plane bounded
recovery; this module gives the CONTROL plane the same property. Every
control-plane hop (rendezvous KV, discovery poll, worker notification)
routes its transient failures through one `RetryPolicy` — jittered
exponential backoff with per-attempt and overall deadlines — instead of
dying on the first connection blip or busy-waiting at a fixed interval.

Design rules:

* Bounded everywhere: a policy always terminates — by attempt count or by
  overall deadline, whichever comes first. No caller can end up in an
  unbounded retry loop.
* Typed outcomes: exhaustion raises `RetryError` (with the last failure as
  `__cause__`); an open breaker raises `CircuitOpenError`. Callers branch
  on types, never on message strings.
* The breaker is OPT-IN, not part of the default KV/discovery paths: a
  breaker failing fast during a rendezvous-server restart is the opposite
  of what a worker needs (the RetryPolicy must carry it across the down
  window). It exists for launcher-side fan-out call sites — health
  probes, per-host notification fan-out — where adding load to a
  struggling endpoint is worse than skipping it.
* Deterministic under test: jitter draws from an injectable
  `random.Random`, so the chaos suite (horovod_tpu/testing/faults.py +
  tests/test_faults.py) replays identical schedules from a seed.

Env knobs (see docs/resilience.md): each call site reads a scoped prefix
(e.g. HOROVOD_KV_RETRY_MAX_ATTEMPTS) with code defaults.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Iterator, Optional

from horovod_tpu.common.config import _env_float, _env_int
from horovod_tpu.common.exceptions import (CircuitOpenError, HorovodTpuError,
                                           RetryError)

_mx = None


def _metrics():
    """Lazy retry/breaker instrument handles (observability/metrics.py;
    refreshed if the registry is reset under test). Series are touched at
    policy creation so a healthy job still scrapes explicit zeros for
    its retry counters instead of an absent metric."""
    global _mx
    from horovod_tpu.observability import metrics as m
    reg = m.registry()
    if _mx is None or _mx[0] is not reg:
        _mx = (reg, {
            "retries": reg.counter(
                "horovod_retry_attempts_total",
                "Retries performed after a transient failure",
                labelnames=("policy",)),
            "exhausted": reg.counter(
                "horovod_retry_exhausted_total",
                "RetryError raises (attempt or deadline budget spent)",
                labelnames=("policy",)),
            "breaker": reg.counter(
                "horovod_circuit_transitions_total",
                "CircuitBreaker state transitions",
                labelnames=("state",)),
        })
    return _mx[1]


def _flight_event(desc: str) -> None:
    """Retry/breaker events feed the flight recorder's ring
    (observability/flight.py) — failure-path only, never the success
    path, so healthy control-plane traffic records nothing."""
    try:
        from horovod_tpu.observability import flight
        flight.record("resilience", desc)
    except Exception:
        pass


def is_transient(e: BaseException) -> bool:
    """Default retryable predicate: transport-level failures and HTTP 5xx.

    Covers what a rendezvous-server restart or network blip produces:
    connection refused/reset, timeouts, unreachable peers, and 5xx from a
    proxy or a half-started server. 4xx (403 auth rejection, 404 missing
    key) is NOT transient — retrying would mask a real error.
    """
    import urllib.error

    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500
    if isinstance(e, urllib.error.URLError):
        reason = getattr(e, "reason", None)
        return reason is None or is_transient(reason) or not isinstance(
            reason, Exception)
    if isinstance(e, (ConnectionError, TimeoutError)):
        return True
    import socket
    if isinstance(e, (socket.timeout, socket.gaierror)):
        return True
    if isinstance(e, OSError):
        import errno
        return e.errno in (errno.ECONNREFUSED, errno.ECONNRESET,
                           errno.ECONNABORTED, errno.EPIPE, errno.ETIMEDOUT,
                           errno.EHOSTUNREACH, errno.ENETUNREACH,
                           errno.EAGAIN, None)
    return False


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff with attempt and deadline bounds.

    `max_attempts` counts calls, not retries: 1 means no retry at all.
    `deadline` bounds the TOTAL time spent inside `call` (attempts plus
    sleeps); a sleep is truncated to the remaining budget and the next
    attempt is skipped if the budget is gone. `jitter` is the randomized
    fraction of each delay (0 = fully deterministic, 1 = full jitter).
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline: Optional[float] = 30.0
    retryable: Callable[[BaseException], bool] = is_transient
    # Metrics label for this policy's retry/exhaustion counters; ""
    # disables per-policy instrumentation (ad-hoc inline policies).
    name: str = ""

    def __post_init__(self) -> None:
        if self.name:
            mx = _metrics()
            mx["retries"].labels(policy=self.name)
            mx["exhausted"].labels(policy=self.name)

    @staticmethod
    def from_env(prefix: str = "HOROVOD_RETRY", **defaults) -> "RetryPolicy":
        """Build a policy from `<prefix>_*` env vars over code defaults.

        Knobs: _MAX_ATTEMPTS, _BASE_DELAY, _MAX_DELAY, _MULTIPLIER,
        _JITTER, _DEADLINE (seconds; _DEADLINE <= 0 means unbounded time).
        """
        base = RetryPolicy(**defaults)
        deadline = _env_float(f"{prefix}_DEADLINE",
                              base.deadline if base.deadline is not None
                              else 0.0)
        return dataclasses.replace(
            base,
            max_attempts=_env_int(f"{prefix}_MAX_ATTEMPTS",
                                  base.max_attempts),
            base_delay=_env_float(f"{prefix}_BASE_DELAY", base.base_delay),
            max_delay=_env_float(f"{prefix}_MAX_DELAY", base.max_delay),
            multiplier=_env_float(f"{prefix}_MULTIPLIER", base.multiplier),
            jitter=_env_float(f"{prefix}_JITTER", base.jitter),
            deadline=deadline if deadline > 0 else None,
        )

    def delays(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """The backoff schedule: one delay per retry (max_attempts - 1).

        delay_i = min(base * multiplier^i, max_delay), with the last
        `jitter` fraction re-drawn uniformly so synchronized clients
        de-correlate (full-jitter style).
        """
        rng = rng or random
        d = self.base_delay
        for _ in range(max(self.max_attempts - 1, 0)):
            capped = min(d, self.max_delay)
            yield capped * (1.0 - self.jitter) + \
                capped * self.jitter * rng.random()
            d *= self.multiplier

    def call(self, fn: Callable, *args,
             rng: Optional[random.Random] = None,
             on_retry: Optional[Callable[[int, BaseException, float],
                                         None]] = None,
             **kwargs):
        """Run `fn(*args, **kwargs)` under this policy.

        Retries only exceptions for which `retryable(e)` is True; others
        propagate immediately. Exhaustion (attempts or deadline) raises
        `RetryError` from the last failure. `on_retry(attempt, exc, delay)`
        is invoked before each sleep (logging / test hooks).
        """
        start = time.monotonic()
        schedule = self.delays(rng)
        attempt = 0
        mx = _metrics() if self.name else None
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                if not self.retryable(e):
                    raise
                try:
                    delay = next(schedule)
                except StopIteration:
                    if mx is not None:
                        mx["exhausted"].labels(policy=self.name).inc()
                    _flight_event(f"retry policy '{self.name or 'inline'}' "
                                  f"exhausted after {attempt} attempt(s): "
                                  f"{e}")
                    raise RetryError(
                        f"retries exhausted after {attempt} attempt(s): "
                        f"{e}") from e
                if self.deadline is not None:
                    remaining = self.deadline - (time.monotonic() - start)
                    if remaining <= 0:
                        if mx is not None:
                            mx["exhausted"].labels(policy=self.name).inc()
                        _flight_event(
                            f"retry policy '{self.name or 'inline'}' "
                            f"deadline {self.deadline}s exceeded after "
                            f"{attempt} attempt(s): {e}")
                        raise RetryError(
                            f"retry deadline {self.deadline}s exceeded "
                            f"after {attempt} attempt(s): {e}") from e
                    delay = min(delay, remaining)
                if mx is not None:
                    mx["retries"].labels(policy=self.name).inc()
                _flight_event(f"retry policy '{self.name or 'inline'}' "
                              f"attempt {attempt} failed ({e}); retrying "
                              f"in {delay:.2f}s")
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                time.sleep(delay)


# Default policy for rendezvous KV traffic. A rendezvous-server restart
# takes O(100ms) on loopback and O(s) across a pod; 8 attempts over ~6 s of
# backoff (cap 1 s) rides out a restart without hammering a dead endpoint.
KV_RETRY_DEFAULTS = dict(max_attempts=8, base_delay=0.05, max_delay=1.0,
                         deadline=30.0, name="kv")
# Discovery scripts flake for longer (cloud API hiccups); cap higher and
# let the driver loop re-arm the schedule — see ElasticDriver._discover_loop.
DISCOVERY_RETRY_DEFAULTS = dict(max_attempts=6, base_delay=0.5,
                                max_delay=10.0, deadline=60.0,
                                name="discovery")


def kv_retry_policy(**overrides) -> RetryPolicy:
    """The rendezvous-KV policy (env prefix HOROVOD_KV_RETRY)."""
    merged = dict(KV_RETRY_DEFAULTS)
    merged.update(overrides)
    return RetryPolicy.from_env("HOROVOD_KV_RETRY", **merged)


def discovery_retry_policy(**overrides) -> RetryPolicy:
    """The host-discovery policy (env prefix HOROVOD_DISCOVERY_RETRY)."""
    merged = dict(DISCOVERY_RETRY_DEFAULTS)
    merged.update(overrides)
    return RetryPolicy.from_env("HOROVOD_DISCOVERY_RETRY", **merged)


class CircuitBreaker:
    """Classic closed → open → half-open breaker for control-plane targets.

    After `failure_threshold` consecutive failures the circuit opens and
    `call` fails fast with `CircuitOpenError` (no network traffic) until
    `recovery_timeout` elapses; then one probe call is admitted
    (half-open) — success closes the circuit, failure re-opens it for
    another window. Protects a struggling rendezvous/discovery endpoint
    from a retry stampede of 10k workers (the ROADMAP's production-scale
    north star), which bare per-client retries would amplify.
    """

    def __init__(self, failure_threshold: int = 5,
                 recovery_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise HorovodTpuError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.recovery_timeout = recovery_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.recovery_timeout:
            return "half_open"
        return "open"

    def allow(self) -> bool:
        """Admission check. In half-open, only ONE caller gets the probe."""
        with self._lock:
            s = self._state_locked()
            if s == "closed":
                return True
            if s == "half_open" and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            reopened = self._opened_at is not None
            self._failures = 0
            self._opened_at = None
            self._probing = False
        if reopened:
            _metrics()["breaker"].labels(state="closed").inc()
            _flight_event("circuit breaker closed (probe succeeded)")

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            opened = False
            if self._failures >= self.failure_threshold:
                opened = self._opened_at is None
                self._opened_at = self._clock()
            failures = self._failures
        if opened:
            _metrics()["breaker"].labels(state="open").inc()
            _flight_event(f"circuit breaker opened after {failures} "
                          f"consecutive failure(s)")

    def call(self, fn: Callable, *args, **kwargs):
        if not self.allow():
            remaining = 0.0
            with self._lock:
                if self._opened_at is not None:
                    remaining = max(
                        0.0, self.recovery_timeout -
                        (self._clock() - self._opened_at))
            raise CircuitOpenError(
                f"circuit open after {self._failures} consecutive "
                f"failure(s); retry in {remaining:.1f}s")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out


class PyStallInspector:
    """Pure-Python StallInspector with the native binding's contract
    (native/__init__.py:247; stall.cc). Used when the native library is
    unavailable (no toolchain), so the stall watchdog — and therefore
    bounded collective waits in elastic mode — never silently degrades
    to an unwatched hang.
    """

    def __init__(self, warn_sec: float = 60.0, shutdown_sec: float = 0.0):
        self.warn_sec = warn_sec
        self.shutdown_sec = shutdown_sec
        self._pending: dict = {}
        self._lock = threading.Lock()

    def submit(self, name: str) -> None:
        with self._lock:
            self._pending.setdefault(name, time.monotonic())

    def done(self, name: str) -> None:
        with self._lock:
            self._pending.pop(name, None)

    def check(self) -> tuple:
        """Returns (stalled_names, shutdown) like the native binding."""
        now = time.monotonic()
        stalled, shut = [], False
        with self._lock:
            for name, t0 in self._pending.items():
                age = now - t0
                if age >= self.warn_sec:
                    stalled.append(name)
                if self.shutdown_sec > 0 and age >= self.shutdown_sec:
                    shut = True
        return stalled, shut

    def free(self) -> None:
        with self._lock:
            self._pending.clear()
