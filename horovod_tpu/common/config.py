"""Environment-knob registry.

The reference configures everything through ~40 HOROVOD_* environment
variables, with names centralized in horovod/common/common.h:118-151 and
parsed at background-thread startup (horovod/common/operations.cc:430-650,
horovod/common/utils/env_parser.cc). We keep the same knob names where the
concept survives the TPU redesign, add TPU-specific ones under the same
prefix, and parse them all in one place so `hvd.init()` has a single config
snapshot (also required for the autotuner, which overrides a subset at
runtime — reference horovod/common/parameter_manager.h:58-101).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_on(name: str, default: bool) -> bool:
    """Like _env_bool, but an empty/whitespace value also keeps the
    default — the convention for the always-on subsystem gates
    (HOROVOD_FLIGHT, HOROVOD_PERFSCOPE), where `VAR=` in a wrapper
    script must not silently disable the subsystem."""
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return float(v)
    except ValueError:
        return default


# Knob names (reference: horovod/common/common.h:118-151). Kept verbatim where
# the concept survives; TPU-specific knobs are new but share the prefix.
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"
HOROVOD_DYNAMIC_PROCESS_SETS = "HOROVOD_DYNAMIC_PROCESS_SETS"
HOROVOD_DISABLE_GROUP_FUSION = "HOROVOD_DISABLE_GROUP_FUSION"
# Bucketed, backward-overlapped gradient allreduce (docs/perf.md).
HOROVOD_BUCKET_CAP = "HOROVOD_BUCKET_CAP"
HOROVOD_BUCKET_REVERSE = "HOROVOD_BUCKET_REVERSE"
HOROVOD_BUCKET_PIPELINE = "HOROVOD_BUCKET_PIPELINE"
HOROVOD_BUCKET_PROFILE = "HOROVOD_BUCKET_PROFILE"
HOROVOD_BUCKET_AUTOTUNE = "HOROVOD_BUCKET_AUTOTUNE"
HOROVOD_BUCKET_AUTOTUNE_INTERVAL = "HOROVOD_BUCKET_AUTOTUNE_INTERVAL"
HOROVOD_BUCKET_AUTOTUNE_MAX_ADJUSTMENTS = \
    "HOROVOD_BUCKET_AUTOTUNE_MAX_ADJUSTMENTS"
# Conv fast path (docs/perf.md): online layout arbitration between the
# lane-padded and as-declared model layouts (ops/layout.py,
# core/autotune.OnlineLayoutTuner).
HOROVOD_LAYOUT_AUTOTUNE = "HOROVOD_LAYOUT_AUTOTUNE"
HOROVOD_LAYOUT_AUTOTUNE_INTERVAL = "HOROVOD_LAYOUT_AUTOTUNE_INTERVAL"
# (HOROVOD_BATCH_D2D_MEMCOPIES and HOROVOD_ENABLE_ASYNC_COMPLETION have no
# TPU analog — XLA fuses the copies and JAX dispatch is always async — so
# those knobs are intentionally absent rather than parsed-and-dead.)
HOROVOD_ADASUM_HALVING = "HOROVOD_ADASUM_HALVING"
HOROVOD_CONSISTENCY_CHECK = "HOROVOD_CONSISTENCY_CHECK"
HOROVOD_CONSISTENCY_TIMEOUT = "HOROVOD_CONSISTENCY_TIMEOUT"
# Cross-rank fingerprint verifier (analysis/verifier.py,
# docs/static_analysis.md): asymptotically-free divergence detection
# through the launcher's rendezvous KV.
HOROVOD_CHECK_COLLECTIVES = "HOROVOD_CHECK_COLLECTIVES"
HOROVOD_CHECK_COLLECTIVES_INTERVAL = "HOROVOD_CHECK_COLLECTIVES_INTERVAL"
HOROVOD_CHECK_COLLECTIVES_WINDOW = "HOROVOD_CHECK_COLLECTIVES_WINDOW"
HOROVOD_CHECK_COLLECTIVES_TIMEOUT = "HOROVOD_CHECK_COLLECTIVES_TIMEOUT"
HOROVOD_NATIVE_KV_ADDR = "HOROVOD_NATIVE_KV_ADDR"
HOROVOD_NATIVE_KV_PORT = "HOROVOD_NATIVE_KV_PORT"

# hvdrace runtime lockset race detector (analysis/race.py,
# docs/static_analysis.md). Read at horovod_tpu import time (the
# instrumentation must precede any runtime instance), so like the
# metrics gate these are parsed where they are used; the Config fields
# exist so `hvd.init()`'s snapshot still shows the effective values.
HOROVOD_RACE_CHECK = "HOROVOD_RACE_CHECK"
HOROVOD_RACE_CHECK_FAIL = "HOROVOD_RACE_CHECK_FAIL"
HOROVOD_RACE_CHECK_MAX_REPORTS = "HOROVOD_RACE_CHECK_MAX_REPORTS"

# Metrics / telemetry (observability/metrics.py, docs/observability.md).
HOROVOD_METRICS = "HOROVOD_METRICS"
HOROVOD_METRICS_DUMP = "HOROVOD_METRICS_DUMP"
HOROVOD_METRICS_DUMP_INTERVAL = "HOROVOD_METRICS_DUMP_INTERVAL"
HOROVOD_METRICS_PUSH_INTERVAL = "HOROVOD_METRICS_PUSH_INTERVAL"
HOROVOD_METRICS_LABEL_MAX = "HOROVOD_METRICS_LABEL_MAX"

# Topology / launcher knobs (reference: injected by the launcher,
# horovod/runner/gloo_run.py:69-75).
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"

# TPU-native knobs (new).
# GSPMD hybrid-parallel mesh authority (docs/parallelism.md): named-axis
# sizes over the canonical dp/pp/ep/sp/tp order, e.g. "dp=2,tp=4";
# parallel/mesh.MeshSpec.parse owns the grammar. Unset = pure DP.
HOROVOD_MESH = "HOROVOD_MESH"
HOROVOD_TPU_MESH_SHAPE = "HOROVOD_TPU_MESH_SHAPE"          # e.g. "dcn:4,ici:8"
HOROVOD_TPU_EMULATE_RANKS = "HOROVOD_TPU_EMULATE_RANKS"    # force N virtual ranks
HOROVOD_TPU_DONATE_BUFFERS = "HOROVOD_TPU_DONATE_BUFFERS"  # in-place eager collectives
HOROVOD_TPU_COMPILE_CACHE = "HOROVOD_TPU_COMPILE_CACHE"    # persistent compile cache dir

# 4 MB, not the reference's 64 MB: the r05 fusion sweep measured 16-64 MB
# payloads ~2x slower than 1-4 MB on the collective engine (the fusion
# cliff); 4 MB is the top of the flat region. HOROVOD_FUSION_THRESHOLD
# still overrides, but the wire payload stays bounded by the bucket cap
# below unless that is raised too.
DEFAULT_FUSION_THRESHOLD_BYTES = 4 * 1024 * 1024
# Hard ceiling on any single fused payload (docs/perf.md): oversize
# tensors and large fusion thresholds are chunked down to this. 0 = off.
DEFAULT_BUCKET_CAP_BYTES = 4 * 1024 * 1024
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60.0


@dataclasses.dataclass
class Config:
    """Snapshot of all knobs, taken at init().

    The autotuner mutates `fusion_threshold_bytes` (and in the reference also
    cycle time / cache / hierarchical flags, parameter_manager.h:58-101) at
    runtime; everything else is fixed for the life of the process.
    """

    # Perf knobs
    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    cycle_time_ms: float = 0.0          # TPU default 0: no background batching delay
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    adasum_halving: bool = False
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    disable_group_fusion: bool = False
    donate_buffers: bool = False
    # Bucketed gradient pipeline (docs/perf.md): wire-payload cap (chunking
    # granularity for oversize tensors), backward-production bucket
    # ordering, per-bucket eager dispatch in DistributedOptimizer, forced
    # per-bucket completion timing, and the online bucket-size tuner.
    bucket_cap_bytes: int = DEFAULT_BUCKET_CAP_BYTES
    bucket_reverse: bool = True
    bucket_pipeline: bool = True
    bucket_profile: bool = False
    bucket_autotune: bool = False
    bucket_autotune_interval: int = 20
    bucket_autotune_max_adjustments: int = 4
    # Per-model layout arbitration (ops/layout.py, docs/perf.md): score
    # NHWC-lane-padded vs as-declared by measured step time; rank 0
    # decides and broadcasts (core/autotune.OnlineLayoutTuner).
    layout_autotune: bool = False
    layout_autotune_interval: int = 20

    # Timeline / autotune
    timeline_path: str = ""
    timeline_mark_cycles: bool = False
    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8

    # Metrics / telemetry (registry in observability/metrics.py; the
    # registry itself reads HOROVOD_METRICS and HOROVOD_METRICS_LABEL_MAX
    # directly — it must work in the launcher, which never builds a
    # Config — so those two have no field here; these gate/configure the
    # worker-side exporter).
    metrics_enabled: bool = True
    metrics_dump: str = ""
    metrics_dump_interval: float = 30.0
    metrics_push_interval: float = 5.0

    # Stall inspector
    stall_check_disable: bool = False
    stall_warning_seconds: float = DEFAULT_STALL_WARNING_SECONDS
    stall_shutdown_seconds: float = 0.0

    # Modes
    elastic: bool = False
    # Debug negotiation: agree cross-rank on every eager collective's
    # signature before running it (core/consistency.py).
    consistency_check: bool = False
    # Rolling fingerprint of the collective call sequence, periodically
    # cross-checked through the rendezvous KV (analysis/verifier.py).
    check_collectives: bool = False
    check_collectives_interval: int = 10
    check_collectives_window: int = 512
    check_collectives_timeout: float = 5.0
    # hvdrace lockset detector (analysis/race.py) — enforcement is wired
    # at import time; these mirror the env for the init() snapshot.
    race_check: bool = False
    race_check_fail: bool = False
    race_check_max_reports: int = 100
    dynamic_process_sets: bool = False

    # Topology overrides (launcher-injected)
    rank: Optional[int] = None
    size: Optional[int] = None
    local_rank: Optional[int] = None
    local_size: Optional[int] = None
    cross_rank: Optional[int] = None
    cross_size: Optional[int] = None
    rendezvous_addr: str = ""
    rendezvous_port: int = 0

    # TPU
    mesh_shape: str = ""
    # HOROVOD_MESH hybrid-parallel spec ("dp=2,tp=4"); empty = pure DP.
    mesh_spec: str = ""
    emulate_ranks: int = 0
    compile_cache_dir: str = ""

    @staticmethod
    def from_env() -> "Config":
        def opt_int(name: str) -> Optional[int]:
            v = os.environ.get(name)
            return int(v) if v not in (None, "") else None

        def _env_or_mpi(primary: str, indirect: str) -> Optional[int]:
            # mpirun/jsrun-placed workers: when the HOROVOD_* var is
            # absent, the MPI flavor's own rank var (named by the
            # HOROVOD_MPI_*_ENV indirection runner/mpi_run.py exports)
            # stands in.
            r = opt_int(primary)
            if r is not None:
                return r
            alt = os.environ.get(indirect, "")
            return opt_int(alt) if alt else None

        return Config(
            fusion_threshold_bytes=_env_int(
                HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES),
            cycle_time_ms=_env_float(HOROVOD_CYCLE_TIME, 0.0),
            cache_capacity=_env_int(HOROVOD_CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY),
            adasum_halving=_env_bool(HOROVOD_ADASUM_HALVING),
            hierarchical_allreduce=_env_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=_env_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            disable_group_fusion=_env_bool(HOROVOD_DISABLE_GROUP_FUSION),
            bucket_cap_bytes=_env_int(
                HOROVOD_BUCKET_CAP, DEFAULT_BUCKET_CAP_BYTES),
            bucket_reverse=_env_bool(HOROVOD_BUCKET_REVERSE, True),
            bucket_pipeline=_env_bool(HOROVOD_BUCKET_PIPELINE, True),
            bucket_profile=_env_bool(HOROVOD_BUCKET_PROFILE),
            bucket_autotune=_env_bool(HOROVOD_BUCKET_AUTOTUNE),
            bucket_autotune_interval=_env_int(
                HOROVOD_BUCKET_AUTOTUNE_INTERVAL, 20),
            bucket_autotune_max_adjustments=_env_int(
                HOROVOD_BUCKET_AUTOTUNE_MAX_ADJUSTMENTS, 4),
            layout_autotune=_env_bool(HOROVOD_LAYOUT_AUTOTUNE),
            layout_autotune_interval=_env_int(
                HOROVOD_LAYOUT_AUTOTUNE_INTERVAL, 20),
            donate_buffers=_env_bool(HOROVOD_TPU_DONATE_BUFFERS),
            timeline_path=os.environ.get(HOROVOD_TIMELINE, ""),
            timeline_mark_cycles=_env_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            autotune=_env_bool(HOROVOD_AUTOTUNE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG, ""),
            autotune_warmup_samples=_env_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
            autotune_steps_per_sample=_env_int(HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10),
            autotune_bayes_opt_max_samples=_env_int(
                HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20),
            autotune_gaussian_process_noise=_env_float(
                HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8),
            metrics_enabled=_env_bool(HOROVOD_METRICS, True),
            metrics_dump=os.environ.get(HOROVOD_METRICS_DUMP, ""),
            metrics_dump_interval=_env_float(
                HOROVOD_METRICS_DUMP_INTERVAL, 30.0),
            metrics_push_interval=_env_float(
                HOROVOD_METRICS_PUSH_INTERVAL, 5.0),
            stall_check_disable=_env_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_seconds=_env_float(
                HOROVOD_STALL_CHECK_TIME_SECONDS, DEFAULT_STALL_WARNING_SECONDS),
            stall_shutdown_seconds=_env_float(HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            elastic=_env_bool(HOROVOD_ELASTIC),
            # Default ON in launcher-started multi-process jobs (the
            # launcher injects the native KV the checker needs) — the
            # reference's controller mismatch checks are always-on
            # (controller.cc:74-447). HOROVOD_CONSISTENCY_CHECK=0 opts
            # out; the checker self-disables when size<=1. Measured
            # overhead: ~2.4 ms per eager collective call on 2-proc
            # loopback — one check per grouped/fused call, so a full
            # gradient set pays it once (docs/concepts.md).
            consistency_check=_env_bool(
                HOROVOD_CONSISTENCY_CHECK,
                default=bool(os.environ.get(HOROVOD_NATIVE_KV_ADDR))),
            check_collectives=_env_bool(HOROVOD_CHECK_COLLECTIVES),
            check_collectives_interval=_env_int(
                HOROVOD_CHECK_COLLECTIVES_INTERVAL, 10),
            check_collectives_window=_env_int(
                HOROVOD_CHECK_COLLECTIVES_WINDOW, 512),
            check_collectives_timeout=_env_float(
                HOROVOD_CHECK_COLLECTIVES_TIMEOUT, 5.0),
            race_check=_env_bool(HOROVOD_RACE_CHECK),
            race_check_fail=_env_bool(HOROVOD_RACE_CHECK_FAIL),
            race_check_max_reports=_env_int(
                HOROVOD_RACE_CHECK_MAX_REPORTS, 100),
            dynamic_process_sets=_env_bool(HOROVOD_DYNAMIC_PROCESS_SETS),
            rank=_env_or_mpi(HOROVOD_RANK, "HOROVOD_MPI_RANK_ENV"),
            size=opt_int(HOROVOD_SIZE),
            local_rank=_env_or_mpi(HOROVOD_LOCAL_RANK,
                                   "HOROVOD_MPI_LOCAL_RANK_ENV"),
            local_size=opt_int(HOROVOD_LOCAL_SIZE),
            cross_rank=opt_int(HOROVOD_CROSS_RANK),
            cross_size=opt_int(HOROVOD_CROSS_SIZE),
            rendezvous_addr=os.environ.get(HOROVOD_RENDEZVOUS_ADDR, ""),
            rendezvous_port=_env_int(HOROVOD_RENDEZVOUS_PORT, 0),
            mesh_shape=os.environ.get(HOROVOD_TPU_MESH_SHAPE, ""),
            mesh_spec=os.environ.get(HOROVOD_MESH, "").strip(),
            emulate_ranks=_env_int(HOROVOD_TPU_EMULATE_RANKS, 0),
            compile_cache_dir=os.environ.get(HOROVOD_TPU_COMPILE_CACHE, ""),
        )
