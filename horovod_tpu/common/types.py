"""Core value types shared across the framework.

TPU-native re-design of the reference's common types (reference:
horovod/common/common.h:170-360, horovod/common/message.h:43-70). Where the
reference defines an abstract Tensor/OpContext hierarchy so four frameworks can
share one C++ runtime, we have a single array language (JAX) — so the types
here are the *semantic* ones: reduce ops, status, data types, and the
per-tensor metadata used by the eager negotiation path.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class ReduceOp(enum.IntEnum):
    """Reduction operators for allreduce/reducescatter.

    Mirrors the reference's ReduceOp enum (horovod/common/message.h:43-49).
    """

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Horovod-style module-level aliases (reference: horovod/torch/mpi_ops.py).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


class StatusType(enum.IntEnum):
    """Result classification (reference: horovod/common/common.h:175-182)."""

    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclasses.dataclass(frozen=True)
class Status:
    """Operation status (reference: horovod/common/common.h:184-228)."""

    type: StatusType = StatusType.OK
    reason: str = ""

    @staticmethod
    def OK() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def UnknownError(msg: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def PreconditionError(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def Aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def InvalidArgument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def InProgress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    def ok(self) -> bool:
        return self.type == StatusType.OK

    def in_progress(self) -> bool:
        return self.type == StatusType.IN_PROGRESS


class RequestType(enum.IntEnum):
    """Collective request kinds (reference: horovod/common/message.h:61-70)."""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7


# DataType registry. The reference enumerates wire dtypes
# (horovod/common/message.h:23-41); ours is keyed on jnp dtypes with bf16 as a
# first-class citizen (TPU-native), fp8 reserved for compression paths.
_SUPPORTED_DTYPES: Tuple[Any, ...] = (
    jnp.uint8,
    jnp.int8,
    jnp.uint16,
    jnp.int16,
    jnp.int32,
    jnp.int64,
    jnp.float16,
    jnp.bfloat16,
    jnp.float32,
    jnp.float64,
    jnp.bool_,
)


def check_supported_dtype(dtype: Any) -> None:
    d = jnp.dtype(dtype)
    if not any(d == jnp.dtype(s) for s in _SUPPORTED_DTYPES):
        raise ValueError(f"Unsupported dtype for collective: {dtype}")


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Static signature of a tensor participating in a collective."""

    shape: Tuple[int, ...]
    dtype: str

    @staticmethod
    def of(array: Any) -> "TensorSpec":
        return TensorSpec(tuple(int(s) for s in np.shape(array)),
                          str(jnp.asarray(array).dtype))


@dataclasses.dataclass(frozen=True)
class CollectiveKey:
    """Cache key for a compiled eager collective.

    Plays the role of the reference's ResponseCache key (tensor name + params,
    horovod/common/response_cache.h) — but on TPU the cached object is a
    compiled XLA executable rather than a negotiated Response: same-signature
    collectives hit the jit cache and skip all negotiation.
    """

    request_type: RequestType
    specs: Tuple[TensorSpec, ...]
    reduce_op: ReduceOp
    process_set_id: int
    prescale_factor: float
    postscale_factor: float
    extra: Tuple[Any, ...] = ()


@dataclasses.dataclass
class TensorTableEntry:
    """Host-side record for one in-flight eager collective tensor.

    Reference: horovod/common/common.h:360-395. On TPU this only exists on the
    eager/dynamic path: jitted step functions compile their collectives in.
    """

    name: str
    request_type: RequestType
    reduce_op: ReduceOp
    spec: TensorSpec
    process_set_id: int
    root_rank: int = -1
    callback: Optional[Any] = None


def reduce_op_name(op: ReduceOp) -> str:
    return ReduceOp(op).name


def normalize_reduce_op(op: Any) -> ReduceOp:
    if isinstance(op, ReduceOp):
        return op
    if isinstance(op, int):
        return ReduceOp(op)
    if isinstance(op, str):
        return ReduceOp[op.upper()]
    raise ValueError(f"Cannot interpret reduce op: {op!r}")
