"""Framework exceptions.

Reference: horovod/common/exceptions.py:18-52. Same three user-visible
exception types drive the elastic retry loop (see horovod_tpu/elastic).
"""

from __future__ import annotations


class HorovodTpuError(Exception):
    """Base class for framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective fails.

    In elastic mode this triggers state restore + re-initialization
    (reference: horovod/common/elastic.py:151-175 retry loop).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """Raised inside elastic training when the host set changed.

    Carries whether the update requires an immediate reset.
    Reference: horovod/common/exceptions.py:29-41.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class VersionMismatchError(HorovodTpuError):
    """Launcher/worker version mismatch (reference exceptions.py:44-52)."""


class TensorShapeMismatchError(HorovodTpuError):
    """Ranks submitted mismatched shapes for the same named collective."""


class DuplicateNameError(HorovodTpuError):
    """Two in-flight eager collectives share a name.

    Reference: DUPLICATE_NAME_ERROR status (horovod/common/common.h:230) and
    duplicate detection in horovod/common/tensor_queue.cc.
    """


class StalledTensorError(HorovodTpuError):
    """Stall inspector forced shutdown (reference stall_inspector.cc)."""


class CollectiveDivergenceError(HorovodTpuError):
    """The cross-rank fingerprint verifier (HOROVOD_CHECK_COLLECTIVES,
    analysis/verifier.py) caught ranks issuing different collective
    sequences. Deliberately NOT a HorovodInternalError: the elastic
    retry loop must not restart a job whose program is deterministic-
    ally divergent — it would diverge again every round."""


class CheckpointCorruptError(HorovodTpuError):
    """A checkpoint directory failed verification: missing `.done`
    commit marker, unreadable/partial manifest, or leaf files absent or
    truncated (horovod_tpu/ckpt/, checkpoint.py). Typed so restore
    paths can quarantine-and-fall-back (ckpt/resume) or fail loudly
    (serve/engine.from_checkpoint) instead of pattern-matching raw
    orbax/KeyError noise. Deliberately NOT a HorovodInternalError: the
    elastic retry loop must not re-rendezvous over a corrupt artifact —
    it would re-read the same bytes every round."""


class RetryError(HorovodTpuError):
    """A RetryPolicy exhausted its attempts or overall deadline.

    `__cause__` carries the last underlying failure
    (common/resilience.py).
    """


class CircuitOpenError(HorovodTpuError):
    """A CircuitBreaker rejected the call without attempting it
    (common/resilience.py)."""


class ResetLimitExceededError(HorovodTpuError):
    """The elastic driver hit --reset-limit: too many topology resets.

    Reference: launch.py --reset-limit / driver reset accounting. Typed so
    orchestrators can distinguish "job churned itself to death" from other
    driver failures instead of matching a bare HorovodTpuError.
    """


class FaultInjectedError(HorovodTpuError):
    """An error produced by the deterministic fault-injection harness
    (horovod_tpu/testing/faults.py) for kinds with no natural exception
    type (e.g. a discovery flap). Never raised in production paths —
    the injector is inert unless HOROVOD_FAULT_SPEC is set."""
