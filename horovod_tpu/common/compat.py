"""jaxlib private-API compatibility shims.

The elastic control plane leans on jax's distributed-runtime service and
client, which live behind `jax._src` internals that jaxlib renames across
releases: the extension module moved from `jax._src.lib.xla_extension`
(≤0.4.x) to `jax._src.lib._jax` (≥0.5), and the service factory's
keepalive knobs changed from (heartbeat_interval, max_missing_heartbeats)
to a single heartbeat_timeout. Resolving the module and signature in ONE
place keeps every call site working across that drift — and keeps the
degradation story (topology.recoverable_client_contract) honest: a moved
import must read as "renamed, adapted" rather than "gone".
"""

from __future__ import annotations


def ensure_jax_api() -> None:
    """Alias public jax symbols this codebase uses that older jax keeps
    under experimental names. Today: `jax.shard_map`, promoted out of
    `jax.experimental.shard_map` in jax 0.5 — every collective here is a
    jit(shard_map(...)) program, so without the alias an old jax fails at
    the first collective build. Idempotent; a no-op on new jax.
    """
    import jax
    if not hasattr(jax, "shard_map"):
        import functools

        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, *args, **kwargs):
            # jax 0.5 renamed check_rep -> check_vma along with the move.
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        # jax.lax.axis_size arrived with the shard_map promotion; old jax
        # spells it psum(1, axis) — special-cased to resolve statically
        # at trace time, so this is an alias, not an added collective.
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

    try:
        from jax.experimental.pallas import tpu as pltpu
        if not hasattr(pltpu, "CompilerParams"):
            # renamed from TPUCompilerParams when pallas de-prefixed its
            # per-backend params classes
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except (ImportError, AttributeError):
        pass  # no pallas TPU backend in this jax: kernels gate on it


def cpu_collectives_implementation():
    """Current `jax_cpu_collectives_implementation` value ('none' / 'gloo'
    / 'mpi'), or None if this jax has no such flag.

    The flag drifted: new jax exposes it as a `jax.config` attribute; jax
    0.4.x registers it lazily from `jax._src.xla_bridge` as a holder that
    `jax.config.update` accepts but attribute reads do NOT see. Reading
    through the holder keeps "is gloo active?" answerable everywhere —
    the elastic scale-down-to-1 reset depends on it (core/topology.py).
    """
    import jax
    try:
        return jax.config.jax_cpu_collectives_implementation
    except AttributeError:
        pass
    try:
        from jax._src import xla_bridge as xb
        return xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except (ImportError, AttributeError):
        return None


def set_cpu_collectives_implementation(value: str) -> bool:
    """Set `jax_cpu_collectives_implementation`; returns False if this jax
    has no such flag. Imports xla_bridge first: on jax 0.4.x the flag only
    registers with `jax.config` when that module loads, so an early call
    would otherwise silently AttributeError inside update()."""
    import jax
    try:
        import jax._src.xla_bridge  # noqa: F401  (registers the flag)
    except ImportError:
        pass
    try:
        jax.config.update("jax_cpu_collectives_implementation", value)
        return True
    except (AttributeError, ValueError):
        return False


def jaxlib_extension():
    """The jaxlib extension module under whichever name this jaxlib uses.

    Raises ImportError only if NEITHER name resolves (a jaxlib newer than
    both naming schemes) — callers keep their own documented fallbacks.
    """
    try:
        from jax._src.lib import _jax as ext  # jaxlib >= 0.5
        return ext
    except ImportError:
        from jax._src.lib import xla_extension as ext  # jaxlib <= 0.4.x
        return ext


def make_distributed_service(address: str, num_nodes: int,
                             heartbeat_timeout: int,
                             shutdown_timeout: int):
    """Start a jax distributed-runtime (coordination) service.

    Adapts the keepalive knobs: new jaxlib takes heartbeat_timeout
    directly; old jaxlib takes an interval and a missed-beat count whose
    product is the effective timeout.
    """
    ext = jaxlib_extension()
    try:
        return ext.get_distributed_runtime_service(
            address, num_nodes, heartbeat_timeout=heartbeat_timeout,
            shutdown_timeout=shutdown_timeout)
    except TypeError:
        missing = 10
        return ext.get_distributed_runtime_service(
            address, num_nodes,
            heartbeat_interval=max(1, heartbeat_timeout // missing),
            max_missing_heartbeats=missing,
            shutdown_timeout=shutdown_timeout)


def make_distributed_client(coord: str, rank: int, init_timeout: int,
                            heartbeat_timeout: int, shutdown_timeout: int):
    """Construct (don't connect) a distributed-runtime client for `coord`.

    Returns (client, recoverable): new jaxlib gives the recoverable client
    the elastic path wants (in-process reconnect after a peer failure);
    old jaxlib lacks the `recoverable` kwarg, so the client is standard —
    still correct for elastic, because every round gets a FRESH
    launcher-side service and therefore a fresh client, just without
    reconnect-to-the-same-service semantics.

    This exists because old jax.distributed.initialize() cannot be used
    here at all: on process 0 it auto-starts a SECOND coordination
    service on the coordinator port, racing the launcher-owned one —
    registration then deadlocks on whichever service lost the bind.
    """
    ext = jaxlib_extension()
    factory = ext.get_distributed_runtime_client
    try:
        return factory(coord, rank, init_timeout=init_timeout,
                       heartbeat_timeout=heartbeat_timeout,
                       shutdown_timeout=shutdown_timeout,
                       use_compression=True, recoverable=True,
                       shutdown_on_destruction=False), True
    except TypeError:
        pass
    try:
        # middle range: heartbeat_timeout exists, `recoverable` not yet
        return factory(coord, rank, init_timeout=init_timeout,
                       heartbeat_timeout=heartbeat_timeout,
                       shutdown_timeout=shutdown_timeout,
                       use_compression=True,
                       shutdown_on_destruction=False), False
    except TypeError:
        missing = 10
        return factory(coord, rank, init_timeout=init_timeout,
                       heartbeat_interval=max(
                           1, heartbeat_timeout // missing),
                       max_missing_heartbeats=missing,
                       shutdown_timeout=shutdown_timeout,
                       use_compression=True,
                       shutdown_on_destruction=False), False
