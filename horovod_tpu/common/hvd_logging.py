"""Rank-prefixed logging.

Reference: horovod/common/logging.cc — C++ macro logger with levels TRACE..
FATAL, optional timestamps, rank prefix, controlled by HOROVOD_LOG_LEVEL /
HOROVOD_LOG_HIDE_TIME. Here it is a thin layer over the std logging module
with the same env contract.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG,   # python has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger: logging.Logger | None = None


class _RankFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        from horovod_tpu.core import topology
        record.hvd_rank = topology.rank_or_none()
        if record.hvd_rank is None:
            record.hvd_rank = "-"
        return True


def get_logger() -> logging.Logger:
    global _logger
    if _logger is not None:
        return _logger
    logger = logging.getLogger("horovod_tpu")
    level = _LEVELS.get(os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(),
                        logging.WARNING)
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        hide_time = os.environ.get("HOROVOD_LOG_HIDE_TIME", "").lower() in (
            "1", "true", "yes")
        fmt = "[%(levelname)s | rank %(hvd_rank)s] %(message)s" if hide_time else \
            "%(asctime)s [%(levelname)s | rank %(hvd_rank)s] %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        handler.addFilter(_RankFilter())
        logger.addHandler(handler)
        logger.propagate = False
    _logger = logger
    return logger
