"""Rank-prefixed logging.

Reference: horovod/common/logging.cc — C++ macro logger with levels TRACE..
FATAL, optional timestamps, rank prefix, controlled by HOROVOD_LOG_LEVEL /
HOROVOD_LOG_HIDE_TIME. Here it is a thin layer over the std logging module
with the same env contract, plus:

* ``HOROVOD_LOG_FORMAT=json`` — one JSON object per line (ts, level,
  rank, elastic round, message, optional exception), for log pipelines
  that ingest structured records instead of scraping prefixes.
* The rank/round context is resolved PER RECORD by a logging.Filter,
  never captured at first emission: after an elastic reset re-assigns
  this process a new rank (elastic/__init__.py `_reset` rewrites
  HOROVOD_RANK and re-inits topology), the very next log line carries
  the new rank and round.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG,   # python has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger: logging.Logger | None = None


class _ContextFilter(logging.Filter):
    """Stamp each record with the CURRENT rank and elastic round.

    Runs per record, so the prefix tracks elastic re-inits instead of
    freezing at whatever the first emission saw.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        from horovod_tpu.core import topology
        rank = topology.rank_or_none()
        record.hvd_rank = "-" if rank is None else rank
        record.hvd_round = os.environ.get("HOROVOD_ELASTIC_ROUND", "") or "-"
        return True


class _JsonFormatter(logging.Formatter):
    """HOROVOD_LOG_FORMAT=json: one structured object per line."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S")
                  + f".{int(record.msecs):03d}",
            "level": record.levelname.lower(),
            "rank": getattr(record, "hvd_rank", "-"),
            "round": getattr(record, "hvd_round", "-"),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj)


def _make_formatter() -> logging.Formatter:
    fmt_kind = os.environ.get("HOROVOD_LOG_FORMAT", "text").strip().lower()
    if fmt_kind == "json":
        return _JsonFormatter()
    hide_time = os.environ.get("HOROVOD_LOG_HIDE_TIME", "").lower() in (
        "1", "true", "yes")
    fmt = "[%(levelname)s | rank %(hvd_rank)s] %(message)s" if hide_time \
        else "%(asctime)s [%(levelname)s | rank %(hvd_rank)s] %(message)s"
    return logging.Formatter(fmt)


def get_logger() -> logging.Logger:
    global _logger
    if _logger is not None:
        return _logger
    logger = logging.getLogger("horovod_tpu")
    level = _LEVELS.get(os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(),
                        logging.WARNING)
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_make_formatter())
        handler.addFilter(_ContextFilter())
        logger.addHandler(handler)
        logger.propagate = False
    _logger = logger
    return _logger


def reset_for_tests() -> None:
    """Drop the cached logger AND its handlers so the next get_logger()
    re-reads HOROVOD_LOG_LEVEL / HOROVOD_LOG_FORMAT / _HIDE_TIME."""
    global _logger
    logger = logging.getLogger("horovod_tpu")
    for h in list(logger.handlers):
        logger.removeHandler(h)
    _logger = None
