"""Elastic training on Spark (reference: spark/runner.py:312
run_elastic — elastic Horovod where each Spark task hosts one worker).

Control is inverted versus the CLI elastic launcher: the launcher can
ssh-spawn worker processes, but a Spark driver cannot start individual
tasks — tasks are where the compute already lives. So each Spark task
runs a long-lived AGENT that places worker subprocesses on command:

  agent/<i>                       heartbeat {host, ts} (registration)
  fn                              cloudpickled user fn (driver → agents)
  launch/<round>/<host>           worker env for a fresh slot
  kill/<host>                     terminate this agent's worker
  status/<round>/<host>/<slot>    worker exit code (agent → driver)
  result/<round>/<rank>           pickled fn() result (agent → driver)
  stopall                         job over; agents exit

(all keys in the job rendezvous KV, scope "spark_elastic", HMAC-signed
like every control-plane write). The driver side reuses the SAME
ElasticDriver/RoundPublisher/drive_elastic_loop as the CLI path —
discovery reads agent heartbeats instead of a discovery script, and
spawn/stop write KV commands instead of ssh-ing. Survivor preservation,
round bumps, and in-worker re-rendezvous are identical.

The agent protocol is Spark-agnostic (it only needs a KV client), which
is also how it is tested: agents in threads + real worker subprocesses,
no Spark installed.
"""

from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

_SCOPE = "spark_elastic"
HEARTBEAT_SECONDS = 2.0
STALE_AFTER_SECONDS = 15.0


# ----------------------------------------------------------------------
# agent (runs inside each Spark task)
# ----------------------------------------------------------------------

def agent_main(kv, index: int, stop_event: Optional[threading.Event] = None,
               poll_interval: float = 0.2) -> None:
    """One placement agent. `kv` is a KVClient bound to the job
    rendezvous; `index` is the agent's stable id (its Spark task index).
    Returns when the driver writes `stopall`."""
    host = f"agent{index}"
    stop_event = stop_event or threading.Event()
    proc: Optional[subprocess.Popen] = None
    proc_round = -1
    fn_path: Optional[str] = None

    def beat():
        while not stop_event.is_set():
            try:
                kv.put(_SCOPE, f"agent/{index}",
                       json.dumps({"host": host,
                                   "ts": time.time()}).encode())
            except Exception:
                pass
            stop_event.wait(HEARTBEAT_SECONDS)

    hb = threading.Thread(target=beat, daemon=True)
    hb.start()
    proc_dirs: List[str] = []
    last_kv_ok = time.monotonic()
    try:
        while not stop_event.is_set():
            try:
                if kv.get(_SCOPE, "stopall", timeout=0) is not None:
                    break
                raw_round = kv.get(_SCOPE, "round_hint", timeout=0)
                last_kv_ok = time.monotonic()
            except Exception:
                # Transient KV outage must not kill the agent — capacity
                # would vanish permanently. But a dead rendezvous (job
                # torn down) must not leave agents spinning either.
                if time.monotonic() - last_kv_ok > 60.0:
                    break
                stop_event.wait(poll_interval)
                continue
            cur_round = int(raw_round) if raw_round else 0
            # launch command for this host at the current (or previous —
            # publish precedes the hint bump) round
            for rid in (cur_round, cur_round + 1):
                raw = kv.get(_SCOPE, f"launch/{rid}/{host}", timeout=0)
                if raw is None:
                    continue
                rec = json.loads(raw)
                if rec["round"] <= proc_round:
                    continue
                if proc is not None and proc.poll() is None:
                    # A still-running worker with NO newer launch record
                    # is a survivor (it re-rendezvouses in-process; the
                    # driver only writes launch for slots it actually
                    # spawned). But a newer launch record for this host
                    # means the driver replaced the worker — if its kill
                    # command was swallowed by spawn()'s stale-key
                    # cleanup before we consumed it (ADVICE r2), the old
                    # process would live forever and stall the host.
                    # The launch record IS the authoritative kill.
                    proc.terminate()
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        try:
                            proc.wait(timeout=30)
                        except subprocess.TimeoutExpired:
                            # unreapable (D-state): abandon the corpse
                            # rather than crash the agent and lose the
                            # host's capacity for good
                            pass
                if fn_path is None:
                    blob = kv.get(_SCOPE, "fn")
                    with tempfile.NamedTemporaryFile(
                            "wb", suffix=".pkl", delete=False) as f:
                        f.write(blob)
                        fn_path = f.name
                out_dir = tempfile.mkdtemp(prefix=f"hvd_spark_el_{index}_")
                proc_dirs.append(out_dir)
                env = dict(os.environ)
                env.update(rec["env"])
                env["HOROVOD_RUN_FUNC_FILE"] = fn_path
                env["HOROVOD_RUN_RESULT_DIR"] = out_dir
                proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "horovod_tpu.runner.task_runner"], env=env)
                proc_round = rec["round"]
                proc_rank = rec["rank"]
                proc_dir = out_dir
            if kv.get(_SCOPE, f"kill/{host}", timeout=0) is not None:
                if proc is not None and proc.poll() is None:
                    proc.terminate()
                # consume the command: a lingering kill key would
                # murder every future worker on this agent
                kv.delete(_SCOPE, f"kill/{host}")
            if proc is not None:
                code = proc.poll()
                if code is not None:
                    if code == 0:
                        # rank_<n>.pkl is named by the SPAWN-time rank
                        # env (task_runner), which is proc_rank even if
                        # the worker re-ranked as a survivor — results
                        # are therefore published HOST-keyed and the
                        # driver maps host -> final rank.
                        res = os.path.join(proc_dir,
                                           f"rank_{proc_rank}.pkl")
                        try:
                            with open(res, "rb") as f:
                                kv.put(_SCOPE, f"result/{host}", f.read())
                        except OSError:
                            code = 1
                    kv.put(_SCOPE, f"status/{proc_round}/{host}/0",
                           str(code).encode())
                    proc = None
            time.sleep(poll_interval)
    finally:
        stop_event.set()
        if proc is not None and proc.poll() is None:
            proc.terminate()
        hb.join(timeout=2)
        import shutil
        if fn_path:
            try:
                os.unlink(fn_path)
            except OSError:
                pass
        for d in proc_dirs:
            shutil.rmtree(d, ignore_errors=True)


# ----------------------------------------------------------------------
# driver side
# ----------------------------------------------------------------------

class KVAgentDiscovery:
    """HostDiscovery over agent heartbeats (duck-typed for HostManager).
    Agents register under fixed indices, so discovery polls
    agent/0..max_agents-1 — the KV has no key listing by design."""

    def __init__(self, kv, max_agents: int):
        self.kv = kv
        self.max_agents = max_agents

    def __init_last_seen(self):
        if not hasattr(self, "_last_seen"):
            self._last_seen: Dict[int, tuple] = {}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        # Staleness is judged by when the heartbeat VALUE last changed on
        # the DRIVER's clock — executor clocks can be skewed arbitrarily,
        # so the remote "ts" field is treated as an opaque nonce.
        self.__init_last_seen()
        now = time.monotonic()
        out: Dict[str, int] = {}
        for i in range(self.max_agents):
            raw = self.kv.get(_SCOPE, f"agent/{i}", timeout=0)
            if raw is None:
                continue
            prev = self._last_seen.get(i)
            if prev is None or prev[0] != raw:
                self._last_seen[i] = (raw, now)
            if now - self._last_seen[i][1] <= STALE_AFTER_SECONDS:
                out[json.loads(raw)["host"]] = 1
        return out


class _AgentHandle:
    """Worker handle whose liveness is the agent-reported status key."""

    def __init__(self, kv, round_id: int, host: str):
        self.kv = kv
        self.round_id = round_id
        self.host = host
        self._killed = False

    def poll(self) -> Optional[int]:
        raw = self.kv.get(_SCOPE, f"status/{self.round_id}/{self.host}/0",
                          timeout=0)
        if raw is not None:
            return int(raw)
        if self._killed:
            return 143
        return None

    def terminate(self) -> None:
        self._killed = True
        self.kv.put(_SCOPE, f"kill/{self.host}", b"1")


def run_elastic(fn, args=(), kwargs=None,
                num_proc: Optional[int] = None,
                min_num_proc: int = 1,
                max_num_proc: Optional[int] = None,
                start_timeout: float = 600.0,
                elastic_timeout: float = 600.0,
                reset_limit: Optional[int] = None,
                extra_env: Optional[dict] = None,
                verbose: int = 1,
                _agent_runner=None) -> List[Any]:
    """Elastic run over Spark tasks (reference: spark/runner.py:312).

    `_agent_runner(n, kv_factory)` is injectable for tests (threads); the
    default submits a Spark job with n long-lived agent tasks.
    """
    import cloudpickle

    from horovod_tpu.common import config as C
    from horovod_tpu.elastic.driver import (ElasticDriver, HostManager,
                                            RoundPublisher,
                                            drive_elastic_loop)
    from horovod_tpu.runner import secret as secret_mod
    from horovod_tpu.runner.launch import _local_ip
    from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer

    job_secret = secret_mod.make_secret_key()
    rdv = RendezvousServer(secret=job_secret.encode())
    rdv_port = rdv.start()
    ip = _local_ip()
    kv = KVClient(ip, rdv_port, secret=job_secret.encode())
    kv.put(_SCOPE, "fn",
           cloudpickle.dumps(lambda: fn(*args, **(kwargs or {}))))

    n_agents = num_proc or max_num_proc or min_num_proc
    max_agents = max_num_proc or n_agents

    if _agent_runner is None:
        _agent_runner = _spark_agent_runner(ip, rdv_port, job_secret,
                                            verbose)
    agent_job = _agent_runner(n_agents, max_agents)

    publisher = RoundPublisher(rdv, ip)
    base_env = dict(extra_env or {})
    base_env.update({
        C.HOROVOD_RENDEZVOUS_ADDR: ip,
        C.HOROVOD_RENDEZVOUS_PORT: str(rdv_port),
        secret_mod.SECRET_ENV: job_secret,
        C.HOROVOD_ELASTIC: "1",
        "HOROVOD_ELASTIC_TIMEOUT": str(elastic_timeout),
        # agents share the launch host in tests; workers must own one CPU
        # device each unless the caller overrides
        "HOROVOD_WORKER_PLATFORM": base_env.get(
            "HOROVOD_WORKER_PLATFORM", "cpu"),
    })

    def spawn(slot, round_id: int):
        env = dict(base_env)
        env.update({
            "HOROVOD_ELASTIC_ROUND": str(round_id),
            "HOROVOD_COORDINATOR_ADDR": publisher.round_coords[round_id],
            "HOROVOD_RANK": str(slot.rank),
            "HOROVOD_SIZE": str(slot.size),
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        })
        # Clear stale commands/results from this host's previous life —
        # a lingering kill would murder the fresh worker on arrival.
        kv.delete(_SCOPE, f"kill/{slot.hostname}")
        kv.delete(_SCOPE, f"result/{slot.hostname}")
        kv.put(_SCOPE, f"launch/{round_id}/{slot.hostname}",
               json.dumps({"round": round_id, "rank": slot.rank,
                           "env": env}).encode())
        kv.put(_SCOPE, "round_hint", str(round_id).encode())
        return _AgentHandle(kv, round_id, slot.hostname)

    hm = HostManager(KVAgentDiscovery(kv, max_agents))
    driver = ElasticDriver(
        hm, spawn, lambda h: h.terminate(),
        min_num_proc=min_num_proc,
        max_num_proc=max_num_proc,
        reset_limit=reset_limit,
        publish_fn=publisher.publish)

    deadline = time.monotonic() + start_timeout

    def _poll_agents() -> bool:
        # update_available_hosts may raise (discovery hiccup, injected
        # flap): absorb until start_timeout — the deadline below stays
        # the single bound on this wait, like wait_for_available_slots.
        try:
            return bool(hm.update_available_hosts())
        except Exception as e:
            print(f"elastic spark: discovery error while waiting for "
                  f"agents: {e}", file=sys.stderr)
            return False

    while not (_poll_agents() or hm.current_hosts):
        if time.monotonic() > deadline:
            kv.put(_SCOPE, "stopall", b"1")
            rdv.stop()
            raise TimeoutError(
                "no Spark agent registered before start_timeout")
        time.sleep(0.2)

    remaining = max(0.0, deadline - time.monotonic())
    driver.start(start_timeout=max(remaining, 1.0))
    try:
        rc = drive_elastic_loop(driver, elastic_timeout)
        if rc != 0:
            raise RuntimeError(f"elastic spark job failed (rc={rc})")
        # Results are HOST-keyed (survivors' spawn-time ranks go stale on
        # resize); the driver owns the final host -> rank mapping
        # (snapshotted by driver.stop()).
        slots = getattr(driver, "last_round_slots", None) or \
            driver.current_slots()
        results: List[Any] = [None] * len(slots)
        for slot in slots:
            raw = kv.get(_SCOPE, f"result/{slot.hostname}", timeout=30.0)
            if raw is not None:
                results[slot.rank] = pickle.loads(raw)
        return results
    finally:
        kv.put(_SCOPE, "stopall", b"1")
        publisher.close()
        if agent_job is not None:
            try:
                agent_job.join(timeout=10)
            except Exception:
                pass
        rdv.stop()


def _spark_agent_runner(ip: str, port: int, job_secret: str, verbose: int):
    """Default agent placement: one long-lived Spark task per agent."""

    def runner(n_agents: int, max_agents: int):
        import pyspark

        sc = pyspark.SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError("no active SparkContext; create a "
                               "SparkSession first")

        def task(index, _it):
            import os as _os

            from horovod_tpu.runner.rendezvous import KVClient as _KV
            _os.environ[
                "HOROVOD_SECRET_KEY"] = job_secret  # noqa: F841
            from horovod_tpu.spark.elastic import agent_main
            agent_main(_KV(ip, port, secret=job_secret.encode()), index)
            yield index

        t = threading.Thread(
            target=lambda: (sc.parallelize(range(n_agents), n_agents)
                            .mapPartitionsWithIndex(task).collect()),
            daemon=True)
        t.start()
        return t

    return runner
