"""Data preparation and parquet IO for the estimator stack.

Reference: horovod/spark/common/util.py — prepare_data (DataFrame →
parquet in the Store, util.py:576+), get_simple_meta_from_parquet
(row counts + column metadata), and the Petastorm reader plumbing the
remote trainers use. Petastorm is replaced by pyarrow.dataset: trainers
read their rank's shard of row groups straight into numpy, which is what
a TPU input pipeline wants (contiguous host arrays, no torch/TF reader
dependency).

Accepted inputs: a pandas DataFrame (written to parquet here on the
driver — works with no Spark at all) or a pyspark DataFrame (written by
the cluster via df.write.parquet).
"""

from __future__ import annotations

import json
import posixpath
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import numpy as np

_META_FILE = "_hvd_tpu_metadata.json"


def _is_pyspark_df(df) -> bool:
    mod = type(df).__module__ or ""
    return mod.startswith("pyspark")


def _cell_array(v) -> np.ndarray:
    """One cell as ndarray; Spark ML Vectors (anything with .toArray)
    are materialized (reference: store.py:617 vector adapters)."""
    return np.asarray(v.toArray() if hasattr(v, "toArray") else v)


def _stack_cells(values) -> np.ndarray:
    return np.stack([_cell_array(v) for v in values])


def _col_meta(arr: np.ndarray) -> Dict:
    """Shape/dtype metadata for one column (reference: util.py metadata
    dict with 'shape'/'intermediate_format' per column)."""
    a = np.asarray(arr)
    elem_shape = a.shape[1:] if a.ndim > 1 else ()
    return {"dtype": str(a.dtype), "shape": list(elem_shape)}


def restore_column(arr, meta: Dict) -> np.ndarray:
    """Restore a column read from parquet to its recorded per-element
    shape and dtype (reference: util.py:200+ metadata-driven reshaping —
    cells are stored flattened; shape/dtype live in the dataset
    metadata). Accepts object arrays of lists/arrays/Vectors or plain
    ndarrays."""
    shape = tuple(meta.get("shape") or ())
    dtype = np.dtype(meta["dtype"])
    a = np.asarray(arr)
    n = len(a)
    if a.dtype == object:
        a = _stack_cells(a) if n else np.zeros((0,) + shape, dtype)
    a = a.reshape((n,) + shape)
    return a.astype(dtype, copy=False)


def _pandas_to_parquet(df, path: str, store, n_shards: int) -> int:
    """Write a pandas DataFrame as n parquet shard files under `path`.

    Object cells (ndarrays / nested lists / Spark ML Vectors) are stored
    FLATTENED as 1-D lists — arrow cannot hold multi-dim cells — with the
    element shape recorded in the dataset metadata and restored by
    `restore_column` on read (reference: util.py:200+ same contract)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    flat = {}
    for c in df.columns:
        vals = df[c].values
        if vals.dtype == object and len(vals) and (
                hasattr(vals[0], "toArray")
                or np.asarray(vals[0]).ndim >= 1):
            flat[c] = [_cell_array(v).ravel().tolist() for v in vals]
        else:
            flat[c] = vals

    store.makedirs(path)
    n = len(df)
    bounds = np.linspace(0, n, n_shards + 1, dtype=int)
    fs = store.fs()
    for i in range(n_shards):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        table = pa.table({c: v[lo:hi] for c, v in flat.items()})
        with fs.open(posixpath.join(path, f"part-{i:05d}.parquet"),
                     "wb") as f:
            pq.write_table(table, f)
    return n


def _split_validation(df, validation):
    """Split off validation rows (reference: util.py _train_val_split —
    float fraction or boolean column name)."""
    if validation is None:
        return df, None
    if isinstance(validation, str):
        val = df[df[validation].astype(bool)]
        train = df[~df[validation].astype(bool)]
        return train.drop(columns=[validation]), \
            val.drop(columns=[validation])
    frac = float(validation)
    if not 0.0 < frac < 1.0:
        raise ValueError(f"validation fraction must be in (0,1): {frac}")
    n_val = int(len(df) * frac)
    return (df.iloc[:-n_val], df.iloc[-n_val:]) if n_val else (df, None)


def _pyspark_to_parquet(df, cols, validation, store,
                        train_path: str, val_path: str, shards: int):
    """Split + write a pyspark DataFrame from the executors."""
    from pyspark.sql import functions as F

    # Spark ML Vector columns -> array<double> so parquet holds plain
    # lists (reference: store.py:617 to_petastorm vector adapters).
    try:
        from pyspark.ml.functions import vector_to_array
        from pyspark.ml.linalg import VectorUDT
        for f in df.schema.fields:
            if f.name in cols and isinstance(f.dataType, VectorUDT):
                df = df.withColumn(f.name, vector_to_array(F.col(f.name)))
    except (ImportError, AttributeError):
        pass  # pyspark without ML (or the test stub)

    if isinstance(validation, str):
        base = df.select(*(cols + [validation]))
        val_df = base.filter(F.col(validation).cast("boolean")) \
                     .drop(validation)
        train_df = base.filter(~F.col(validation).cast("boolean")) \
                       .drop(validation)
    elif validation:
        frac = float(validation)
        train_df, val_df = df.select(*cols).randomSplit(
            [1.0 - frac, frac], seed=97)
    else:
        train_df, val_df = df.select(*cols), None

    train_df.repartition(shards).write.mode("overwrite").parquet(train_path)
    val_rows = 0
    if val_df is not None:
        val_df.repartition(shards).write.mode("overwrite").parquet(val_path)
        val_rows = _parquet_row_count(store, val_path)
    # Count and sample from what was actually WRITTEN — re-evaluating the
    # DataFrame lineage (count(), limit().toPandas()) would launch extra
    # Spark jobs and, under a nondeterministic upstream, could disagree
    # with the files on disk.
    train_rows = _parquet_row_count(store, train_path)
    sample = _parquet_sample(store, train_path, cols, n=64)
    metadata = {
        c: _col_meta(_stack_cells(sample[c]) if sample[c].dtype == object
                     and len(sample[c]) else sample[c])
        for c in cols
    }
    return train_rows, val_rows, metadata


def _parquet_row_count(store, path: str) -> int:
    import pyarrow.parquet as pq

    fs = store.fs()
    total = 0
    for fname in store.list_files(path):
        if not str(fname).endswith(".parquet"):
            continue
        with fs.open(fname, "rb") as f:
            total += pq.ParquetFile(f).metadata.num_rows
    return total


def _parquet_sample(store, path: str, cols, n: int) -> Dict[str, np.ndarray]:
    import pyarrow.parquet as pq

    fs = store.fs()
    for fname in store.list_files(path):
        if not str(fname).endswith(".parquet"):
            continue
        with fs.open(fname, "rb") as f:
            table = pq.read_table(f, columns=list(cols)).slice(0, n)
        if table.num_rows:
            out = {}
            for c in cols:
                col = table.column(c)
                try:
                    out[c] = col.to_numpy(zero_copy_only=False)
                except (pa_import().ArrowInvalid,
                        pa_import().ArrowNotImplementedError):
                    out[c] = np.asarray(col.to_pylist(), dtype=object)
            return out
    return {c: np.zeros((0,)) for c in cols}


def pa_import():
    import pyarrow

    return pyarrow


@contextmanager
def prepare_data(num_processes: int, store, df,
                 label_columns: List[str],
                 feature_columns: List[str],
                 validation=None,
                 sample_weight_col: Optional[str] = None,
                 dataset_idx: Optional[int] = None,
                 verbose: int = 0):
    """Materialize `df` as parquet in the store; yield the dataset index.

    Reference: util.py prepare_data (:576) — a context manager keyed by a
    dataset cache index so repeated fits on the same data skip the write.
    The cache here is intentionally simple: each call gets a fresh idx
    unless the caller pins one.
    """
    if dataset_idx is None:
        idx = 0
        while store.exists(posixpath.join(
                store.get_train_data_path(idx), _META_FILE)):
            idx += 1
    else:
        idx = dataset_idx
    train_path = store.get_train_data_path(idx)
    val_path = store.get_val_data_path(idx)

    cols = list(feature_columns) + list(label_columns)
    if sample_weight_col:
        cols.append(sample_weight_col)

    shards = max(num_processes, 1)
    if _is_pyspark_df(df):
        # Cluster-side write: executors stream straight to the store, the
        # driver never materializes the dataset (reference: util.py
        # prepare_data's df.write through to_parquet helpers).
        train_rows, val_rows, metadata = _pyspark_to_parquet(
            df, cols, validation, store, train_path, val_path, shards)
    else:
        keep = cols + ([validation] if isinstance(validation, str) and
                       validation in getattr(df, "columns", []) else [])
        pdf = df[keep].copy()
        train_df, val_df = _split_validation(pdf, validation)
        train_rows = _pandas_to_parquet(train_df, train_path, store, shards)
        val_rows = (_pandas_to_parquet(val_df, val_path, store, shards)
                    if val_df is not None and len(val_df) else 0)
        metadata = {
            c: _col_meta(_stack_cells(train_df[c].values)
                         if train_df[c].dtype == object
                         and len(train_df) else train_df[c].values)
            for c in cols
        }
    meta = {"train_rows": train_rows, "val_rows": val_rows,
            "metadata": metadata, "feature_columns": list(feature_columns),
            "label_columns": list(label_columns),
            "sample_weight_col": sample_weight_col}
    store.write(posixpath.join(train_path, _META_FILE),
                json.dumps(meta).encode())
    yield idx


def get_simple_meta_from_parquet(store, label_columns=None,
                                 feature_columns=None,
                                 sample_weight_col=None,
                                 dataset_idx: Optional[int] = None
                                 ) -> Tuple[int, int, Dict, float]:
    """(train_rows, val_rows, metadata, avg_row_size_bytes) for a prepared
    dataset (reference: util.py get_simple_meta_from_parquet)."""
    idx = 0 if dataset_idx is None else dataset_idx
    train_path = store.get_train_data_path(idx)
    raw = store.read(posixpath.join(train_path, _META_FILE))
    meta = json.loads(raw)
    md = meta["metadata"]
    row_bytes = float(sum(
        np.dtype(m["dtype"]).itemsize * int(np.prod(m["shape"] or [1]))
        for m in md.values())) or 1.0
    return meta["train_rows"], meta["val_rows"], md, row_bytes


def _shard_files(files: List[str], rank: int, size: int) -> List[str]:
    """Round-robin file sharding. A rank beyond the file count gets NO
    files (an empty shard) — wrapping around would hand the same file to
    two ranks and silently double-weight its rows in every averaged
    gradient. The trainers' MIN-consensus step count turns the empty
    shard into a clear 'dataset too small for num_proc' error instead."""
    return [f for i, f in enumerate(files) if i % size == rank]


def read_shard(store, path: str, rank: int, size: int,
               columns: List[str]) -> Dict[str, np.ndarray]:
    """Read this rank's shard of a parquet dataset into numpy columns.

    Reference analog: the Petastorm `make_batch_reader(cur_shard=rank,
    shard_count=size)` call in spark/keras/remote.py; here a plain
    pyarrow read of the rank's file subset.
    """
    import pyarrow.parquet as pq

    files = [f for f in store.list_files(path)
             if str(f).endswith(".parquet")]
    if not files:
        raise FileNotFoundError(f"no parquet files under {path}")
    fs = store.fs()
    mine = _shard_files(files, rank, size)
    parts = []
    if not mine:
        # empty shard: zero-row table with the right schema so the
        # trainer's step consensus can diagnose it (footer-only read —
        # the shard file itself may be huge)
        import pyarrow as pa

        with fs.open(files[0], "rb") as f:
            schema = pq.ParquetFile(f).schema_arrow
        schema = pa.schema([schema.field(c) for c in columns])
        parts.append(schema.empty_table())
    for fname in mine:
        with fs.open(fname, "rb") as f:
            parts.append(pq.read_table(f, columns=columns))
    import pyarrow as pa

    table = pa.concat_tables(parts)
    out: Dict[str, np.ndarray] = {}
    for c in columns:
        col = table.column(c)
        try:
            out[c] = col.to_numpy(zero_copy_only=False)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            # nested/list cells: fall back to the object path
            out[c] = np.asarray(col.to_pylist(), dtype=object)
    return out


def batch_iter(data: Dict[str, np.ndarray], batch_size: int,
               shuffle: bool, seed: int, epoch: int,
               drop_remainder: bool = True):
    """Yield dict batches; epoch-deterministic shuffle so every rank with
    the same seed sees a different (sharded) but stable order."""
    cols = list(data)
    n = len(data[cols[0]])
    order = np.arange(n)
    if shuffle:
        rng = np.random.default_rng(seed * 100003 + epoch)
        rng.shuffle(order)
    end = n - (n % batch_size) if drop_remainder else n
    if end == 0 and n:
        end = n  # tiny shard: one short batch beats zero batches
    for lo in range(0, end, batch_size):
        sel = order[lo:lo + batch_size]
        yield {c: data[c][sel] for c in cols}
