"""Storage abstraction for the estimator stack.

Reference: horovod/spark/common/store.py — Store / AbstractFilesystemStore
/ FilesystemStore / LocalStore / HDFSStore / DBFSLocalStore (store.py:38,
167, 301, 386, 396, 540). The reference hand-rolls one subclass per
filesystem (pyarrow-HDFS, DBFS path rewriting, local); here a single
`FilesystemStore` rides fsspec, which already speaks local, HDFS, S3, GCS
and DBFS URLs — the TPU-era idiom for the same capability. Layout of the
run directory (intermediate data, per-run checkpoints and logs) mirrors
the reference so users find the same artifacts in the same places.
"""

from __future__ import annotations

import os
import posixpath
from typing import Optional


class Store:
    """Abstract artifact store (reference: store.py:38).

    Concrete stores expose paths for intermediate (parquet) train/val
    data and per-run checkpoints/logs, plus small read/write helpers used
    by the estimator to move models between driver and workers.
    """

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_test_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def exists(self, path: str) -> bool:
        raise NotImplementedError()

    def read(self, path: str) -> bytes:
        raise NotImplementedError()

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError()

    def write_text(self, path: str, text: str) -> None:
        self.write(path, text.encode())

    def makedirs(self, path: str) -> None:
        raise NotImplementedError()

    def is_parquet_dataset(self, path: str) -> bool:
        """True if `path` holds at least one parquet file."""
        raise NotImplementedError()

    @staticmethod
    def create(prefix_path: str, **kwargs) -> "Store":
        """Factory keyed on the URL scheme (reference: store.py:78
        Store.create dispatching to HDFSStore vs FilesystemStore)."""
        return FilesystemStore(prefix_path, **kwargs)


class FilesystemStore(Store):
    """fsspec-backed store: one class for local paths and remote URLs
    (hdfs://, s3://, gs://, ...) — subsumes the reference's
    FilesystemStore/HDFSStore/DBFSLocalStore split (store.py:301,396,540).
    """

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 runs_path: Optional[str] = None):
        self.prefix_path = prefix_path.rstrip("/")
        self._train_path = train_path
        self._val_path = val_path
        self._test_path = test_path
        self._runs_path = runs_path or self._join(self.prefix_path, "runs")
        import fsspec

        self._fs, self._root = fsspec.core.url_to_fs(self.prefix_path)

    # -- paths ------------------------------------------------------------
    def _join(self, *parts: str) -> str:
        return posixpath.join(*parts)

    def _data_path(self, base: Optional[str], name: str,
                   idx: Optional[int]) -> str:
        p = base or self._join(self.prefix_path, name)
        return p if idx is None else f"{p}.{idx}"

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        return self._data_path(self._train_path,
                               "intermediate_train_data", idx)

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        return self._data_path(self._val_path, "intermediate_val_data", idx)

    def get_test_data_path(self, idx: Optional[int] = None) -> str:
        return self._data_path(self._test_path,
                               "intermediate_test_data", idx)

    def get_run_path(self, run_id: str) -> str:
        return self._join(self._runs_path, run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._join(self.get_run_path(run_id), "logs")

    # -- IO ---------------------------------------------------------------
    def _strip(self, path: str) -> str:
        # fsspec filesystems want scheme-less paths for local fs; for
        # remote schemes the full URL works with the matching fs.
        return path

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def read(self, path: str) -> bytes:
        with self._fs.open(self._strip(path), "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        parent = posixpath.dirname(self._strip(path))
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(self._strip(path), "wb") as f:
            f.write(data)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(self._strip(path), exist_ok=True)

    def list_files(self, path: str):
        if not self.exists(path):
            return []
        out = []
        for p in sorted(self._fs.ls(self._strip(path), detail=False)):
            if self._fs.isfile(p):
                out.append(p)
        return out

    def is_parquet_dataset(self, path: str) -> bool:
        return any(str(p).endswith(".parquet")
                   for p in self.list_files(path))

    def fs(self):
        return self._fs


class LocalStore(FilesystemStore):
    """Local-filesystem store (reference: store.py:386 — LocalStore is the
    FilesystemStore specialization for plain paths)."""

    def __init__(self, prefix_path: str, **kwargs):
        super().__init__(os.path.abspath(prefix_path), **kwargs)


class HDFSStore(FilesystemStore):
    """HDFS store via fsspec's hdfs/webhdfs drivers (reference:
    store.py:396 HDFSStore over pyarrow.hdfs). Requires an fsspec HDFS
    backend at use time; construction fails with a clear error if the
    driver is unavailable."""

    def __init__(self, prefix_path: str, **kwargs):
        if not prefix_path.startswith(("hdfs://", "webhdfs://")):
            # Keep the leading slash: hdfs:///a/b = path /a/b on the
            # default namenode; hdfs://a/b would make "a" the namenode.
            prefix_path = "hdfs:///" + prefix_path.lstrip("/")
        super().__init__(prefix_path, **kwargs)
