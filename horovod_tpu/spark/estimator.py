"""Estimator / Model (Transformer) stack.

Reference: horovod/spark/common/estimator.py (HorovodEstimator /
HorovodModel), spark/keras/estimator.py, spark/torch/estimator.py — the
Spark-ML `est.fit(df) -> model; model.transform(df)` workflow: DataFrame
→ parquet in a Store → distributed training job → trained transformer.

TPU-first redesign:
  * The training backend is pluggable (backend.py): Spark tasks are one
    placement provider, `LocalBackend` (our launcher) is another — the
    estimator works, and is tested end-to-end, with no Spark installed.
  * The flagship estimator is `JaxEstimator` (the reference has none —
    its frontends are keras/torch/lightning); `TorchEstimator` mirrors
    the reference's torch estimator over our torch frontend.
  * Petastorm readers are replaced by pyarrow shard reads (util.py).

Data contract (reference: spark/common/util.py:200+ metadata-driven
reshaping): per-column element dtype + shape are recorded in the dataset
metadata at prepare time and restored end-to-end —

  * a SINGLE feature column whose elements are >= 2-D (e.g. an 8x8x1
    image) reaches the model as a shaped tensor `X[batch, *shape]` in
    its recorded dtype;
  * otherwise feature columns are concatenated column-wise into a
    float32 matrix `X[batch, D]` (vector cells flatten into their slot);
  * a single label column keeps its recorded dtype and shape (integer
    class labels stay integers); multiple label columns concatenate to
    float32.

Spark ML Vector columns are accepted (converted to arrays at prepare
time; Vector cells in pandas frames are materialized via .toArray()).
"""

from __future__ import annotations

import posixpath
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from horovod_tpu.spark.backend import Backend, LocalBackend, SparkBackend
from horovod_tpu.spark.params import EstimatorParams, ModelParams
from horovod_tpu.spark import util as sutil

_CKPT_FILE = "model.pkl"


def _stack_columns(data: Dict[str, np.ndarray], cols: List[str],
                   metadata: Optional[Dict] = None) -> np.ndarray:
    """Concat columns into a 2-D float32 matrix (vector cells flatten)."""
    mats = []
    for c in cols:
        a = np.asarray(data[c])
        if len(a) == 0:
            # Empty shard/frame: take the element width from the dataset
            # metadata when available; a bare object column keeps width 1,
            # which is all the zero-row paths (init probes, empty
            # transform) need. (reshape(0, -1) cannot infer a width from
            # zero elements, so build the 2-D form directly.)
            m = (metadata or {}).get(c)
            width = int(np.prod(m["shape"] or [1])) if m else \
                max(1, int(np.prod(a.shape[1:])))
            mats.append(np.zeros((0, max(1, width)), np.float32))
            continue
        if a.dtype == object:
            a = sutil._stack_cells(a)
        a = a.reshape(len(a), -1)
        mats.append(a.astype(np.float32, copy=False))
    return np.concatenate(mats, axis=1) if len(mats) > 1 else mats[0]


def _features(data: Dict[str, np.ndarray], cols: List[str],
              metadata: Optional[Dict] = None) -> np.ndarray:
    """Model input: a single >=2-D feature column arrives SHAPED in its
    recorded dtype (image-style models); anything else is the flat
    float32 matrix (reference: util.py:200+ reshaping per metadata)."""
    if metadata and len(cols) == 1:
        m = metadata.get(cols[0])
        if m and len(m.get("shape") or ()) >= 2:
            a = sutil.restore_column(data[cols[0]], m)
            if a.dtype.kind == "f" and a.dtype != np.float32:
                # float64 cells (numpy's default) would feed DoubleTensors
                # to float32 torch/keras models; integer dtypes keep
                a = a.astype(np.float32)
            return a
    return _stack_columns(data, cols, metadata)


def _labels(data: Dict[str, np.ndarray], cols: List[str],
            metadata: Optional[Dict] = None) -> np.ndarray:
    if metadata and len(cols) == 1 and cols[0] in metadata:
        # dtype/shape-preserving path: int class labels stay int; float64
        # (numpy/Spark default) normalizes to float32 for f32 models
        y = sutil.restore_column(data[cols[0]], metadata[cols[0]])
        if y.dtype.kind == "f" and y.dtype != np.float32:
            y = y.astype(np.float32)
        return y
    y = _stack_columns(data, cols, metadata)
    return y[:, 0] if y.shape[1] == 1 else y


class HorovodEstimator(EstimatorParams):
    """Backend-agnostic base (reference: estimator.py:25 HorovodEstimator).

    Subclasses supply `_make_trainer_payload` (what ships to workers) and
    `_make_model` (wrap the trained state as a transformer).
    """

    def fit(self, df, params: Optional[dict] = None) -> "HorovodModel":
        if params:
            return self.copy(params).fit(df)
        backend = self._get_or_create_backend()
        store = self.getStore()
        if store is None:
            raise ValueError("estimator requires store=Store.create(...)")
        with sutil.prepare_data(
                backend.num_processes(), store, df,
                label_columns=self.getLabelCols(),
                feature_columns=self.getFeatureCols(),
                validation=self.getValidation(),
                sample_weight_col=self.getSampleWeightCol(),
                verbose=self.getVerbose()) as dataset_idx:
            return self._fit_on_prepared_data(backend, dataset_idx)

    def fit_on_parquet(self, params: Optional[dict] = None,
                       dataset_idx: Optional[int] = None) -> "HorovodModel":
        """Train on already-prepared parquet at the store's train path
        (reference: estimator.py:37 fit_on_parquet)."""
        if params:
            return self.copy(params).fit_on_parquet(dataset_idx=dataset_idx)
        backend = self._get_or_create_backend()
        return self._fit_on_prepared_data(backend, dataset_idx or 0)

    # -- internals --------------------------------------------------------
    def _get_or_create_backend(self) -> Backend:
        backend = self.getBackend()
        if backend is not None:
            if self.getNumProc() is not None:
                raise ValueError(
                    'at most one of "backend" and "num_proc" may be set')
            return backend
        np_ = self.getNumProc()
        try:
            import pyspark  # noqa: F401
            has_spark = (pyspark.SparkContext._active_spark_context
                         is not None)
        except ImportError:
            has_spark = False
        if has_spark:
            return SparkBackend(np_, verbose=self.getVerbose())
        return LocalBackend(np_ or 1)

    def _fit_on_prepared_data(self, backend: Backend,
                              dataset_idx: int) -> "HorovodModel":
        import cloudpickle

        store = self.getStore()
        run_id = self.getRunId() or f"run_{uuid.uuid4().hex[:12]}"
        train_rows, val_rows, metadata, _ = \
            sutil.get_simple_meta_from_parquet(store,
                                               dataset_idx=dataset_idx)
        payload = cloudpickle.dumps(dict(
            kind=self._kind,
            store=store,
            dataset_idx=dataset_idx,
            run_id=run_id,
            train_rows=train_rows,
            val_rows=val_rows,
            metadata=metadata,
            trainer=self._make_trainer_payload(),
            feature_cols=self.getFeatureCols(),
            label_cols=self.getLabelCols(),
            sample_weight_col=self.getSampleWeightCol(),
            batch_size=self.getBatchSize(),
            val_batch_size=self.getValBatchSize() or self.getBatchSize(),
            epochs=self.getEpochs(),
            train_steps_per_epoch=self.getTrainStepsPerEpoch(),
            val_steps_per_epoch=self.getValidationStepsPerEpoch(),
            shuffle=self.getShuffle(),
            seed=self.getRandomSeed(),
            shuffle_seed=(self.getShufflingSeed()
                          if self.getShufflingSeed() is not None
                          else self.getRandomSeed()),
            callbacks=self.getCallbacks(),
            compression=self.getCompression(),
            predivide=self.getGradientPredivideFactor(),
            bpps=self.getBackwardPassesPerStep(),
            use_adasum=self.getUseAdasum(),
            verbose=self.getVerbose(),
        ))
        results = backend.run(_remote_train, args=(payload,))
        history = results[0]
        blob = store.read(posixpath.join(
            store.get_checkpoint_path(run_id), _CKPT_FILE))
        state = cloudpickle.loads(blob)
        return self._make_model(state, metadata, run_id, history)

    _kind = "base"

    def _make_trainer_payload(self) -> dict:
        raise NotImplementedError()

    def _make_model(self, state, metadata, run_id, history):
        raise NotImplementedError()


class HorovodModel(ModelParams):
    """Trained transformer (reference: estimator.py:100 HorovodModel).

    `transform(df)` appends prediction columns. pandas DataFrames are
    handled directly; pyspark DataFrames go through mapInPandas so
    inference runs on the executors (reference: torch/estimator.py
    transform via udf).
    """

    def __init__(self, history: Optional[list] = None, **kwargs):
        super().__init__(**kwargs)
        self.history = history or []

    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError()

    def _output_cols(self) -> List[str]:
        out = self.getOutputCols()
        if out:
            return out
        return [f"{c}__output" for c in self.getLabelCols()]

    def _transform_pandas(self, pdf):
        if not len(pdf):
            out = pdf.copy()
            for c in self._output_cols():
                out[c] = np.zeros((0,), np.float32)
            return out
        bs = self.getBatchSize()
        data = {c: pdf[c].values for c in self.getFeatureCols()}
        X = _features(data, self.getFeatureCols(), self.getMetadata())
        preds = np.concatenate(
            [np.asarray(self._predict_batch(X[i:i + bs]))
             for i in range(0, len(X), bs)])
        out = pdf.copy()
        ocols = self._output_cols()
        if preds.ndim > 1 and preds.shape[-1] == 1:
            preds = preds[..., 0]  # (B,1) -> scalar column
        if preds.ndim == 1 and len(ocols) > 1:
            raise ValueError(
                f"model produced 1 output per row but {len(ocols)} "
                f"output columns were requested: {ocols}")
        if preds.ndim == 1 or len(ocols) == 1:
            out[ocols[0]] = list(preds) if preds.ndim > 1 else preds
        else:
            if preds.shape[-1] % len(ocols):
                raise ValueError(
                    f"model output width {preds.shape[-1]} is not "
                    f"divisible across {len(ocols)} output columns")
            per = preds.shape[-1] // len(ocols)
            for j, c in enumerate(ocols):
                cut = preds[..., j * per:(j + 1) * per]
                out[c] = list(cut) if per > 1 else cut[..., 0]
        return out

    def _spark_output_schema(self, df, probe_pdf):
        """Input schema + prediction columns, typed by probing a small
        local predict (the reference derives this from stored metadata;
        probing needs no metadata contract)."""
        from pyspark.sql.types import (ArrayType, DoubleType, StructField,
                                       StructType)

        fields = list(df.schema.fields)
        present = {f.name for f in fields}
        for c in self._output_cols():
            if c in present:
                continue
            cell = probe_pdf[c].iloc[0] if len(probe_pdf) else 0.0
            dt = (ArrayType(DoubleType())
                  if isinstance(cell, (list, np.ndarray)) else DoubleType())
            fields.append(StructField(c, dt, True))
        return StructType(fields)

    def transform(self, df, params: Optional[dict] = None):
        if params:
            return self.copy(params).transform(df)
        if sutil._is_pyspark_df(df):
            import cloudpickle

            blob = cloudpickle.dumps(self)
            probe = self._transform_pandas(df.limit(4).toPandas())
            schema = self._spark_output_schema(df, probe)

            def mapper(it):
                model = cloudpickle.loads(blob)
                for pdf in it:
                    out = model._transform_pandas(pdf)
                    for c in model._output_cols():
                        if out[c].dtype != object:
                            out[c] = out[c].astype(float)
                    yield out
            return df.mapInPandas(mapper, schema)
        return self._transform_pandas(df)


# ======================================================================
# JAX estimator (flagship)
# ======================================================================

class JaxEstimator(HorovodEstimator):
    """Estimator over a JAX/flax model.

    model: either a flax `nn.Module` (init/apply derived) or a pair
    `(init_fn, apply_fn)` with `init_fn(rng, X_sample) -> params` and
    `apply_fn(params, X) -> preds`.
    optimizer: an optax GradientTransformation.
    loss: `loss(preds, y[, sample_weight]) -> scalar` (jax-traceable).
    """

    _kind = "jax"

    def _make_trainer_payload(self) -> dict:
        model = self.getModel()
        if model is None or self.getOptimizer() is None \
                or self.getLoss() is None:
            raise ValueError("JaxEstimator requires model=, optimizer=, "
                             "loss=")
        return dict(model=model, optimizer=self.getOptimizer(),
                    loss=self.getLoss(), metrics=self.getMetrics())

    def _make_model(self, state, metadata, run_id, history) -> "JaxModel":
        return JaxModel(history=history, model=state,
                        featureCols=self.getFeatureCols(),
                        labelCols=self.getLabelCols(),
                        runId=run_id, metadata=metadata)


class JaxModel(HorovodModel):
    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        import jax

        state = self.getModel()
        if getattr(self, "_jitted", None) is None:
            self._jitted = jax.jit(state["apply_fn"])
        return np.asarray(self._jitted(state["params"], X))

    def __getstate__(self):
        # Compiled executables don't pickle (and shouldn't ship to
        # executors); each process re-jits lazily.
        d = dict(self.__dict__)
        d.pop("_jitted", None)
        return d


# ======================================================================
# Torch estimator
# ======================================================================

class TorchEstimator(HorovodEstimator):
    """Estimator over a torch.nn.Module via the torch frontend
    (reference: spark/torch/estimator.py TorchEstimator).

    optimizer: factory `(params_iter) -> torch.optim.Optimizer`.
    loss: `loss(preds, y[, sample_weight]) -> scalar` (torch ops; the
        third positional arg is passed iff sampleWeightCol is set).
    """

    _kind = "torch"

    def _make_trainer_payload(self) -> dict:
        if self.getModel() is None or self.getOptimizer() is None \
                or self.getLoss() is None:
            raise ValueError("TorchEstimator requires model=, optimizer=, "
                             "loss=")
        return dict(model=self.getModel(), optimizer=self.getOptimizer(),
                    loss=self.getLoss(), metrics=self.getMetrics())

    def _make_model(self, state, metadata, run_id, history) -> "TorchModel":
        return TorchModel(history=history, model=state,
                          featureCols=self.getFeatureCols(),
                          labelCols=self.getLabelCols(),
                          runId=run_id, metadata=metadata)


class TorchModel(HorovodModel):
    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        import torch

        model = self.getModel()
        model.eval()
        with torch.no_grad():
            return model(torch.from_numpy(np.asarray(X))).numpy()


# ======================================================================
# Remote trainer (runs on every worker under the backend)
# ======================================================================

def _remote_train(payload: bytes):
    import cloudpickle

    spec = cloudpickle.loads(payload)
    if spec["kind"] == "jax":
        return _remote_train_jax(spec)
    if spec["kind"] == "torch":
        return _remote_train_torch(spec)
    if spec["kind"] == "keras":
        return _remote_train_keras(spec)
    if spec["kind"] == "lightning":
        return _remote_train_lightning(spec)
    raise ValueError(f"unknown estimator kind {spec['kind']}")


def _load_shards(spec, rank: int, size: int):
    store = spec["store"]
    cols = list(spec["feature_cols"]) + list(spec["label_cols"])
    if spec["sample_weight_col"]:
        cols.append(spec["sample_weight_col"])
    train = sutil.read_shard(store, store.get_train_data_path(
        spec["dataset_idx"]), rank, size, cols)
    val = None
    if spec["val_rows"]:
        val = sutil.read_shard(store, store.get_val_data_path(
            spec["dataset_idx"]), rank, size, cols)
    return train, val


def _batch_weights(b, spec):
    """The sample-weight column of one batch (reference:
    spark/common/params.py sample_weight_col — weights flow into the
    loss), or None when unconfigured."""
    col = spec.get("sample_weight_col")
    if not col:
        return None
    return np.asarray(b[col], np.float32).reshape(-1)


def _local_batch_count(data, batch_size: int) -> int:
    n = len(next(iter(data.values())))
    full = n // batch_size
    return full if full else (1 if n else 0)


def _agree_steps(hvd_allreduce, data, batch_size: int,
                 limit, allow_zero: bool = False) -> int:
    """Global per-epoch step count = MIN over ranks of local batches.

    Parquet shards are near-equal, not exactly equal, so ranks can hold
    different batch counts; every step runs one collective, so all ranks
    MUST agree on the count or the job deadlocks (the reference never hits
    this: its Petastorm readers cycle infinitely and steps_per_epoch is
    explicit, spark/keras/remote.py). One MIN consensus up front pins it.
    Every rank must call this unconditionally — it is itself a collective.
    """
    local = _local_batch_count(data, batch_size)
    agreed = int(np.asarray(hvd_allreduce(
        np.asarray(local, np.int32), op="min")))
    if limit is not None:
        agreed = min(agreed, int(limit))
    if agreed == 0 and not allow_zero:
        raise ValueError(
            "a worker received zero rows — dataset too small for "
            "num_proc; reduce processes or grow the dataset")
    return agreed


def _metric_dict(metrics) -> dict:
    if isinstance(metrics, dict):
        return dict(metrics)
    return {getattr(m, "__name__", f"metric_{i}"): m
            for i, m in enumerate(metrics or [])}


def _epoch_batches(spec, data, epoch: int, batch_size: int, steps: int):
    it = sutil.batch_iter(data, batch_size, spec["shuffle"],
                          spec["shuffle_seed"], epoch)
    for i, b in enumerate(it):
        if i >= steps:
            break
        yield b


def _run_training(spec, train, val, rank, *, allreduce, train_step,
                  eval_batch, metric_fns, on_train_epoch=None,
                  on_eval=None) -> list:
    """Shared epoch driver for all frontends.

    Framework-specific pieces come in as hooks: `allreduce(np_arr, op)`,
    `train_step(batch) -> loss float`, `eval_batch(batch) -> (loss,
    {metric: value})`. Collective counts per epoch are identical on every
    rank by construction: `steps` train collectives + 1 loss mean +
    (if val) 1 val mean + one per metric.
    """
    steps = _agree_steps(allreduce, train, spec["batch_size"],
                         spec["train_steps_per_epoch"])
    val_steps = 0
    if val is not None:
        val_steps = _agree_steps(allreduce, val, spec["val_batch_size"],
                                 spec["val_steps_per_epoch"],
                                 allow_zero=True)
        if val_steps == 0 and rank == 0:
            import sys
            print("[estimator] WARNING: validation was requested but at "
                  "least one rank's validation shard is empty — "
                  "val_loss/val metrics are DISABLED for this run "
                  "(grow the validation split or reduce num_proc)",
                  file=sys.stderr)

    def mean_all(vals) -> float:
        return float(np.asarray(allreduce(
            np.float32(np.mean(vals)), op="average")))

    history = []
    for epoch in range(spec["epochs"]):
        if on_train_epoch:
            on_train_epoch()
        losses = [train_step(b) for b in _epoch_batches(
            spec, train, epoch, spec["batch_size"], steps)]
        row = {"epoch": epoch, "loss": mean_all(losses)}
        if val_steps:
            if on_eval:
                on_eval()
            vlosses, msums = [], {k: [] for k in metric_fns}
            for i, b in enumerate(sutil.batch_iter(
                    val, spec["val_batch_size"], False, 0, 0)):
                if i >= val_steps:
                    break
                vl, mvals = eval_batch(b)
                vlosses.append(vl)
                for k, v in mvals.items():
                    msums[k].append(v)
            row["val_loss"] = mean_all(vlosses)
            for k in metric_fns:
                row[f"val_{k}"] = mean_all(msums[k])
        history.append(row)
        if rank == 0:
            for cb in spec.get("callbacks") or []:
                cb(epoch, dict(row))
            if spec["verbose"]:
                print(f"[estimator] {row}")
    return history


def _save_model(spec, state: dict, history: list) -> None:
    import cloudpickle

    store = spec["store"]
    ckpt_dir = store.get_checkpoint_path(spec["run_id"])
    store.write(posixpath.join(ckpt_dir, _CKPT_FILE),
                cloudpickle.dumps(state))
    store.write_text(posixpath.join(
        store.get_logs_path(spec["run_id"]), "history.json"),
        __import__("json").dumps(history))


def _remote_train_jax(spec):
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.common import types as T
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    from horovod_tpu.optim.functions import broadcast_parameters

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    train, val = _load_shards(spec, rank, size)
    fcols, lcols = spec["feature_cols"], spec["label_cols"]

    t = spec["trainer"]
    model = t["model"]
    if isinstance(model, tuple):
        init_fn, apply_fn = model
    else:  # flax module
        init_fn = lambda rng, xs: model.init(rng, xs)  # noqa: E731
        apply_fn = model.apply
    loss_fn = t["loss"]

    # Init from a zero 2-row probe shaped from the dataset METADATA, not
    # from shard rows: an empty-shard rank cannot infer a vector column's
    # width from its rows, and a width mismatch here would turn the
    # params broadcast below into a cryptic collective shape error
    # (reference: util.py metadata drives input shaping).
    md = spec["metadata"]
    m0 = md.get(fcols[0]) if len(fcols) == 1 else None
    if m0 and len(m0.get("shape") or ()) >= 2:
        sample = np.zeros((2, *m0["shape"]), np.dtype(m0["dtype"]))
    else:
        width = sum(max(1, int(np.prod(md[c]["shape"] or [1])))
                    for c in fcols)
        sample = np.zeros((2, width), np.float32)
    params = init_fn(jax.random.PRNGKey(spec["seed"]), sample)
    params = broadcast_parameters(params, root_rank=0)

    from horovod_tpu.ops.compression import Compression
    comp = spec["compression"] or Compression.none
    dist_opt = DistributedOptimizer(
        t["optimizer"], compression=comp,
        backward_passes_per_step=spec["bpps"],
        op=T.ReduceOp.ADASUM if spec["use_adasum"] else T.ReduceOp.AVERAGE,
        gradient_predivide_factor=spec["predivide"])
    opt_state = dist_opt.init(params)

    has_sw = bool(spec.get("sample_weight_col"))

    def batch_loss(p, xb, yb, wb=None):
        preds = apply_fn(p, xb)
        return loss_fn(preds, yb, wb) if has_sw else loss_fn(preds, yb)

    value_grad = jax.jit(jax.value_and_grad(batch_loss))
    metric_fns = _metric_dict(t.get("metrics"))

    # params/opt_state live in this mutable box so train_step can update
    # them while keeping the hook signature uniform across frontends.
    box = {"params": params, "opt_state": opt_state}

    def train_step(b) -> float:
        xb, yb = _features(b, fcols, md), _labels(b, lcols, md)
        l, g = value_grad(box["params"], xb, yb, _batch_weights(b, spec))
        box["params"], box["opt_state"] = dist_opt.step(
            g, box["params"], box["opt_state"])
        return float(l)

    def eval_batch(b):
        xv, yv = _features(b, fcols, md), _labels(b, lcols, md)
        preds = apply_fn(box["params"], xv)
        wv = _batch_weights(b, spec)
        return float(loss_fn(preds, yv, wv) if has_sw
                     else loss_fn(preds, yv)), {
            k: float(fn(preds, yv)) for k, fn in metric_fns.items()}

    history = _run_training(spec, train, val, rank,
                            allreduce=hvd.allreduce,
                            train_step=train_step, eval_batch=eval_batch,
                            metric_fns=metric_fns)
    if rank == 0:
        _save_model(spec, {"params": jax.device_get(box["params"]),
                           "apply_fn": apply_fn}, history)
    hvd.barrier()
    hvd.shutdown()
    return history


def _wrap_torch_optimizer(spec, hvd, model, opt):
    """Shared torch/lightning plumbing: wrap the base optimizer with the
    frontend's DistributedOptimizer honoring the estimator knobs."""
    comp = spec["compression"] or hvd.Compression.none
    return hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=comp,
        backward_passes_per_step=spec["bpps"],
        op=hvd.Adasum if spec["use_adasum"] else hvd.Average,
        gradient_predivide_factor=spec["predivide"])


def _torch_np_allreduce(hvd):
    import torch

    def np_allreduce(arr, op):
        return hvd.allreduce(torch.from_numpy(np.asarray(arr)),
                             op=op).numpy()
    return np_allreduce


def _remote_train_torch(spec):
    import torch

    import horovod_tpu.frontends.torch as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    train, val = _load_shards(spec, rank, size)
    fcols, lcols = spec["feature_cols"], spec["label_cols"]

    t = spec["trainer"]
    model = t["model"]
    loss_fn = t["loss"]
    metric_fns = _metric_dict(t.get("metrics"))
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = _wrap_torch_optimizer(spec, hvd, model,
                                t["optimizer"](model.parameters()))
    np_allreduce = _torch_np_allreduce(hvd)

    md = spec["metadata"]

    def train_step(b) -> float:
        xb = torch.from_numpy(_features(b, fcols, md))
        yb = torch.from_numpy(np.asarray(_labels(b, lcols, md)))
        wb = _batch_weights(b, spec)
        loss_args = (model(xb), yb) + \
            ((torch.from_numpy(wb),) if wb is not None else ())
        opt.zero_grad()
        loss = loss_fn(*loss_args)
        loss.backward()
        opt.step()
        return float(loss.detach())

    def eval_batch(b):
        with torch.no_grad():
            xv = torch.from_numpy(_features(b, fcols, md))
            yv = torch.from_numpy(np.asarray(_labels(b, lcols, md)))
            wv = _batch_weights(b, spec)
            args = (model(xv), yv) + \
                ((torch.from_numpy(wv),) if wv is not None else ())
            preds = args[0]
            return float(loss_fn(*args)), {
                k: float(fn(preds, yv)) for k, fn in metric_fns.items()}

    history = _run_training(spec, train, val, rank,
                            allreduce=np_allreduce,
                            train_step=train_step, eval_batch=eval_batch,
                            metric_fns=metric_fns,
                            on_train_epoch=model.train,
                            on_eval=model.eval)
    if rank == 0:
        _save_model(spec, model, history)
    hvd.barrier()
    hvd.shutdown()
    return history


# ======================================================================
# Keras (TF) estimator
# ======================================================================

class KerasEstimator(HorovodEstimator):
    """Estimator over a compiled tf.keras model (reference:
    spark/keras/estimator.py KerasEstimator).

    model: a built (not necessarily compiled) tf.keras.Model.
    optimizer: a tf.keras optimizer instance (serialized by config).
    loss: a tf.keras loss instance, name string, or callable.

    The model travels as architecture JSON + weights (keras' own
    serialization — cloudpickling live TF objects is fragile), is rebuilt
    on every worker, and trains with gradients reduced through the TF
    frontend's allreduce — the same collective path as
    DistributedGradientTape.
    """

    _kind = "keras"

    def _make_trainer_payload(self) -> dict:
        model = self.getModel()
        if model is None or self.getOptimizer() is None \
                or self.getLoss() is None:
            raise ValueError("KerasEstimator requires model=, optimizer=, "
                             "loss=")
        import tensorflow as tf

        return dict(model_json=model.to_json(),
                    weights=[np.asarray(w) for w in model.get_weights()],
                    optimizer_cfg=tf.keras.optimizers.serialize(
                        self.getOptimizer()),
                    loss=self.getLoss(), metrics=self.getMetrics())

    def _make_model(self, state, metadata, run_id, history) -> "KerasModel":
        return KerasModel(history=history, model=state,
                          featureCols=self.getFeatureCols(),
                          labelCols=self.getLabelCols(),
                          runId=run_id, metadata=metadata)


class KerasModel(HorovodModel):
    """state = {"model_json": ..., "weights": [...]} — rebuilt lazily per
    process, so the transformer itself stays picklable for mapInPandas."""

    def _keras(self):
        import tensorflow as tf

        if getattr(self, "_built", None) is None:
            st = self.getModel()
            m = tf.keras.models.model_from_json(st["model_json"])
            m.set_weights(st["weights"])
            self._built = m
        return self._built

    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self._keras()(X))

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_built", None)
        return d


def _remote_train_keras(spec):
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as hvd

    hvd.init()
    rank = hvd.rank()
    train, val = _load_shards(spec, rank, hvd.size())
    fcols, lcols = spec["feature_cols"], spec["label_cols"]

    t = spec["trainer"]
    model = tf.keras.models.model_from_json(t["model_json"])
    model.set_weights(t["weights"])  # driver weights == rank-0 broadcast
    opt = tf.keras.optimizers.deserialize(t["optimizer_cfg"])
    loss_obj = t["loss"]
    if isinstance(loss_obj, str):
        loss_obj = tf.keras.losses.get(loss_obj)
    # Loss INSTANCES take sample_weight; plain functions (what a name
    # string resolves to) return per-sample values we weight manually.
    loss_takes_sw = isinstance(loss_obj, tf.keras.losses.Loss)

    def weighted_loss(y, preds, w):
        if w is None:
            return tf.reduce_mean(loss_obj(y, preds))
        wt = tf.constant(w)
        if loss_takes_sw:
            return tf.reduce_mean(loss_obj(y, preds, sample_weight=wt))
        return tf.reduce_mean(wt * loss_obj(y, preds))
    metric_fns = _metric_dict(t.get("metrics"))

    # The frontend's gradient fn handles None grads (variables off the
    # loss path), compression, Adasum, and the predivide split — the
    # same path DistributedGradientTape uses (tensorflow.py:166).
    from horovod_tpu.common import types as T
    comp = spec["compression"] or hvd.Compression.none
    reduce_grads = hvd._make_allreduce_grads_fn(
        T.ReduceOp.ADASUM if spec["use_adasum"] else T.ReduceOp.AVERAGE,
        spec["predivide"], comp, None)
    bpps = max(1, int(spec["bpps"]))
    accum = {"grads": None, "count": 0}

    def np_allreduce(arr, op):
        return np.asarray(hvd.allreduce(
            tf.constant(np.asarray(arr)), op=op))

    md = spec["metadata"]

    def train_step(b) -> float:
        xb = tf.constant(_features(b, fcols, md))
        yb = tf.constant(np.asarray(_labels(b, lcols, md)))
        wb = _batch_weights(b, spec)
        with tf.GradientTape() as tape:
            loss = weighted_loss(yb, model(xb, training=True), wb)
        grads = tape.gradient(loss, model.trainable_variables)
        if bpps > 1:  # local aggregation (reference:
            # gradient_aggregation.py LocalGradientAggregationHelper)
            if accum["grads"] is None:
                accum["grads"] = [None if g is None else tf.identity(g)
                                  for g in grads]
            else:
                accum["grads"] = [
                    a if g is None else (g if a is None else a + g)
                    for a, g in zip(accum["grads"], grads)]
            accum["count"] += 1
            if accum["count"] < bpps:
                return float(loss)
            grads = [None if a is None else a / bpps
                     for a in accum["grads"]]
            accum["grads"], accum["count"] = None, 0
        grads = reduce_grads(grads)
        opt.apply_gradients(
            (g, v) for g, v in zip(grads, model.trainable_variables)
            if g is not None)
        return float(loss)

    def eval_batch(b):
        xv = tf.constant(_features(b, fcols, md))
        yv = tf.constant(np.asarray(_labels(b, lcols, md)))
        preds = model(xv, training=False)
        wv = _batch_weights(b, spec)
        return float(weighted_loss(yv, preds, wv)), {
            k: float(fn(preds, yv)) for k, fn in metric_fns.items()}

    history = _run_training(spec, train, val, rank,
                            allreduce=np_allreduce,
                            train_step=train_step, eval_batch=eval_batch,
                            metric_fns=metric_fns)
    if rank == 0:
        _save_model(spec, {"model_json": model.to_json(),
                           "weights": [np.asarray(w)
                                       for w in model.get_weights()]},
                    history)
    hvd.barrier()
    hvd.shutdown()
    return history


# ======================================================================
# Lightning estimator
# ======================================================================

class LightningEstimator(HorovodEstimator):
    """Estimator over a LightningModule-style model (reference:
    spark/lightning/estimator.py).

    The model is DUCK-TYPED to the LightningModule training protocol —
    `training_step(batch, batch_idx) -> loss`, `configure_optimizers()
    -> torch optimizer` (optionally `validation_step(batch, idx) ->
    loss-like`) — so pytorch_lightning itself is not required: any
    torch.nn.Module implementing those two methods trains. Batches
    arrive as `(features, labels)` tensor tuples per the estimator data
    contract. `loss`/`optimizer` params are therefore unused here; the
    module supplies both.
    """

    _kind = "lightning"

    def _make_trainer_payload(self) -> dict:
        model = self.getModel()
        if model is None:
            raise ValueError("LightningEstimator requires model=")
        if self.getSampleWeightCol():
            raise ValueError(
                "sample_weight_col is not supported by LightningEstimator: "
                "batches reach training_step as (features, labels) tuples "
                "per the Lightning contract; fold weights into the module "
                "or use JaxEstimator/TorchEstimator/KerasEstimator")
        for attr in ("training_step", "configure_optimizers"):
            if not callable(getattr(model, attr, None)):
                raise ValueError(
                    f"model must implement {attr}() (LightningModule "
                    f"training protocol)")
        return dict(model=model, metrics=self.getMetrics())

    def _make_model(self, state, metadata, run_id, history) -> "TorchModel":
        return TorchModel(history=history, model=state,
                          featureCols=self.getFeatureCols(),
                          labelCols=self.getLabelCols(),
                          runId=run_id, metadata=metadata)


def _configured_optimizer(configured):
    """Normalize configure_optimizers() return shapes (reference:
    Lightning accepts an optimizer, [optimizers], ([opts], [scheds]),
    or {"optimizer": ..., "lr_scheduler": ...}). One optimizer is
    supported; multi-optimizer (GAN-style) setups are rejected loudly
    rather than silently training only the first."""
    if isinstance(configured, dict):
        if "optimizer" not in configured:
            raise ValueError("configure_optimizers() dict must contain "
                             "an 'optimizer' key")
        return configured["optimizer"]
    if isinstance(configured, (tuple, list)):
        opts = configured[0] if isinstance(configured[0], (tuple, list)) \
            else list(configured)
        opts = [o for o in opts
                if hasattr(o, "param_groups")] or list(opts)
        if len(opts) != 1:
            raise ValueError(
                f"multi-optimizer configure_optimizers() "
                f"({len(opts)} optimizers) is not supported — parameters "
                f"owned by other optimizers would silently never update")
        return opts[0]
    return configured


def _remote_train_lightning(spec):
    import torch

    import horovod_tpu.frontends.torch as hvd

    hvd.init()
    rank = hvd.rank()
    train, val = _load_shards(spec, rank, hvd.size())
    fcols, lcols = spec["feature_cols"], spec["label_cols"]

    t = spec["trainer"]
    model = t["model"]
    metric_fns = _metric_dict(t.get("metrics"))
    # Metrics need predictions, i.e. a real forward override (nn.Module's
    # inherited forward raises NotImplementedError) — fail up front, not
    # on the first validation batch of every rank.
    fwd_overridden = type(model).forward is not torch.nn.Module.forward
    if metric_fns and not fwd_overridden:
        raise ValueError(
            "metrics require the model to override forward() so "
            "predictions can be computed")
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = _wrap_torch_optimizer(
        spec, hvd, model, _configured_optimizer(
            model.configure_optimizers()))
    np_allreduce = _torch_np_allreduce(hvd)

    # batch_idx is epoch-local per the Lightning contract; the epoch
    # hook resets it.
    step_counter = {"i": 0}

    def on_train_epoch():
        step_counter["i"] = 0
        model.train()

    md = spec["metadata"]

    def to_batch(b):
        return (torch.from_numpy(_features(b, fcols, md)),
                torch.from_numpy(np.asarray(_labels(b, lcols, md))))

    def train_step(b) -> float:
        opt.zero_grad()
        loss = model.training_step(to_batch(b), step_counter["i"])
        if isinstance(loss, dict):  # lightning allows {"loss": ...}
            loss = loss["loss"]
        loss.backward()
        opt.step()
        step_counter["i"] += 1
        return float(loss.detach())

    has_val_step = callable(getattr(model, "validation_step", None))
    val_counter = {"i": 0}

    def on_eval():
        val_counter["i"] = 0
        model.eval()

    def eval_batch(b):
        with torch.no_grad():
            xb, yb = to_batch(b)
            idx = val_counter["i"]
            val_counter["i"] += 1
            if has_val_step:
                out = model.validation_step((xb, yb), idx)
                vl = float(out["loss"] if isinstance(out, dict) else out)
            else:
                vl = float(model.training_step((xb, yb), idx))
            if not metric_fns:  # loss already forwarded the batch once
                return vl, {}
            preds = model(xb)
            return vl, {k: float(fn(preds, yb))
                        for k, fn in metric_fns.items()}

    history = _run_training(spec, train, val, rank,
                            allreduce=np_allreduce,
                            train_step=train_step, eval_batch=eval_batch,
                            metric_fns=metric_fns,
                            on_train_epoch=on_train_epoch,
                            on_eval=on_eval)
    if rank == 0:
        _save_model(spec, model, history)
    hvd.barrier()
    hvd.shutdown()
    return history
