"""Estimator parameter mixin.

Reference: horovod/spark/common/params.py — EstimatorParams/ModelParams
define ~30 pyspark.ml Params with get/set pairs. pyspark.ml's Param
machinery exists to ride Spark's ParamGridBuilder; the estimator here
must work without pyspark installed (the backend is pluggable), so the
same camelCase getter/setter surface is generated over a plain dict.
"""

from __future__ import annotations

from typing import Any, Dict


def _accessor_suffix(name: str) -> str:
    return name[0].upper() + name[1:]


class _ParamBag:
    """get<Name>/set<Name> accessors over a plain dict, preserving the
    pyspark.ml-style API of the reference (params.py get_from_dicts /
    _CamelGetterSetter convention)."""

    _defaults: Dict[str, Any] = {}

    def __init__(self, **kwargs):
        import copy as _copy

        # deepcopy: list defaults (metrics, callbacks) must not alias the
        # class-level dict or one instance's mutation leaks to all.
        self._params: Dict[str, Any] = _copy.deepcopy(self._defaults)
        unknown = set(kwargs) - set(self._defaults)
        if unknown:
            raise ValueError(f"unknown estimator params: {sorted(unknown)}; "
                             f"valid: {sorted(self._defaults)}")
        self._params.update(kwargs)

    def __getattr__(self, attr: str):
        # Only called when normal lookup fails: synthesize accessors.
        if attr.startswith(("get", "set")) and len(attr) > 3:
            name = attr[3].lower() + attr[4:]
            params = object.__getattribute__(self, "_params")
            if name not in params:
                # snake_case params keep pythonic names (num_proc) while
                # accessors stay camel (getNumProc), like the reference.
                import re

                snake = re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()
                if snake in params:
                    name = snake
            if name in params:
                if attr.startswith("get"):
                    return lambda: params[name]

                def setter(value, _name=name):
                    params[_name] = value
                    return self
                return setter
        raise AttributeError(attr)

    def param_dict(self) -> Dict[str, Any]:
        return dict(self._params)

    def copy(self, overrides: Dict[str, Any] = None) -> "_ParamBag":
        import copy as _copy

        new = _copy.copy(self)  # keeps subclass state (e.g. history)
        new._params = dict(self._params)
        if overrides:
            unknown = set(overrides) - set(new._params)
            if unknown:
                raise ValueError(
                    f"unknown params in override: {sorted(unknown)}; "
                    f"valid: {sorted(new._params)}")
            new._params.update(overrides)
        return new


class EstimatorParams(_ParamBag):
    """Reference: params.py EstimatorParams — the training-side knobs."""

    _defaults: Dict[str, Any] = {
        "num_proc": None,
        "backend": None,
        "store": None,
        "model": None,
        "optimizer": None,
        "loss": None,
        "metrics": [],
        "featureCols": None,
        "labelCols": None,
        "sampleWeightCol": None,
        "validation": None,          # float fraction or bool column name
        "batchSize": 32,
        "valBatchSize": None,
        "epochs": 1,
        "trainStepsPerEpoch": None,
        "validationStepsPerEpoch": None,
        "shufflingSeed": None,
        "shuffle": True,
        "callbacks": [],
        "runId": None,
        "verbose": 1,
        "randomSeed": 0,
        "compression": None,
        "gradientPredivideFactor": 1.0,
        "backwardPassesPerStep": 1,
        "useAdasum": False,
    }


class ModelParams(_ParamBag):
    """Reference: params.py ModelParams — the inference-side knobs."""

    _defaults: Dict[str, Any] = {
        "model": None,
        "featureCols": None,
        "labelCols": None,
        "outputCols": None,
        "runId": None,
        "metadata": None,
        "batchSize": 1024,
    }
