"""Spark orchestration.

Reference: horovod/spark/__init__.py + spark/runner.py (448 LoC) —
`horovod.spark.run(fn, ...)` spawns a Spark job whose tasks each run one
worker (`_task_fn`, runner.py:49), with the driver doing rendezvous.

The Estimator stack (reference: spark/common/estimator.py, store.py,
util.py, params.py + spark/{keras,torch,lightning}/) lives in the sibling
modules: `store` (fsspec-backed Store), `params`, `util` (DataFrame →
parquet + shard readers), `backend` (pluggable Spark/Local execution),
`estimator` (JaxEstimator / TorchEstimator / models). See estimator.py's
docstring for the TPU-first redesign notes.

This module is import-gated: it only needs pyspark when actually used.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark (reference extra: "
            "horovod[spark])") from e


def run(fn: Callable[[], Any], args=(), kwargs=None, num_proc: Optional[int] = None,
        extra_env: Optional[dict] = None, verbose: int = 1) -> List[Any]:
    """Run `fn` once per Spark task slot (reference: spark/runner.py:200).

    Each Spark task becomes one framework worker: the driver starts the
    rendezvous, tasks rendezvous back, run fn, and return per-rank results
    through Spark's collect.
    """
    pyspark = _require_pyspark()
    import cloudpickle

    from horovod_tpu.runner.launch import _local_ip
    from horovod_tpu.runner.rendezvous import RendezvousServer

    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a SparkSession "
                           "first (reference: spark/runner.py checks the "
                           "same)")
    np_ = num_proc or int(sc.defaultParallelism)
    payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))

    from horovod_tpu.runner import secret as secret_mod
    job_secret = secret_mod.make_secret_key()
    rdv = RendezvousServer(secret=job_secret.encode())
    port = rdv.start()
    addr = _local_ip()
    env = dict(extra_env or {})
    env[secret_mod.SECRET_ENV] = job_secret

    def task_fn(index, _it):
        # Reference: _task_fn (spark/runner.py:49) — set worker identity env
        # then run the user function. Exceptions travel back as data so the
        # driver can name the failing rank(s) with their remote tracebacks
        # instead of surfacing an opaque Spark task failure.
        import os as _os
        import cloudpickle as _cp

        from horovod_tpu.runner.results import capture
        _os.environ.update(env)
        _os.environ["HOROVOD_RANK"] = str(index)
        _os.environ["HOROVOD_SIZE"] = str(np_)
        _os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = addr
        _os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)
        f, a, kw = _cp.loads(payload)
        ok, result = capture(f, *a, **kw)
        yield (index, ok, result)

    from horovod_tpu.runner.results import PerRankResults
    collected = PerRankResults(np_)
    try:
        for index, ok, result in (sc.parallelize(range(np_), np_)
                                  .mapPartitionsWithIndex(task_fn)
                                  .collect()):
            collected.add(index, ok, result)
    finally:
        rdv.stop()
    return collected.values()


# Estimator stack re-exports (reference: horovod.spark.keras.KerasEstimator
# etc. are imported from the subpackages; here one namespace).
from horovod_tpu.spark.backend import Backend, LocalBackend, SparkBackend  # noqa: E402,F401
from horovod_tpu.spark.estimator import (  # noqa: E402,F401
    HorovodEstimator, HorovodModel, JaxEstimator, JaxModel,
    KerasEstimator, KerasModel, LightningEstimator, TorchEstimator,
    TorchModel)
from horovod_tpu.spark.store import (  # noqa: E402,F401
    FilesystemStore, HDFSStore, LocalStore, Store)
from horovod_tpu.spark.elastic import run_elastic  # noqa: E402,F401
