"""Pluggable execution backends for the estimator.

Reference: horovod/spark/common/backend.py — Backend/SparkBackend run the
remote training function on the cluster. The TPU-first change: Spark is
just one placement provider, so a `LocalBackend` (our own multi-process
launcher over loopback/pods) trains the same estimator with no Spark
installed — which is also how the estimator stack is tested end-to-end.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class Backend:
    """Reference: backend.py Backend interface (run / num_processes)."""

    def run(self, fn: Callable[..., Any], args=(),
            env: Optional[dict] = None) -> List[Any]:
        raise NotImplementedError()

    def num_processes(self) -> int:
        raise NotImplementedError()


class LocalBackend(Backend):
    """Train with horovod_tpu's own launcher: one subprocess per rank on
    this host (JAX CPU or the attached TPU chips). No Spark required."""

    def __init__(self, num_proc: int = 1,
                 extra_env: Optional[dict] = None,
                 use_cpu: bool = True):
        self._np = num_proc
        self._env = dict(extra_env or {})
        if use_cpu:
            # Workers share one host; pin each to its own CPU device
            # rather than fighting over a single attached accelerator.
            # HOROVOD_WORKER_PLATFORM makes task_runner switch through
            # jax.config BEFORE backend init (env vars alone don't win
            # against a sitecustomize-pinned platform) and scrub a parent
            # pytest's virtual-device XLA flags.
            self._env.setdefault("HOROVOD_WORKER_PLATFORM", "cpu")
            self._env.setdefault("JAX_PLATFORMS", "cpu")

    def num_processes(self) -> int:
        return self._np

    def run(self, fn, args=(), env=None) -> List[Any]:
        from horovod_tpu import runner

        merged = dict(self._env)
        merged.update(env or {})
        return runner.run(lambda: fn(*args), np=self._np,
                          extra_env=merged)


class SparkBackend(Backend):
    """Run the trainer inside Spark tasks (reference: backend.py
    SparkBackend → spark/runner.py run)."""

    def __init__(self, num_proc: Optional[int] = None, verbose: int = 1,
                 extra_env: Optional[dict] = None):
        self._np = num_proc
        self._verbose = verbose
        self._env = dict(extra_env or {})

    def num_processes(self) -> int:
        if self._np is not None:
            return self._np
        import pyspark

        sc = pyspark.SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError("no active SparkContext; pass num_proc")
        return sc.defaultParallelism

    def run(self, fn, args=(), env=None) -> List[Any]:
        from horovod_tpu import spark as hvd_spark

        merged = dict(self._env)
        merged.update(env or {})
        return hvd_spark.run(fn, args=args, num_proc=self.num_processes(),
                             extra_env=merged, verbose=self._verbose)
