"""ResNet v1.5 (50/101/152) — the reference's headline benchmark model
(docs/benchmarks.rst:40-42 reports ResNet-101 images/sec under
tf_cnn_benchmarks; examples/pytorch/pytorch_synthetic_benchmark.py defaults
to resnet50).

TPU-first choices:
  * NHWC layout + bf16-friendly convs — XLA tiles NHWC convs onto the MXU.
  * BatchNorm is functional: apply() returns (logits, new_batch_stats);
    cross-replica stat sync is layered on via ops/sync_batch_norm.
  * No Python control flow on data — the whole net is one traced graph.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

STAGE_BLOCKS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * \
        (2.0 / fan_in) ** 0.5


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_stats(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _stem_conv_s2d(x, w):
    """The 7x7-stride-2 stem conv as a space-to-depth 4x4-stride-1 conv.

    C_in=3 cannot tile onto the MXU's 128-lane contraction — measured on a
    v5e, the plain 7x7s2 stem runs at <1% peak and dominates the whole
    forward pass. Folding 2x2 pixel blocks into channels (H,W,3) ->
    (H/2,W/2,12) turns it into a stride-1 conv with a 4*4*12=192-deep
    contraction that XLA tiles well. Bit-identical math: out[p,q] of the
    original reads pixels u=2p+kh-2, kh<=6; with u=2(p+a-1)+di this is
    kernel tap (a, di), kh=2a+di, zero for kh=7 (standard MLPerf-on-TPU
    space-to-depth trick).
    """
    n, h, wdt, c = x.shape
    o = w.shape[-1]
    xb = x.reshape(n, h // 2, 2, wdt // 2, 2, c)
    xb = xb.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, wdt // 2, 4 * c)
    w8 = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    w4 = w8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    w4 = w4.reshape(4, 4, 4 * c, o)
    return lax.conv_general_dilated(
        xb, w4, window_strides=(1, 1), padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def batch_norm(x, p, stats, train: bool, momentum=0.9, eps=1e-5,
               axis_name=None):
    """Functional BN. With `axis_name`, batch stats are psum-synced across
    that mesh axis (the role of hvd.SyncBatchNormalization,
    reference: tensorflow/sync_batch_norm.py, torch/sync_batch_norm.py)."""
    if train:
        # f32 accumulation without binding an f32 activation copy to a
        # Python name: the convert+square feed straight into the reduce,
        # which XLA fuses into one pass (squaring in bf16 instead would
        # admit var = E[x^2]-E[x]^2 cancellation error ~1e-3*meansq —
        # negative variance -> rsqrt NaN when mean^2 >> var).
        mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
        meansq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            meansq = lax.pmean(meansq, axis_name)
        var = meansq - jnp.square(mean)
        new_stats = {"mean": stats["mean"] * momentum + mean * (1 - momentum),
                     "var": stats["var"] * momentum + var * (1 - momentum)}
    else:
        mean, var = stats["mean"], stats["var"]
        new_stats = stats
    inv = lax.rsqrt(var + eps).astype(x.dtype)
    out = (x - mean.astype(x.dtype)) * inv * p["scale"] + p["bias"]
    return out, new_stats


def init(key: jax.Array, depth: int = 50, num_classes: int = 1000,
         dtype=jnp.float32) -> Tuple[Dict, Dict]:
    """Returns (params, batch_stats)."""
    blocks = STAGE_BLOCKS[depth]
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    key, k0 = jax.random.split(key)
    params["stem"] = {"conv": _conv_init(k0, 7, 7, 3, 64, dtype),
                      "bn": _bn_init(64, dtype)}
    stats["stem"] = _bn_stats(64)
    cin = 64
    for s, n in enumerate(blocks):
        width = 64 * (2 ** s)
        cout = width * 4
        for b in range(n):
            name = f"s{s}b{b}"
            stride = 2 if (b == 0 and s > 0) else 1
            key, k1, k2, k3, k4 = jax.random.split(key, 5)
            blk = {
                "conv1": _conv_init(k1, 1, 1, cin, width, dtype),
                "bn1": _bn_init(width, dtype),
                "conv2": _conv_init(k2, 3, 3, width, width, dtype),
                "bn2": _bn_init(width, dtype),
                "conv3": _conv_init(k3, 1, 1, width, cout, dtype),
                "bn3": _bn_init(cout, dtype),
            }
            st = {"bn1": _bn_stats(width), "bn2": _bn_stats(width),
                  "bn3": _bn_stats(cout)}
            if b == 0:
                blk["proj"] = _conv_init(k4, 1, 1, cin, cout, dtype)
                blk["bnp"] = _bn_init(cout, dtype)
                st["bnp"] = _bn_stats(cout)
            params[name] = blk
            stats[name] = st
            cin = cout
    key, kf = jax.random.split(key)
    params["fc"] = {"w": jax.random.normal(kf, (cin, num_classes), dtype) *
                    cin ** -0.5,
                    "b": jnp.zeros((num_classes,), dtype)}
    return params, stats


# Indirection for the stem maxpool so profiling scripts can substitute the
# pooling op (avg/skip A/Bs) without monkeypatching the shared jax.lax
# module process-wide (scripts/profile_resnet.py).
_reduce_window = lax.reduce_window


def _fuse_conv_bn() -> bool:
    """Fused 1x1-conv+BN backward (ops/conv_bn_backward.py): the dy
    tensor between BN backward and the conv backward never touches HBM.
    Wins 1.5-1.9x at the dominant conv3 sites (parity at conv1) but
    LOSES end-to-end (80.9 vs
    45.2 ms/step measured r05): the custom_vjp boundary de-fuses relu/
    mask/stat-reduce passes XLA otherwise folds into neighbors, and
    forces {3,0,2,1}<->{3,2,1,0} layout copies against the 3x3 convs'
    preferred layouts — docs/benchmarks.md has the full trace autopsy.
    Default OFF everywhere; HOROVOD_FUSE_CONV_BN=1 opts in (kernel A/B:
    scripts/bn_conv_bwd_ab.py)."""
    import os
    return os.environ.get("HOROVOD_FUSE_CONV_BN") in ("1", "true", "True")


def _fused_site_profitable(w) -> bool:
    """Where the fused backward wins on v5e (scripts/bn_conv_bwd_ab.py,
    docs/benchmarks.md): the high-resolution conv3/conv1 sites. At
    cin/cout >= 2048 (stage 4) the resident f32 dW accumulator squeezes
    the kernel's row blocks and XLA wins — keep those unfused."""
    cin, cout = w.shape[-2], w.shape[-1]
    return cin <= 1024 and cout <= 1024


def _fused_conv_bn_site(x, w, p, stats, axis_name, momentum=0.9, eps=1e-5):
    """conv1x1 + train-mode BN through the fused-backward op, emitting
    the same (out, new_stats) contract as _conv + batch_norm."""
    from horovod_tpu.ops.conv_bn_backward import conv1x1_bn_nhwc

    z, (mean, var) = conv1x1_bn_nhwc(x, w, p["scale"], p["bias"], eps,
                                     axis_name)
    new_stats = {"mean": stats["mean"] * momentum + mean * (1 - momentum),
                 "var": stats["var"] * momentum + var * (1 - momentum)}
    return z, new_stats


def _conv_block() -> bool:
    """Fully fused conv+BN+ReLU block family (ops/conv_block.py,
    docs/perf.md "conv fast path"): fused forward (stats ride the
    matmul pass) AND fused masked backward. HOROVOD_CONV_BLOCK=1 opts
    in; supersedes the backward-only HOROVOD_FUSE_CONV_BN."""
    from horovod_tpu.ops.conv_block import conv_block_enabled
    return conv_block_enabled()


def _fused_conv_block_site(x, w, p, stats, axis_name, relu,
                           momentum=0.9, eps=1e-5):
    """conv1x1 + train-mode BN (+ ReLU) through the fused block op,
    emitting the same (out, new_stats) contract as
    _conv + batch_norm (+ jax.nn.relu)."""
    from horovod_tpu.ops.conv_block import conv1x1_bn_act_nhwc

    z, (mean, var) = conv1x1_bn_act_nhwc(x, w, p["scale"], p["bias"],
                                         eps, axis_name, relu)
    new_stats = {"mean": stats["mean"] * momentum + mean * (1 - momentum),
                 "var": stats["var"] * momentum + var * (1 - momentum)}
    return z, new_stats


def apply(params, stats, x: jax.Array, depth: int = 50, train: bool = True,
          axis_name=None) -> Tuple[jax.Array, Dict]:
    """x: (N, H, W, 3) NHWC. Returns (logits, new_batch_stats)."""
    bn = functools.partial(batch_norm, train=train, axis_name=axis_name)
    # Train-mode 1x1-conv+BN(+ReLU) triplets ride the fully fused block
    # op (HOROVOD_CONV_BLOCK) or the fused-backward-only op
    # (HOROVOD_FUSE_CONV_BN); eval mode and 3x3 sites keep the unfused
    # path.
    block = train and _conv_block()
    fuse = block or (train and _fuse_conv_bn())
    if block:
        cbn = functools.partial(_fused_conv_block_site,
                                axis_name=axis_name, relu=False)
        cbnr = functools.partial(_fused_conv_block_site,
                                 axis_name=axis_name, relu=True)
    else:
        cbn = functools.partial(_fused_conv_bn_site, axis_name=axis_name)
        cbnr = None
    new_stats: Dict[str, Any] = {}
    if x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        h = _stem_conv_s2d(x, params["stem"]["conv"])
    else:
        h = _conv(x, params["stem"]["conv"], stride=2)
    h, new_stats["stem"] = bn(h, params["stem"]["bn"], stats["stem"])
    h = jax.nn.relu(h)
    h = _reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                       "SAME")
    blocks = STAGE_BLOCKS[depth]
    for s, n in enumerate(blocks):
        for b in range(n):
            name = f"s{s}b{b}"
            blk, st = params[name], stats[name]
            stride = 2 if (b == 0 and s > 0) else 1
            ns = {}
            if block and _fused_site_profitable(blk["conv1"]):
                # conv1's ReLU folds into the block op — no separate pass
                y, ns["bn1"] = cbnr(h, blk["conv1"], blk["bn1"],
                                    st["bn1"])
            elif fuse and _fused_site_profitable(blk["conv1"]):
                y, ns["bn1"] = cbn(h, blk["conv1"], blk["bn1"], st["bn1"])
                y = jax.nn.relu(y)
            else:
                y = _conv(h, blk["conv1"])
                y, ns["bn1"] = bn(y, blk["bn1"], st["bn1"])
                y = jax.nn.relu(y)
            y = _conv(y, blk["conv2"], stride=stride)
            y, ns["bn2"] = bn(y, blk["bn2"], st["bn2"])
            y = jax.nn.relu(y)
            if fuse and _fused_site_profitable(blk["conv3"]):
                y, ns["bn3"] = cbn(y, blk["conv3"], blk["bn3"], st["bn3"])
            else:
                y = _conv(y, blk["conv3"])
                y, ns["bn3"] = bn(y, blk["bn3"], st["bn3"])
            if "proj" in blk:
                if fuse and stride == 1 and \
                        _fused_site_profitable(blk["proj"]):
                    sc, ns["bnp"] = cbn(h, blk["proj"], blk["bnp"],
                                        st["bnp"])
                else:
                    sc = _conv(h, blk["proj"], stride=stride)
                    sc, ns["bnp"] = bn(sc, blk["bnp"], st["bnp"])
            else:
                sc = h
            h = jax.nn.relu(y + sc)
            new_stats[name] = ns
    h = jnp.mean(h, axis=(1, 2))
    logits = h @ params["fc"]["w"] + params["fc"]["b"]
    return logits, new_stats


def conv_stack(depth: int = 50):
    """One-time declaration of the conv stack for the layout pass
    (ops/layout.py): every channel-carrying dim of every param/stat
    array, tagged with the named channel EDGE it rides. Edges that two
    arrays share (a conv's output channels and its BN vectors; the
    residual trunk an entire stage adds over) MUST pad together for the
    padded model to stay exact — declaring the stack once here is what
    lets the pass guarantee that.

    Edge map: "img" is the 3-channel input (never padded — the growth
    cap rejects 3→128), "stem" the stem output / stage-0 trunk input,
    "s{s}" stage s's residual trunk (width*4), "s{s}b{b}.c1"/".c2" the
    block-internal widths.
    """
    from horovod_tpu.ops.layout import Site

    blocks = STAGE_BLOCKS[depth]
    sites = [Site("stem/conv", {2: "img", 3: "stem"}),
             Site("stem/bn/scale", {0: "stem"}),
             Site("stem/bn/bias", {0: "stem"}),
             Site("stem/mean", {0: "stem"}),
             Site("stem/var", {0: "stem"})]
    in_edge = "stem"
    for s, n in enumerate(blocks):
        out_edge = f"s{s}"
        for b in range(n):
            name = f"s{s}b{b}"
            c1, c2 = f"{name}.c1", f"{name}.c2"
            sites += [Site(f"{name}/conv1", {2: in_edge, 3: c1}),
                      Site(f"{name}/conv2", {2: c1, 3: c2}),
                      Site(f"{name}/conv3", {2: c2, 3: out_edge})]
            for bn, edge in (("bn1", c1), ("bn2", c2), ("bn3", out_edge)):
                sites += [Site(f"{name}/{bn}/scale", {0: edge}),
                          Site(f"{name}/{bn}/bias", {0: edge}),
                          Site(f"{name}/{bn}/mean", {0: edge}),
                          Site(f"{name}/{bn}/var", {0: edge})]
            if b == 0:
                sites += [Site(f"{name}/proj", {2: in_edge, 3: out_edge}),
                          Site(f"{name}/bnp/scale", {0: out_edge}),
                          Site(f"{name}/bnp/bias", {0: out_edge}),
                          Site(f"{name}/bnp/mean", {0: out_edge}),
                          Site(f"{name}/bnp/var", {0: out_edge})]
            in_edge = out_edge
    sites.append(Site("fc/w", {0: in_edge}))
    return sites


def loss_fn(params, stats, batch, depth: int = 50, train: bool = True,
            axis_name=None):
    """Cross-entropy; returns (loss, new_stats)."""
    x, y = batch
    logits, new_stats = apply(params, stats, x, depth=depth, train=train,
                              axis_name=axis_name)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
    return loss, new_stats
