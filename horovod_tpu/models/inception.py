"""Inception V3 — the reference's top headline scaling model
(reference: README.rst:102-108 — 90% scaling efficiency for Inception V3
on 512 GPUs is THE Horovod result; docs/benchmarks.rst tf_cnn_benchmarks
recipe).

TPU-first choices mirror models/resnet.py: NHWC + bf16 convs, functional
BN returning (out, new_stats), no Python control flow on data. The
asymmetric 1x7/7x1 factorized convs tile the MXU fine in NHWC. The
training-only auxiliary classifier head is omitted (synthetic-benchmark
scope; torchvision's aux_logits=False equivalent).

Channel plan follows the canonical V3 (torchvision inception_v3 /
Szegedy et al. 2015): 299x299 input, stem to 35x35x192, 3x InceptionA,
ReductionA, 4x InceptionB, ReductionB, 2x InceptionC, global avg pool,
fc 2048->classes.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.resnet import _conv_init, batch_norm


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_stats(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


# Each conv is (kh, kw, cout, stride, padding) — padding "SAME"/"VALID".
# A block is {branch_name: [conv, conv, ...]}; branches concatenate on C.
# "pool" / "avgpool" pseudo-convs insert a 3x3 max/avg pool first.

def _stem_plan():
    return [("c0", 3, 3, 32, 2, "VALID"), ("c1", 3, 3, 32, 1, "VALID"),
            ("c2", 3, 3, 64, 1, "SAME"), ("maxpool", 0, 0, 0, 2, ""),
            ("c3", 1, 1, 80, 1, "VALID"), ("c4", 3, 3, 192, 1, "VALID"),
            ("maxpool", 0, 0, 0, 2, "")]


def _inception_a(pool_feat):
    return {
        "b1x1": [(1, 1, 64, 1, "SAME")],
        "b5x5": [(1, 1, 48, 1, "SAME"), (5, 5, 64, 1, "SAME")],
        "b3x3dbl": [(1, 1, 64, 1, "SAME"), (3, 3, 96, 1, "SAME"),
                    (3, 3, 96, 1, "SAME")],
        "bpool": ["avgpool", (1, 1, pool_feat, 1, "SAME")],
    }


def _reduction_a():
    return {
        "b3x3": [(3, 3, 384, 2, "VALID")],
        "b3x3dbl": [(1, 1, 64, 1, "SAME"), (3, 3, 96, 1, "SAME"),
                    (3, 3, 96, 2, "VALID")],
        "bpool": ["maxpool"],
    }


def _inception_b(c7):
    return {
        "b1x1": [(1, 1, 192, 1, "SAME")],
        "b7x7": [(1, 1, c7, 1, "SAME"), (1, 7, c7, 1, "SAME"),
                 (7, 1, 192, 1, "SAME")],
        "b7x7dbl": [(1, 1, c7, 1, "SAME"), (7, 1, c7, 1, "SAME"),
                    (1, 7, c7, 1, "SAME"), (7, 1, c7, 1, "SAME"),
                    (1, 7, 192, 1, "SAME")],
        "bpool": ["avgpool", (1, 1, 192, 1, "SAME")],
    }


def _reduction_b():
    return {
        "b3x3": [(1, 1, 192, 1, "SAME"), (3, 3, 320, 2, "VALID")],
        "b7x7x3": [(1, 1, 192, 1, "SAME"), (1, 7, 192, 1, "SAME"),
                   (7, 1, 192, 1, "SAME"), (3, 3, 192, 2, "VALID")],
        "bpool": ["maxpool"],
    }


def _inception_c():
    # b3x3 and b3x3dbl each END in a pair of parallel (1,3)/(3,1) convs
    # whose outputs concatenate — encoded as a "split" tail.
    return {
        "b1x1": [(1, 1, 320, 1, "SAME")],
        "b3x3": [(1, 1, 384, 1, "SAME"),
                 ("split", (1, 3, 384, 1, "SAME"), (3, 1, 384, 1, "SAME"))],
        "b3x3dbl": [(1, 1, 448, 1, "SAME"), (3, 3, 384, 1, "SAME"),
                    ("split", (1, 3, 384, 1, "SAME"),
                     (3, 1, 384, 1, "SAME"))],
        "bpool": ["avgpool", (1, 1, 192, 1, "SAME")],
    }


_BLOCKS = (
    [("a0", _inception_a(32)), ("a1", _inception_a(64)),
     ("a2", _inception_a(64)), ("ra", _reduction_a()),
     ("b0", _inception_b(128)), ("b1", _inception_b(160)),
     ("b2", _inception_b(160)), ("b3", _inception_b(192)),
     ("rb", _reduction_b()), ("c0", _inception_c()),
     ("c1", _inception_c())])


def init(key: jax.Array, num_classes: int = 1000,
         dtype=jnp.float32) -> Tuple[Dict, Dict]:
    """Returns (params, batch_stats)."""
    params: Dict = {"stem": {}}
    stats: Dict = {"stem": {}}
    cin = 3
    for name, kh, kw, cout, _s, _p in _stem_plan():
        if name == "maxpool":
            continue
        key, k1 = jax.random.split(key)
        params["stem"][name] = {"w": _conv_init(k1, kh, kw, cin, cout,
                                                dtype),
                                "bn": _bn_init(cout, dtype)}
        stats["stem"][name] = _bn_stats(cout)
        cin = cout
    for bname, spec in _BLOCKS:
        bp: Dict = {}
        bs: Dict = {}
        c_out_total = 0
        for br, plan in spec.items():
            c = cin
            convs = []
            cstats = []
            for step in plan:
                if step in ("avgpool", "maxpool"):
                    continue
                if isinstance(step, tuple) and step[0] == "split":
                    # every arm reads the SAME pre-split channel count;
                    # the concat of arm outputs is the branch output
                    pre_c = c
                    c = 0
                    for kh, kw, cout, _s, _p in step[1:]:
                        key, k1 = jax.random.split(key)
                        convs.append({"w": _conv_init(k1, kh, kw, pre_c,
                                                      cout, dtype),
                                      "bn": _bn_init(cout, dtype)})
                        cstats.append(_bn_stats(cout))
                        c += cout
                    continue
                kh, kw, cout, _s, _p = step
                key, k1 = jax.random.split(key)
                convs.append({"w": _conv_init(k1, kh, kw, c, cout, dtype),
                              "bn": _bn_init(cout, dtype)})
                cstats.append(_bn_stats(cout))
                c = cout
            bp[br] = convs
            bs[br] = cstats
            c_out_total += c
        params[bname] = bp
        stats[bname] = bs
        cin = c_out_total
    key, kf = jax.random.split(key)
    params["fc"] = {"w": jax.random.normal(kf, (cin, num_classes), dtype) *
                    cin ** -0.5,
                    "b": jnp.zeros((num_classes,), dtype)}
    return params, stats


def _conv(x, w, stride, padding):
    return lax.conv_general_dilated(
        x, w, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _pool(x, kind, stride=1, padding="SAME"):
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                                 (1, stride, stride, 1), padding)
    # literal 0. init so JAX recognizes the differentiable
    # reduce-window-sum monoid (a non-literal init has no transpose rule)
    s = lax.reduce_window(x, 0.0, lax.add, (1, 3, 3, 1),
                          (1, stride, stride, 1), padding)
    if padding == "SAME":
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, (1, 3, 3, 1),
                                (1, stride, stride, 1), padding)
        return (s / cnt).astype(x.dtype)
    return (s / 9.0).astype(x.dtype)


def apply(params, stats, x: jax.Array, train: bool = True,
          axis_name=None) -> Tuple[jax.Array, Dict]:
    """x: (N, 299, 299, 3) NHWC. Returns (logits, new_batch_stats)."""
    bn = functools.partial(batch_norm, train=train, axis_name=axis_name)
    new_stats: Dict = {"stem": {}}
    h = x
    for name, _kh, _kw, _cout, s, p in _stem_plan():
        if name == "maxpool":
            h = _pool(h, "max", stride=2, padding="VALID")
            continue
        blk = params["stem"][name]
        h = _conv(h, blk["w"], s, p)
        h, new_stats["stem"][name] = bn(h, blk["bn"],
                                        stats["stem"][name])
        h = jax.nn.relu(h)
    for bname, spec in _BLOCKS:
        outs = []
        ns: Dict = {}
        for br, plan in spec.items():
            y = h
            ci = 0
            nst = []
            for step in plan:
                if step == "avgpool":
                    y = _pool(y, "avg")
                    continue
                if step == "maxpool":
                    y = _pool(y, "max", stride=2, padding="VALID")
                    continue
                if step[0] == "split":
                    arms_out = []
                    for arm in step[1:]:
                        kh, kw, cout, s, p = arm
                        blk = params[bname][br][ci]
                        a = _conv(y, blk["w"], s, p)
                        a, st = bn(a, blk["bn"], stats[bname][br][ci])
                        nst.append(st)
                        arms_out.append(jax.nn.relu(a))
                        ci += 1
                    y = jnp.concatenate(arms_out, axis=-1)
                    continue
                kh, kw, cout, s, p = step
                blk = params[bname][br][ci]
                y = _conv(y, blk["w"], s, p)
                y, st = bn(y, blk["bn"], stats[bname][br][ci])
                nst.append(st)
                y = jax.nn.relu(y)
                ci += 1
            ns[br] = nst
            outs.append(y)
        h = jnp.concatenate(outs, axis=-1)
        new_stats[bname] = ns
    h = jnp.mean(h, axis=(1, 2))
    logits = h @ params["fc"]["w"] + params["fc"]["b"]
    return logits, new_stats


def loss_fn(params, stats, batch, train: bool = True, axis_name=None):
    x, y = batch
    logits, new_stats = apply(params, stats, x, train=train,
                              axis_name=axis_name)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
    return loss, new_stats
