"""MNIST-scale MLP — the reference's smallest end-to-end config
(examples/pytorch/pytorch_mnist.py uses a small convnet; the MLP plays the
same role as the minimal DistributedOptimizer smoke model)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def init(key: jax.Array, sizes: Sequence[int] = (784, 512, 256, 10),
         dtype=jnp.float32):
    params = []
    for i in range(len(sizes) - 1):
        key, wk = jax.random.split(key)
        w = jax.random.normal(wk, (sizes[i], sizes[i + 1]), dtype) * \
            (2.0 / sizes[i]) ** 0.5
        b = jnp.zeros((sizes[i + 1],), dtype)
        params.append({"w": w, "b": b})
    return params


def apply(params, x: jax.Array) -> jax.Array:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def loss_fn(params, batch: Tuple[jax.Array, jax.Array]) -> jax.Array:
    x, y = batch
    logits = apply(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
