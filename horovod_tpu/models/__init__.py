"""Model zoo: the benchmark models the reference exercises
(examples/pytorch/pytorch_mnist.py, examples/*/\\*_synthetic_benchmark.py —
MNIST MLP/convnet, ResNet-50) plus the transformer flagship used for
long-context and multi-axis parallelism (absent from the reference; this
framework treats it as first-class, SURVEY.md §5).

Models are plain functional JAX: `init(key, ...) -> params` pytrees and
pure `apply` functions — idiomatic for pjit/shard_map, no framework layer.
"""

from horovod_tpu.models import (  # noqa: F401
    inception, mlp, resnet, tied_lm, transformer, vgg,
)
