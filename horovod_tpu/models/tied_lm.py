"""Tied-embedding LM — the GSPMD hybrid-parallel runtime model.

The shape the hvdshard gate has linted since PR 12 (``--hlo-step
lm_sharded``: a tied 16 MB embedding + residual tanh-FFN blocks),
promoted from an analysis fixture to a real trainable model
(ROADMAP item 3 / ISSUE 14): ``examples/hybrid_lm.py`` trains it,
``bench.py``'s gspmd_hybrid section measures it pure-DP vs tp x dp,
and ``analysis/shard.py`` lowers BOTH its GSPMD twin and the
``DistributedOptimizer``-driven runtime step from this one module, so
the linted program and the trained program can never drift apart.

Two formulations of the same math:

* ``global_loss`` — the dense single-device reference (also what the
  GSPMD ``lm_sharded`` analysis twin jits under ``in_shardings``): the
  partitioner decides the collectives.
* ``local_loss`` — the shard-local (Megatron-LM, Shoeybi et al.,
  arXiv:1909.08053) formulation for ``shard_map``: vocab-parallel
  embedding lookup (mask + local gather + psum over ``tp``),
  column/row-parallel FFN (``wi`` sharded on the F dim, ``wo`` psum'd),
  and the vocab-parallel cross entropy (pmax/psum logsumexp + masked
  target gather) — every ``tp`` member ends with the SAME loss value,
  computed cooperatively, never materializing a full logits tensor per
  device. All axis ops collapse to identities when the axis has size 1,
  so the identical code is the pure-DP step on a ``dp=N`` mesh.

Gradient semantics under per-shard AD (why the optimizer divides by
``tp`` and psums replicated leaves over it) are documented at
``optim.optimizer.grad_axes_from_specs`` — the same calculus
``models/transformer.py`` pins against a single-device oracle.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TiedLMConfig:
    vocab: int = 8192
    d_model: int = 512
    d_ff: int = 2048
    n_layers: int = 2
    dtype: Any = jnp.float32


def canonical_config() -> TiedLMConfig:
    """The shapes the shard-lint gate has pinned since PR 12 (16 MB f32
    embedding — the HVD301/302 canary)."""
    return TiedLMConfig(vocab=8192, d_model=512, d_ff=2048, n_layers=2)


def init(seed: int, cfg: TiedLMConfig) -> Dict[str, jax.Array]:
    """Global (unsharded) parameter pytree, deterministic per seed."""
    rng = np.random.default_rng(seed)
    dt = cfg.dtype
    params: Dict[str, jax.Array] = {"emb": jnp.asarray(
        rng.standard_normal((cfg.vocab, cfg.d_model)) * 0.02, dt)}
    for i in range(cfg.n_layers):
        params[f"wi{i}"] = jnp.asarray(
            rng.standard_normal((cfg.d_model, cfg.d_ff)) * 0.02, dt)
        params[f"wo{i}"] = jnp.asarray(
            rng.standard_normal((cfg.d_ff, cfg.d_model)) * 0.02, dt)
    return params


def param_specs(cfg: TiedLMConfig) -> Dict[str, P]:
    """The canonical hybrid layout: vocab-sharded embedding,
    column-parallel ``wi``, row-parallel ``wo`` — every parameter
    sharded over ``tp``, replicated over ``dp``."""
    specs: Dict[str, P] = {"emb": P("tp", None)}
    for i in range(cfg.n_layers):
        specs[f"wi{i}"] = P(None, "tp")
        specs[f"wo{i}"] = P("tp", None)
    return specs


def replicated_specs(cfg: TiedLMConfig) -> Dict[str, P]:
    """The 'forgot to annotate the params' twin: everything replicated
    (what HVD301/302 exist to catch)."""
    return {k: P() for k in param_specs(cfg)}


def sample_batch(seed: int, cfg: TiedLMConfig, batch: int = 16,
                 seq: int = 64):
    """Deterministic synthetic (tokens, targets) — targets are the
    next-token roll, the lm_overlap/lm_sharded convention."""
    rng = np.random.default_rng(seed)
    tok = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)),
                      jnp.int32)
    return tok, jnp.roll(tok, -1, axis=1)


def global_loss(params: Dict[str, jax.Array], tokens: jax.Array,
                targets: jax.Array, cfg: TiedLMConfig,
                constrain_logits: Optional[Callable] = None) -> jax.Array:
    """Dense reference: mean next-token NLL on one device (or under
    GSPMD jit — `constrain_logits` lets the lm_sharded analysis twin
    pin the batch x model logits layout with a sharding constraint)."""
    h = params["emb"][tokens]
    for i in range(cfg.n_layers):
        h = h + jnp.tanh(h @ params[f"wi{i}"]) @ params[f"wo{i}"]
    logits = h @ params["emb"].T          # tied unembedding
    if constrain_logits is not None:
        logits = constrain_logits(logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], -1))


def local_loss(params: Dict[str, jax.Array], tokens: jax.Array,
               targets: jax.Array, cfg: TiedLMConfig,
               tp_axis: str = "tp") -> jax.Array:
    """Shard-local loss for shard_map: `params` per param_specs shards,
    `tokens`/`targets` the local batch shard. Returns the LOCAL batch
    shard's mean NLL — identical on every `tp_axis` member (computed
    cooperatively through psums), NOT reduced over the batch axes
    (the optimizer's gradient reduction owns that; psum'ing the loss
    before grad would scale cotangents by the axis size —
    models/transformer.py NOTE)."""
    emb = params["emb"]
    v_loc = emb.shape[0]
    lo = lax.axis_index(tp_axis) * v_loc

    def vocab_parallel_rows(ids):
        """Embedding rows for global token ids from the local vocab
        shard: out-of-shard ids contribute zeros, psum assembles."""
        local = ids - lo
        ok = (local >= 0) & (local < v_loc)
        safe = jnp.clip(local, 0, v_loc - 1)
        rows = jnp.where(ok[..., None], emb[safe], 0).astype(cfg.dtype)
        return lax.psum(rows, tp_axis)

    h = vocab_parallel_rows(tokens)
    for i in range(cfg.n_layers):
        u = jnp.tanh(h @ params[f"wi{i}"])          # column-parallel
        h = h + lax.psum(u @ params[f"wo{i}"], tp_axis)  # row-parallel
    logits = h @ emb.T                     # (B_loc, S, V_loc) shard
    lf = logits.astype(jnp.float32)
    # Vocab-parallel log-softmax: global max, then the psum'd exp-sum.
    # The shift is numerical stabilization only — it cancels exactly in
    # lse - tgt_logit's derivative — so it rides stop_gradient (pmax
    # also has no transpose rule).
    # stop_gradient INSIDE pmax: with the tangent symbolically zeroed
    # before the collective, AD never needs pmax's (missing) JVP rule.
    m = lax.pmax(lax.stop_gradient(jnp.max(lf, axis=-1)), tp_axis)
    se = lax.psum(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1), tp_axis)
    lse = m + jnp.log(se)
    tgt_local = targets - lo
    ok = (tgt_local >= 0) & (tgt_local < v_loc)
    safe = jnp.clip(tgt_local, 0, v_loc - 1)
    tgt_logit = lax.psum(
        jnp.where(ok, jnp.take_along_axis(
            lf, safe[..., None], axis=-1)[..., 0], 0.0), tp_axis)
    return jnp.mean(lse - tgt_logit)
