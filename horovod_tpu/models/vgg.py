"""VGG-16/19 — one of the reference's three headline scaling models
(reference: README.rst:108 reports 68% scaling efficiency for VGG-16 on
512 GPUs; docs/benchmarks.rst tf_cnn_benchmarks recipe).

TPU-first choices mirror models/resnet.py: NHWC + bf16 convs for the
MXU, functional apply (no mutable state — VGG has no batch norm in its
classic form), one traced graph end to end. The classifier head's two
4096-wide FC layers are where VGG's parameters live (~90%), which is
exactly why its gradient allreduce is the reference's hardest scaling
case — a useful stress shape for fusion/bucketing work.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Stage plans: (convs per stage, channels); pooling after each stage.
STAGE_PLANS = {
    16: ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)),
    19: ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)),
}


def _conv_init(key, cin, cout, dtype):
    fan_in = 9 * cin
    return jax.random.normal(key, (3, 3, cin, cout), dtype) * \
        (2.0 / fan_in) ** 0.5


def init(key: jax.Array, depth: int = 16, num_classes: int = 1000,
         dtype=jnp.float32, image_size: int = 224) -> Dict:
    plan = STAGE_PLANS[depth]
    params: Dict = {}
    cin = 3
    for s, (n, cout) in enumerate(plan):
        for b in range(n):
            key, k1 = jax.random.split(key)
            params[f"s{s}c{b}"] = {
                "w": _conv_init(k1, cin, cout, dtype),
                "b": jnp.zeros((cout,), dtype),
            }
            cin = cout
    feat = (image_size // 2 ** len(plan)) ** 2 * cin
    dims = (feat, 4096, 4096, num_classes)
    for i in range(3):
        key, k1 = jax.random.split(key)
        params[f"fc{i}"] = {
            "w": jax.random.normal(k1, (dims[i], dims[i + 1]), dtype) *
            dims[i] ** -0.5,
            "b": jnp.zeros((dims[i + 1],), dtype),
        }
    return params


def apply(params: Dict, x: jax.Array, depth: int = 16) -> jax.Array:
    """x: (N, H, W, 3) NHWC -> logits (N, num_classes)."""
    plan = STAGE_PLANS[depth]
    h = x
    for s, (n, _cout) in enumerate(plan):
        for b in range(n):
            p = params[f"s{s}c{b}"]
            h = lax.conv_general_dilated(
                h, p["w"], (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + p["b"]
            h = jax.nn.relu(h)
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 2, 2, 1),
                              (1, 2, 2, 1), "VALID")
    h = h.reshape(h.shape[0], -1)
    for i in range(3):
        p = params[f"fc{i}"]
        h = h @ p["w"] + p["b"]
        if i < 2:
            h = jax.nn.relu(h)
    return h


def loss_fn(params: Dict, batch: Tuple[jax.Array, jax.Array],
            depth: int = 16) -> jax.Array:
    x, y = batch
    logits = apply(params, x, depth=depth)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
