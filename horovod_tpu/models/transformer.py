"""Transformer LM flagship — the multi-axis-parallel model of the framework.

The reference has no model of its own (it wraps torch/TF models) and no
TP/PP/SP/EP (SURVEY.md §2.6). This flagship exercises every mesh axis the
framework supports, in one compiled XLA program per train step:

  dp/ep — batch sharding; gradients psum'd over these axes (the Horovod
          DistributedOptimizer role, reference torch/optimizer.py:36).
  tp    — attention heads + FFN hidden sharded; row-parallel outputs psum'd.
  sp    — sequence sharded; ring attention (parallel/ring_attention.py) or
          Ulysses all_to_all attention (parallel/ulysses.py).
  pp    — layer stack sharded into stages; GPipe microbatch schedule
          (parallel/pipeline.py).
  ep    — MoE FFN experts sharded; all_to_all token dispatch
          (parallel/moe.py). When num_experts == 0 the FFN is dense.

Everything is static-shape, scan-based, bf16-capable — MXU/XLA-friendly.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.common.exceptions import HorovodTpuError
from horovod_tpu.parallel import moe as moe_mod
from horovod_tpu.parallel import pipeline as pp_mod
from horovod_tpu.parallel import ulysses as ulysses_mod
from horovod_tpu.parallel.ring_attention import (
    blockwise_attention_reference, ring_attention)
from horovod_tpu.parallel.mesh import AXIS_ORDER, mesh_axis_sizes


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    n_layers: int = 4
    max_seq: int = 2048
    num_experts: int = 0          # 0 → dense FFN; >0 → MoE every layer
    capacity_factor: float = 2.0
    attn: str = "ring"            # "ring" | "ulysses" | "flash" | "local"
    microbatches: int = 1         # pipeline microbatches (≥ pp size ideal)
    dtype: Any = jnp.float32
    # Rematerialize each layer in backward instead of saving residuals
    # (notably the (B,H,S,S) attention matrices the layer scan would
    # otherwise stack L-deep in HBM) — the standard TPU FLOPs-for-memory
    # trade (jax.checkpoint; HBM is the usual bottleneck).
    remat: bool = False
    # What the checkpoint saves: "dots" keeps non-batch matmul outputs
    # (projections/FFN — small, expensive to recompute) and recomputes
    # batched dots; "full" saves nothing (maximum recompute, minimum HBM).
    # A/B'd on v5e in docs/benchmarks.md — "dots" wins at the flagship
    # config.
    remat_policy: str = "dots"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init(key: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    """Global (unsharded) parameter pytree."""
    D, H, dh, F, L, V = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff,
                         cfg.n_layers, cfg.vocab)
    dt = cfg.dtype
    ks = jax.random.split(key, 12)

    def norm(k, shape, fan_in):
        return jax.random.normal(k, shape, dt) * fan_in ** -0.5

    layers: Dict[str, Any] = {
        "ln1_scale": jnp.ones((L, D), dt), "ln1_bias": jnp.zeros((L, D), dt),
        "wq": norm(ks[0], (L, D, H, dh), D),
        "wk": norm(ks[1], (L, D, H, dh), D),
        "wv": norm(ks[2], (L, D, H, dh), D),
        "wo": norm(ks[3], (L, H, dh, D), H * dh),
        "ln2_scale": jnp.ones((L, D), dt), "ln2_bias": jnp.zeros((L, D), dt),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        layers.update({
            "router": norm(ks[4], (L, D, E), D),
            "we1": norm(ks[5], (L, E, D, F), D),
            "we2": norm(ks[6], (L, E, F, D), F),
        })
    else:
        layers.update({
            "w1": norm(ks[4], (L, D, F), D),
            "b1": jnp.zeros((L, F), dt),
            "w2": norm(ks[5], (L, F, D), F),
            "b2": jnp.zeros((L, D), dt),
        })
    return {
        "embed": norm(ks[7], (V, D), 1.0) * 0.02 * D ** 0.5,
        "pos": norm(ks[8], (cfg.max_seq, D), 1.0) * 0.02,
        "layers": layers,
        "lnf_scale": jnp.ones((D,), dt), "lnf_bias": jnp.zeros((D,), dt),
        "unembed": norm(ks[9], (D, V), D),
    }


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec tree matching init()'s structure (in_specs for
    shard_map; also the NamedSharding layout for device_put)."""
    lp = {
        "ln1_scale": P("pp", None), "ln1_bias": P("pp", None),
        "wq": P("pp", None, "tp", None),
        "wk": P("pp", None, "tp", None),
        "wv": P("pp", None, "tp", None),
        "wo": P("pp", "tp", None, None),
        "ln2_scale": P("pp", None), "ln2_bias": P("pp", None),
    }
    if cfg.num_experts:
        lp.update({
            "router": P("pp", None, None),
            "we1": P("pp", "ep", None, None),
            "we2": P("pp", "ep", None, None),
        })
    else:
        lp.update({
            "w1": P("pp", None, "tp"), "b1": P("pp", "tp"),
            "w2": P("pp", "tp", None), "b2": P("pp", None),
        })
    return {
        "embed": P(), "pos": P(), "layers": lp,
        "lnf_scale": P(), "lnf_bias": P(), "unembed": P(),
    }


def grad_reduce_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Per-leaf mesh axes whose partial gradients must be psum'd — the
    compiled counterpart of Horovod's gradient allreduce, generalised to a
    multi-axis mesh (reference: torch/optimizer.py hooks psum over the one
    world communicator)."""
    # The tp axis computes the loss redundantly on every member, so per-rank
    # reverse AD yields d(Σ_r L_r)/dθ_r = tp·dL/dθ in aggregate. The exact
    # correction (verified leaf-by-leaf against a single-device oracle in
    # tests/test_parallel.py) is: divide EVERY gradient by tp, and
    # additionally pmean replicated-over-tp leaves — i.e. add 'tp' to their
    # psum axes — to mix each rank's local-heads contribution.
    data_axes = ("dp", "ep", "sp", "tp")    # replicated-over-tp layer params
    glob = ("dp", "ep", "sp", "pp", "tp")   # replicated-over-everything
    tp_sharded = ("dp", "ep", "sp")         # tp-sharded weights: no tp psum
    lp = {"ln1_scale": data_axes, "ln1_bias": data_axes,
          "ln2_scale": data_axes, "ln2_bias": data_axes,
          "wq": tp_sharded, "wk": tp_sharded, "wv": tp_sharded,
          "wo": tp_sharded}
    if cfg.num_experts:
        lp.update({"router": data_axes,
                   "we1": ("dp", "sp", "tp"),   # expert-sharded over ep
                   "we2": ("dp", "sp", "tp")})
    else:
        lp.update({"w1": tp_sharded, "b1": tp_sharded, "w2": tp_sharded,
                   "b2": data_axes})
    return {"embed": glob, "pos": glob, "layers": lp,
            "lnf_scale": glob, "lnf_bias": glob, "unembed": glob}


def _ln(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


def _layer(x: jax.Array, lp: Dict[str, Any], cfg: TransformerConfig):
    """One transformer block on per-shard activations x: (B, S_loc, D)."""
    h = _ln(x, lp["ln1_scale"], lp["ln1_bias"])
    q = jnp.einsum("bsd,dhk->bhsk", h, lp["wq"])
    k = jnp.einsum("bsd,dhk->bhsk", h, lp["wk"])
    v = jnp.einsum("bsd,dhk->bhsk", h, lp["wv"])
    if cfg.attn == "ring":
        a = ring_attention(q, k, v, "sp", causal=True)
    elif cfg.attn == "ulysses":
        a = ulysses_mod.ulysses_attention(q, k, v, "sp", causal=True)
    elif cfg.attn == "flash":
        # Pallas flash kernel (ops/flash_attention.py) computes
        # shard-LOCAL attention; silently wrong under a sequence-sharded
        # mesh, so refuse — sharded sequences ride ring/Ulysses.
        if lax.axis_size("sp") > 1:
            raise HorovodTpuError(
                "attn='flash' requires sp=1 (shard-local attention); use "
                "attn='ring' or 'ulysses' for sequence parallelism")
        from horovod_tpu.ops.flash_attention import flash_attention
        a = flash_attention(q, k, v, causal=True)
    else:
        a = blockwise_attention_reference(q, k, v, causal=True)
    o = jnp.einsum("bhsk,hkd->bsd", a, lp["wo"])
    o = lax.psum(o, "tp")                    # row-parallel combine
    x = x + o

    h2 = _ln(x, lp["ln2_scale"], lp["ln2_bias"])
    if cfg.num_experts:
        B, S, D = h2.shape
        flat = h2.reshape(B * S, D)
        out = moe_mod.moe_ffn(flat, lp["router"], lp["we1"], lp["we2"],
                              axis_name="ep",
                              capacity_factor=cfg.capacity_factor)
        f = out.reshape(B, S, D)
    else:
        u = jnp.einsum("bsd,df->bsf", h2, lp["w1"]) + lp["b1"]
        u = jax.nn.gelu(u)
        f = jnp.einsum("bsf,fd->bsd", u, lp["w2"])
        f = lax.psum(f, "tp") + lp["b2"]
    return x + f


def _forward_local(params, tokens, cfg: TransformerConfig) -> jax.Array:
    """Per-shard forward to logits. tokens: (B_loc, S_loc) int32, batch
    sharded over (dp, ep), sequence over sp, run under shard_map. With
    pp > 1 only the last stage's logits are real (zeros elsewhere)."""
    sp_idx = lax.axis_index("sp")
    B, S = tokens.shape
    D = cfg.d_model

    x = params["embed"][tokens]
    pos = lax.dynamic_slice_in_dim(params["pos"], sp_idx * S, S, axis=0)
    x = (x + pos[None]).astype(cfg.dtype)

    def stage_fn(stage_params, act):
        def body(a, lp):
            return _layer(a, lp, cfg), None
        if cfg.remat:
            # "dots": save projection/FFN matmul outputs (small, expensive
            # to recompute); recompute batched-dot products — exactly the
            # (B,H,S,S) attention matrices that blow up HBM. "full": save
            # nothing, recompute the whole layer in backward.
            policies = {
                "dots":
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                "full": None,
            }
            if cfg.remat_policy not in policies:
                raise HorovodTpuError(
                    f"remat_policy={cfg.remat_policy!r}: choose from "
                    f"{sorted(policies)} (remat=False turns remat off)")
            body = jax.checkpoint(body, prevent_cse=False,
                                  policy=policies[cfg.remat_policy])
        out, _ = lax.scan(body, act, stage_params)
        return out

    M = cfg.microbatches
    if lax.axis_size("pp") > 1 and M <= 1:
        raise HorovodTpuError(
            "pp > 1 requires microbatches > 1 (stages exchange activations "
            "only through the pipeline schedule)")
    if M > 1:
        if B % M:
            raise HorovodTpuError(f"local batch {B} not divisible by "
                                  f"microbatches {M}")
        xm = x.reshape(M, B // M, S, D)
        ym = pp_mod.pipeline_apply(stage_fn, params["layers"], xm, "pp")
        x = ym.reshape(B, S, D)
    else:
        x = stage_fn(params["layers"], x)

    x = _ln(x, params["lnf_scale"], params["lnf_bias"])
    return jnp.einsum("bsd,dv->bsv", x, params["unembed"])


def _local_loss(params, tokens, targets, cfg: TransformerConfig):
    """Per-shard loss contribution (see NOTE below on psum placement)."""
    pp_size = lax.axis_size("pp")
    B, S = tokens.shape
    logits = _forward_local(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum(nll)
    # Only the last pipeline stage holds real outputs (pipeline_apply emits
    # zeros elsewhere); mask others out of the loss.
    is_last = (lax.axis_index("pp") == pp_size - 1).astype(jnp.float32)
    local_sum = local_sum * is_last
    n_tokens = (B * S * lax.axis_size("dp") * lax.axis_size("ep")
                * lax.axis_size("sp"))
    # NOTE: this is the LOCAL contribution to the global mean loss — it is
    # deliberately NOT psum'd here. The transpose of psum multiplies
    # cotangents by the axis size, so differentiating a psum'd loss per-rank
    # then psum-ing gradients again would overcount by ∏ axis sizes.
    # build_loss_and_grads psums gradients (and the reported loss value)
    # explicitly instead.
    #
    # The tp axis computes this loss redundantly on every member. Reverse AD
    # differentiates the implicit sum of per-rank losses, which (a) leaves
    # gradients of REPLICATED leaves exact — each rank only differentiates
    # its own copy's paths, and the tp-peer contributions arriving through
    # the psum transposes complete the chain rule — but (b) overcounts
    # gradients of tp-SHARDED leaves by tp, since a shard feeds every
    # redundant loss copy. build_loss_and_grads rescales the sharded leaves.
    return local_sum / n_tokens


def psum_axes(x, axes):
    for a in axes:
        x = lax.psum(x, a)
    return x


def build_loss_and_grads(cfg: TransformerConfig, mesh: Mesh):
    """shard_map'd (params, tokens, targets) -> (loss, grads) with gradient
    psums compiled in. The multi-axis generalisation of
    optim/optimizer.py:reduce_gradients_in_jit."""
    specs = param_specs(cfg)
    raxes = grad_reduce_axes(cfg)
    bspec = P(("dp", "ep"), "sp")

    def fn(params, tokens, targets):
        local_mean, grads = jax.value_and_grad(
            lambda p: _local_loss(p, tokens, targets, cfg))(params)
        tp_size = lax.axis_size("tp")
        # See grad_reduce_axes: /tp everywhere (redundant loss copies), psum
        # per-leaf axes (includes 'tp' for replicated-over-tp leaves).
        grads = jax.tree_util.tree_map(
            lambda g, ax: psum_axes(g / tp_size, ax), grads, raxes)
        loss = psum_axes(local_mean, ("dp", "ep", "sp", "pp"))
        return loss, grads

    return jax.shard_map(fn, mesh=mesh, in_specs=(specs, bspec, bspec),
                         out_specs=(P(), specs), check_vma=False)


def build_forward(cfg: TransformerConfig, mesh: Mesh):
    """Jittable (params, tokens) -> logits over the mesh (inference path)."""
    specs = param_specs(cfg)
    bspec = P(("dp", "ep"), "sp")

    def fn(params, tokens):
        logits = _forward_local(params, tokens, cfg)
        # With pp > 1 only the last stage holds real logits (zeros
        # elsewhere); psum over pp collapses them to the real values.
        return lax.psum(logits, "pp")

    return jax.shard_map(fn, mesh=mesh, in_specs=(specs, bspec),
                         out_specs=P(("dp", "ep"), "sp", None),
                         check_vma=False)


def build_train_step(cfg: TransformerConfig, mesh: Mesh,
                     optimizer: optax.GradientTransformation):
    """Full jitted train step over the mesh. Forward/backward/gradient
    collectives run inside shard_map; the optax update runs under GSPMD,
    which propagates param shardings through the elementwise update."""
    lg = build_loss_and_grads(cfg, mesh)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        loss, grads = lg(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def shard_params(params, cfg: TransformerConfig, mesh: Mesh):
    """Place a global param pytree onto the mesh per param_specs."""
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def validate_cfg_for_mesh(cfg: TransformerConfig, mesh: Mesh) -> None:
    ax = mesh_axis_sizes(mesh)
    checks = [
        (cfg.n_layers % (ax["pp"],)[0] == 0, "n_layers % pp"),
        (cfg.n_heads % ax["tp"] == 0, "n_heads % tp"),
        (cfg.d_ff % ax["tp"] == 0, "d_ff % tp"),
        (cfg.num_experts % ax["ep"] == 0 if cfg.num_experts else True,
         "num_experts % ep"),
        # pp > 1 REQUIRES the microbatch pipeline: without it stages never
        # exchange activations and each stage silently trains only its own
        # layer slice on raw embeddings.
        (ax["pp"] == 1 or cfg.microbatches > 1,
         "pp > 1 requires microbatches > 1"),
    ]
    if cfg.attn == "ulysses":
        checks.append((cfg.n_heads // ax["tp"] % ax["sp"] == 0,
                       "heads/tp % sp for ulysses"))
    for ok, what in checks:
        if not ok:
            raise HorovodTpuError(f"config/mesh mismatch: {what}")
