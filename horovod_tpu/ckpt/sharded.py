"""Sharding-aware snapshot/assemble for checkpoint pytrees.

The save side of the GSPMD follow-on ("checkpoint/serve sharded
models", docs/parallelism.md): a leaf that is a sharded ``jax.Array``
is snapshotted SHARD-WISE from ``addressable_shards`` keeping only
``replica_id == 0`` — on a ``dp x tp`` mesh that is exactly "each
dp-replica-0 rank along the batch axis writes only its model shards":
the tp-distinct shards are written once each, the dp copies are not
written at all. The manifest records every shard's slice of the global
array plus the leaf's PartitionSpec and the mesh axis sizes at save
time, so restore can

* reassemble the FULL host array from the shard files (coverage
  verified — a missing/truncated shard is a typed
  CheckpointCorruptError, never a silent zero-block), and
* re-shard it onto a DIFFERENT mesh shape (tp=4 -> tp=2 resume): the
  assembled global array is ``jax.device_put`` under the new mesh's
  NamedSharding, so the new shard boundaries need not match the old.

Host-side trees (plain numpy, the pure-DP elastic path) take the same
code path with one full-coverage "shard" per leaf.

Device→host mechanics: ``snapshot_tree`` is the only phase that touches
device memory — it blocks until the tree's buffers are ready
(``jax.block_until_ready``) and copies each kept shard to host. It is
the bounded, on-critical-path half of the two-phase save
(ckpt/async_ckpt.py runs it under the perfscope ``checkpoint`` phase);
everything else in this module is host-only.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.common.exceptions import CheckpointCorruptError
from horovod_tpu.ckpt.manifest import LeafEntry


def _keypath_str(kp) -> str:
    import jax
    return jax.tree_util.keystr(kp)


def spec_to_json(spec) -> Optional[List[Any]]:
    """PartitionSpec -> JSON (per-dim axis-name list or None)."""
    if spec is None:
        return None
    out: List[Any] = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append([str(entry)])
    return out


def spec_from_json(spec_json: Optional[List[Any]]):
    """JSON -> PartitionSpec (None stays None)."""
    if spec_json is None:
        return None
    from jax.sharding import PartitionSpec as P
    entries = []
    for entry in spec_json:
        if entry is None:
            entries.append(None)
        elif len(entry) == 1:
            entries.append(entry[0])
        else:
            entries.append(tuple(entry))
    return P(*entries)


class LeafSnapshot:
    """One leaf's host copy: manifest entry (files unfilled) + the
    shard payloads to be written by the background persist phase."""

    __slots__ = ("entry", "shards")

    def __init__(self, entry: LeafEntry,
                 shards: List[Tuple[Tuple[int, ...], Tuple[int, ...],
                                    np.ndarray]]):
        self.entry = entry
        self.shards = shards  # [(start, stop, host array)]

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for _, _, a in self.shards)


def _norm_index(index, shape: Tuple[int, ...]
                ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """A shard's `.index` (tuple of slices into the global shape) ->
    (start, stop) int tuples."""
    start, stop = [], []
    for sl, dim in zip(index, shape):
        a, b, _ = sl.indices(dim)
        start.append(int(a))
        stop.append(int(b))
    return tuple(start), tuple(stop)


def snapshot_tree(tree: Any) -> Tuple[List[LeafSnapshot], int]:
    """Device→host snapshot of every array leaf, shard-aware.

    Returns (snapshots in flatten order, total host bytes). Blocks
    until the device buffers are ready — this is the only part of a
    save that sits on the training critical path.
    """
    import jax

    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = [l for _, l in leaves_with_path
              if isinstance(l, jax.Array)]
    if arrays:
        jax.block_until_ready(arrays)
    out: List[LeafSnapshot] = []
    total = 0
    for kp, leaf in leaves_with_path:
        path = _keypath_str(kp)
        spec_json = None
        mesh_axes = None
        if isinstance(leaf, jax.Array):
            shape = tuple(leaf.shape)
            dtype = np.dtype(leaf.dtype).name
            sharding = getattr(leaf, "sharding", None)
            from jax.sharding import NamedSharding
            if isinstance(sharding, NamedSharding):
                spec_json = spec_to_json(sharding.spec)
            shards = []
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:
                    continue
                start, stop = _norm_index(sh.index, shape)
                shards.append((start, stop, np.asarray(sh.data)))
            if not shards:
                # every addressable shard is a replica of one held by
                # another process: nothing to write from here
                pass
        else:
            arr = np.asarray(leaf)
            shape = tuple(arr.shape)
            dtype = arr.dtype.name
            shards = [(tuple(0 for _ in shape), shape, arr)]
        entry = LeafEntry(path=path, shape=shape, dtype=dtype,
                          spec=spec_json)
        snap = LeafSnapshot(entry, shards)
        total += snap.nbytes
        out.append(snap)
    return out, total


def write_snapshots(dirpath: str, snaps: Sequence[LeafSnapshot]) -> int:
    """Persist every shard payload as `.npy` files into `dirpath`,
    filling each entry's `files` list. Host-only (the background
    phase). Returns bytes written.

    Shard files are named by their START OFFSETS into the global
    array, not by a local enumeration index: in a multi-writer save
    every process persists into the SAME directory, and offset names
    are globally unique per distinct shard (replica_id==0 is held by
    exactly one process per shard), so concurrent writers can never
    clobber each other's shards — and the primary's merge can safely
    dedupe fragments by filename (same name ⇒ same shard)."""
    os.makedirs(dirpath, exist_ok=True)
    written = 0
    for i, snap in enumerate(snaps):
        snap.entry.files = []
        full = len(snap.shards) == 1 and \
            snap.shards[0][0] == tuple(0 for _ in snap.entry.shape) and \
            snap.shards[0][1] == snap.entry.shape
        for start, stop, arr in snap.shards:
            off = "" if full else \
                ".o" + "-".join(str(a) for a in start)
            name = f"leaf-{i:05d}{off}.npy"
            np.save(os.path.join(dirpath, name), arr,
                    allow_pickle=False)
            written += int(arr.nbytes)
            snap.entry.files.append({"file": name, "start": list(start),
                                     "stop": list(stop)})
    return written


def assemble_leaf(dirpath: str, entry: LeafEntry) -> np.ndarray:
    """Shard files -> full host array, coverage-verified."""
    dtype = np.dtype(entry.dtype)
    arr = np.empty(entry.shape, dtype=dtype)
    covered = 0
    for f in entry.files:
        p = os.path.join(dirpath, f["file"])
        try:
            part = np.load(p, allow_pickle=False)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"checkpoint leaf shard unreadable: {p}: "
                f"{type(e).__name__}: {e}") from e
        want = tuple(b - a for a, b in zip(f["start"], f["stop"]))
        if tuple(part.shape) != want:
            raise CheckpointCorruptError(
                f"checkpoint leaf shard {p} has shape {part.shape}, "
                f"manifest says {want}")
        sl = tuple(slice(a, b) for a, b in zip(f["start"], f["stop"]))
        arr[sl] = part
        covered += part.size
    if covered < arr.size:
        raise CheckpointCorruptError(
            f"checkpoint leaf {entry.path!r} incompletely covered: "
            f"{covered}/{arr.size} elements present in "
            f"{len(entry.files)} shard file(s) under {dirpath}")
    return arr


def _parse_dict_keypath(path: str) -> Optional[List[str]]:
    """``"['params']['emb']"`` -> ``["params", "emb"]``; None when the
    keypath contains non-dict components (then `like` is required)."""
    out: List[str] = []
    rest = path
    while rest:
        m = re.match(r"^\[(?:'([^']*)'|\"([^\"]*)\")\]", rest)
        if not m:
            return None
        out.append(m.group(1) if m.group(1) is not None else m.group(2))
        rest = rest[m.end():]
    return out


def restore_tree(dirpath: str, entries: Sequence[LeafEntry],
                 like: Optional[Any] = None) -> Any:
    """Manifest entries -> pytree of host arrays.

    With `like`: leaves are matched by keypath against `like`'s
    structure (a mismatch is a CheckpointCorruptError naming the missing
    path) and the result has `like`'s treedef, with numpy-scalar leaves
    in `like` coerced back to their scalar types. Without `like`: the
    tree is rebuilt as nested dicts from the recorded keypaths
    (dict-only trees; anything else needs `like`).
    """
    import jax

    by_path: Dict[str, LeafEntry] = {e.path: e for e in entries}
    if like is not None:
        leaves_with_path, treedef = \
            jax.tree_util.tree_flatten_with_path(like)
        out_leaves = []
        for kp, l in leaves_with_path:
            path = _keypath_str(kp)
            e = by_path.get(path)
            if e is None:
                raise CheckpointCorruptError(
                    f"checkpoint at {dirpath} has no leaf {path!r} "
                    f"(has: {sorted(by_path)[:8]}...)")
            arr = assemble_leaf(dirpath, e)
            if isinstance(l, np.generic):
                out_leaves.append(type(l)(arr[()]))
            else:
                out_leaves.append(arr)
        return jax.tree_util.tree_unflatten(treedef, out_leaves)
    root: Dict[str, Any] = {}
    for e in entries:
        keys = _parse_dict_keypath(e.path)
        if keys is None:
            raise CheckpointCorruptError(
                f"checkpoint leaf {e.path!r} is not dict-addressed; "
                f"restore it with like=<matching pytree>")
        node = root
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = assemble_leaf(dirpath, e)
    return root


def reshard(tree: Any, mesh, specs: Any) -> Any:
    """Host tree -> device tree under `mesh` with per-leaf
    PartitionSpecs (the mesh-shape-changing restore: the assembled
    global arrays are placed under the NEW mesh's shardings, which need
    not match the shard boundaries the checkpoint was written with)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, specs)
