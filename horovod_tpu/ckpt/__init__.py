"""horovod_tpu/ckpt — async checkpointing + exactly-once elastic
step-resume (docs/checkpointing.md).

The preemption-proofing subsystem: a two-phase ``AsyncCheckpointer``
(device snapshot on the step boundary, persist + atomic commit on a
background writer — CheckFreq, FAST '21), sharding-aware save/restore
(replica-0 shard files + manifest PartitionSpecs, re-shardable onto a
different mesh shape — the GSPMD follow-on), a crash-consistent
manifest/commit-marker protocol with quarantine fallback, and the
restore signal that keeps peers' stall watchdogs from expiring during
a long restore. ``elastic.TrainLoopState`` ties it into the elastic
retry loop so resumed rounds continue from the last committed step
instead of restarting the epoch.

    from horovod_tpu import ckpt

    saver = ckpt.AsyncCheckpointer("/ckpts/run1")
    saver.save(step, {"params": params, "opt_state": opt_state},
               objects={"step": step, "cursor": cursor})
    ...
    got = saver.restore_latest(like={"params": params,
                                     "opt_state": opt_state})
"""

from horovod_tpu.common.exceptions import CheckpointCorruptError  # noqa: F401
from horovod_tpu.ckpt.async_ckpt import (  # noqa: F401
    AsyncCheckpointer, Restored,
)
from horovod_tpu.ckpt.manifest import (  # noqa: F401
    Manifest, LeafEntry, committed, latest_committed,
    write_done_marker, has_done_marker, quarantine, sweep_stale,
)
from horovod_tpu.ckpt.resume import (  # noqa: F401
    latest_pointer, load_params, peer_restore_active, restore_latest,
    signal_restore,
)
