"""Restore side: newest-committed walk with quarantine fallback, and
the cross-rank restore signal that re-arms stall deadlines.

``restore_latest`` walks committed generations newest-first. A
generation that fails verification (missing marker dir, unreadable
manifest, absent/truncated shard files) is QUARANTINED — moved under
``<root>/quarantine/`` with the reason, counted in
``horovod_ckpt_quarantined_total``, recorded as a flight ``ckpt``
event — and the walk falls back to the next older generation. Restore
therefore degrades in freshness, never in correctness.

The restore signal (``signal_restore``): a rank reading a checkpoint
from disk can take arbitrarily long (cold object store, big model),
and its PEERS are already parked in the first collective of the round
— whose StallWatchdog budget (HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)
would otherwise be eaten by the restore and trip a spurious stall
shutdown. While restoring, the rank heartbeats a ``ckpt/restoring``
KV key; a peer's watchdog, on reaching its deadline, probes
``peer_restore_active()`` and — while the signal is fresh — re-arms
the deadline from *now* (i.e. from restore time, not round start),
bounded overall by HOROVOD_CKPT_RESTORE_GRACE_MAX. The elastic
launcher clears the key at every round publication so a dead restorer's
stale signal can never leak grace into the next round
(elastic/driver.py RoundPublisher).
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
from typing import Any, Dict, Optional

from horovod_tpu.common.exceptions import CheckpointCorruptError
from horovod_tpu.ckpt import manifest as mf
from horovod_tpu.ckpt import sharded
from horovod_tpu.ckpt.async_ckpt import ident_fields, kv_from_env

HOROVOD_CKPT_RESTORE_HEARTBEAT = "HOROVOD_CKPT_RESTORE_HEARTBEAT"
HOROVOD_CKPT_RESTORE_GRACE_MAX = "HOROVOD_CKPT_RESTORE_GRACE_MAX"

KV_SCOPE = "ckpt"
KV_RESTORING_KEY = "restoring"
DEFAULT_HEARTBEAT = 1.0
#: Floor of the staleness window: a restoring signal older than
#: ``stale_seconds()`` is ignored (dead restorer, or wall-clock skew
#: larger than the job should tolerate). The window SCALES with the
#: configured heartbeat (3x, this floor) — a tuned-down heartbeat
#: cadence must not silently disable the grace it feeds.
STALE_SECONDS = 10.0


def heartbeat_seconds() -> float:
    return max(0.1, _env_float(HOROVOD_CKPT_RESTORE_HEARTBEAT,
                               DEFAULT_HEARTBEAT))


def stale_seconds() -> float:
    return max(STALE_SECONDS, 3.0 * heartbeat_seconds())

_local_restoring = threading.Event()


def _env_float(name: str, default: float) -> float:
    from horovod_tpu.common.config import _env_float as shared
    return shared(name, default)


def grace_max_seconds() -> float:
    return _env_float(HOROVOD_CKPT_RESTORE_GRACE_MAX, 600.0)


def latest_pointer(kv: Optional[Any] = None) -> Optional[Dict[str, Any]]:
    """The writer-published ``ckpt/latest`` pointer
    ({step, generation, root, time}), or None."""
    kv = kv or kv_from_env()
    if kv is None:
        return None
    from horovod_tpu.ckpt.async_ckpt import KV_LATEST_KEY
    try:
        data = kv.get(KV_SCOPE, KV_LATEST_KEY, timeout=0.0)
    except Exception:
        return None
    if not data:
        return None
    try:
        body = json.loads(data.decode())
    except ValueError:
        return None
    return body if isinstance(body, dict) else None


# --------------------------------------------------------------- restore
def restore_latest(root: str, like: Optional[Any] = None,
                   mesh: Optional[Any] = None,
                   specs: Optional[Any] = None,
                   kv: Optional[Any] = None):
    """Newest committed checkpoint under `root`, with quarantine
    fallback. Returns a ckpt.async_ckpt.Restored or None. The whole
    disk read runs under the restore signal so peers' stall deadlines
    re-arm instead of expiring."""
    from horovod_tpu.ckpt.async_ckpt import Restored, _flight, _ident, _mx

    swept = mf.sweep_stale(root)
    for step in swept:
        _mx()["quarantined"].inc()
        _flight(f"quarantine step={step} reason=stale-uncommitted "
                f"{_ident()}")
    t0 = time.perf_counter()
    with signal_restore(kv=kv):
        for gen, step in reversed(mf.committed(root)):
            dirpath = os.path.join(root, mf.dirname_for(step))
            try:
                man = mf.read_manifest(dirpath)
                tree = sharded.restore_tree(dirpath, man.leaves,
                                            like=like)
                objects: Dict[str, Any] = {}
                if man.has_objects:
                    with open(os.path.join(dirpath, mf.OBJECTS_NAME),
                              "rb") as f:
                        objects = pickle.load(f)
            except (CheckpointCorruptError, OSError,
                    pickle.UnpicklingError, EOFError) as e:
                mf.quarantine(root, step, f"restore failed: {e}")
                _mx()["quarantined"].inc()
                _flight(f"quarantine step={step} gen={gen} "
                        f"reason={type(e).__name__} {_ident()}")
                continue
            if mesh is not None and specs is not None:
                tree = sharded.reshard(tree, mesh, specs)
            dt = time.perf_counter() - t0
            _mx()["restores"].inc()
            _mx()["restore_s"].set(dt)
            _flight(f"restore step={step} gen={gen} source=checkpoint "
                    f"seconds={dt:.3f} {_ident()}")
            ptr = latest_pointer(kv)
            if ptr and int(ptr.get("generation", -1)) > gen:
                # restored an older generation than the job-wide
                # pointer says exists: surfaced for the doctor's
                # [ckpt] stale-restore line
                _flight(f"restore-stale step={step} gen={gen} "
                        f"latest={int(ptr['generation'])} {_ident()}")
            return Restored(step=step, generation=gen, tree=tree,
                            objects=objects)
    return None


def load_params(root: str, key: str = "params",
                like: Optional[Any] = None) -> Any:
    """Params-only restore of the newest committed manifest checkpoint
    (serve/engine.from_checkpoint's ride onto the new restore): the
    optimizer subtree's leaves are never read from disk at all.

    Both payload layouts the repo writes are accepted: a bare
    ``{key: ...}`` tree (direct AsyncCheckpointer use) and the
    TrainLoopState wrapper ``{"trees": {key: ...}}`` (elastic/state.py
    _payload) — so a replica can serve straight from a live training
    job's checkpoint root."""
    latest = mf.latest_committed(root)
    if latest is None:
        raise CheckpointCorruptError(
            f"no committed checkpoint under {root} (no "
            f"ckpt-*.done marker with a surviving directory)")
    gen, step = latest
    dirpath = os.path.join(root, mf.dirname_for(step))
    man = mf.read_manifest(dirpath)
    entries = None
    keypath = (key,)
    for prefix, kp in ((f"['{key}']", (key,)),
                       (f"['trees']['{key}']", ("trees", key))):
        found = [e for e in man.leaves if e.path.startswith(prefix)]
        if found:
            entries, keypath = found, kp
            break
    if not entries:
        tops = sorted({e.path.split("]")[0] + "]" for e in man.leaves})
        raise KeyError(
            f"checkpoint generation {gen} at {dirpath} has no {key!r} "
            f"subtree (top-level keys: {tops}); pass key=... for "
            f"checkpoints saved under a different name")
    if like is not None:
        wrapped = like
        for k in reversed(keypath):
            wrapped = {k: wrapped}
        out = sharded.restore_tree(dirpath, entries, like=wrapped)
    else:
        out = sharded.restore_tree(dirpath, entries)
    for k in keypath:
        out = out[k]
    return out


# -------------------------------------------------------- restore signal
class _RestoreSignal:
    """Heartbeats ``ckpt/restoring`` while a disk restore runs."""

    def __init__(self, kv: Optional[Any]) -> None:
        self._kv = kv
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.heartbeat = heartbeat_seconds()

    def _beat(self) -> None:
        body = dict(ident_fields())
        while not self._stop.is_set():
            body["ts"] = time.time()
            try:
                self._kv.put(KV_SCOPE, KV_RESTORING_KEY,
                             json.dumps(body).encode())
            except Exception:
                pass
            self._stop.wait(self.heartbeat)

    def __enter__(self):
        _local_restoring.set()
        if self._kv is None:
            self._kv = kv_from_env()
        if self._kv is not None:
            self._thread = threading.Thread(
                target=self._beat, name="hvd-ckpt-restore-signal",
                daemon=True)
            self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._kv is not None:
            try:
                body = dict(ident_fields())
                body["ts"] = 0.0  # done: explicitly stale
                self._kv.put(KV_SCOPE, KV_RESTORING_KEY,
                             json.dumps(body).encode())
            except Exception:
                pass
        _local_restoring.clear()
        return False


def signal_restore(kv: Optional[Any] = None) -> _RestoreSignal:
    return _RestoreSignal(kv)


def peer_restore_active(kv: Optional[Any] = None) -> bool:
    """True while some rank's restore signal is FRESH (heartbeat within
    ``stale_seconds()``). The StallWatchdog's grace probe
    (ops/collectives.py): while true, a deadline-hit wait re-arms
    instead of raising. Local restores (same process, another thread)
    count too, without a KV round-trip."""
    if _local_restoring.is_set():
        return True
    kv = kv or kv_from_env()
    if kv is None:
        return False
    try:
        data = kv.get(KV_SCOPE, KV_RESTORING_KEY, timeout=0.0)
    except Exception:
        return False
    if not data:
        return False
    try:
        body = json.loads(data.decode())
        ts = float(body.get("ts", 0.0))
    except (ValueError, TypeError, AttributeError):
        return False
    return 0.0 < ts and (time.time() - ts) < stale_seconds()
